"""Fixed-shape batch assembly for jitted TPU programs.

Every batch has identical shapes (XLA compiles once): the final partial batch
of an epoch is padded with zeroed samples whose labels are all <pad>, so they
contribute nothing to the masked loss; a ``valid`` bool array marks real rows
for eval bookkeeping. COO edges are padded per-sample to cfg.max_edges
(pad entries scatter zero — a no-op on device).

The reference instead ships a dense 650^2 float adjacency per sample through
a torch DataLoader (Dataset.py:336-343) — the batching fix called out in
SURVEY.md §7 hard-part 3.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.dataset import ProcessedSplit, ARRAY_FIELDS

Batch = Dict[str, np.ndarray]


def sort_edge_rows(senders, receivers, values, kinds, graph_len: int):
    """Row-wise sort of padded COO fields by linear cell index -> the
    device scatter's index stream is globally sorted (rows ascend, cells
    ascend within a row); pads (0,0,value 0) land first and still add
    nothing. ALL per-edge fields must ride the same permutation — kinds
    included when the typed-edge extension ships them."""
    order = np.argsort(
        senders.astype(np.int32) * graph_len + receivers, axis=1,
        kind="stable")
    senders = np.take_along_axis(senders, order, axis=1)
    receivers = np.take_along_axis(receivers, order, axis=1)
    values = np.take_along_axis(values, order, axis=1)
    if kinds is not None:
        kinds = np.take_along_axis(kinds, order, axis=1)
    return senders, receivers, values, kinds


def make_batch(split: ProcessedSplit, indices: np.ndarray, cfg: FiraConfig,
               batch_size: Optional[int] = None) -> Batch:
    """Gather + pad a batch. ``indices`` may be shorter than batch_size."""
    bs = batch_size or len(indices)
    n_real = len(indices)
    if n_real > bs:
        raise ValueError(f"{n_real} indices exceed batch_size={bs}")
    batch: Batch = {}
    for f in ARRAY_FIELDS:
        src = split.arrays[f][indices]
        if n_real < bs:
            pad = np.zeros((bs - n_real,) + src.shape[1:], dtype=src.dtype)
            src = np.concatenate([src, pad])
        batch[f] = src

    # --- narrow wire dtypes: ids ship int16/int8 and consumers upcast on
    # device (the loss path casts labels to int32, scatters cast indices,
    # embeds take any integer dtype). H2D is a per-step cost on every rig
    # and THE cost on thin host links; ids are ~7% and edge arrays ~93% of
    # the batch bytes, so together with the compute-dtype edge values below
    # this takes ~9 MB/batch-170 to ~5. Preconditions are enforced loudly:
    # a config scaled past a narrow dtype's range must fail here, not wrap
    # silently on device.
    if cfg.output_vocab_size - 1 > np.iinfo(np.int16).max:
        raise ValueError(
            f"output_vocab_size={cfg.output_vocab_size} exceeds int16 wire "
            f"range (max id {np.iinfo(np.int16).max}); widen the id dtype")
    for f in ("diff", "msg", "msg_tar", "sub_token"):
        batch[f] = batch[f].astype(np.int16)
    # mark vocabulary is 0..3 today; guard like the int16 fields so a future
    # mark-vocabulary change fails loudly instead of wrapping on the wire
    if batch["diff_mark"].size and batch["diff_mark"].max() > np.iinfo(np.int8).max:
        raise ValueError(
            f"diff_mark max {batch['diff_mark'].max()} exceeds int8 wire "
            f"range (max {np.iinfo(np.int8).max}); widen the mark dtype")
    batch["diff_mark"] = batch["diff_mark"].astype(np.int8)
    if cfg.ast_change_vocab_size - 1 > np.iinfo(np.int16).max:
        raise ValueError(
            f"ast_change_vocab_size={cfg.ast_change_vocab_size} exceeds "
            f"int16 wire range; widen the id dtype")
    ast_dt = (np.int8 if cfg.ast_change_vocab_size - 1 <= np.iinfo(np.int8).max
              else np.int16)
    batch["ast_change"] = batch["ast_change"].astype(ast_dt)

    # int16 indices: graph_len caps at 650 << 32767, and edge arrays dominate
    # the per-step host->device transfer (the model upcasts on device).
    # Enforce the dtype's precondition: a config scaled past int16 range
    # must fail loudly here, not wrap around silently in the scatter.
    if cfg.graph_len - 1 > np.iinfo(np.int16).max:  # indices are 0..len-1
        raise ValueError(
            f"graph_len={cfg.graph_len} exceeds int16 edge-index range "
            f"(max index {np.iinfo(np.int16).max}); widen the edge dtype")
    senders = np.zeros((bs, cfg.max_edges), dtype=np.int16)
    receivers = np.zeros((bs, cfg.max_edges), dtype=np.int16)
    values = np.zeros((bs, cfg.max_edges), dtype=np.float32)
    # pad entries keep kind 0 — harmless, a pad edge's value is 0 so any
    # gain multiplies into nothing
    kinds = (np.zeros((bs, cfg.max_edges), dtype=np.int8)
             if cfg.typed_edges else None)
    offsets = split.arrays["edge_offsets"]
    for row, i in enumerate(indices):
        lo, hi = offsets[i], offsets[i + 1]
        n = hi - lo
        if n > cfg.max_edges:
            raise ValueError(f"sample {i}: {n} edges > max_edges={cfg.max_edges}")
        senders[row, :n] = split.arrays["edge_senders"][lo:hi]
        receivers[row, :n] = split.arrays["edge_receivers"][lo:hi]
        values[row, :n] = split.arrays["edge_values"][lo:hi]
        if kinds is not None:
            kinds[row, :n] = split.arrays["edge_kinds"][lo:hi]
    if cfg.sort_edges:
        senders, receivers, values, kinds = sort_edge_rows(
            senders, receivers, values, kinds, cfg.graph_len)

    batch["senders"] = senders
    batch["receivers"] = receivers
    if (cfg.compute_dtype == "bfloat16" and cfg.adjacency_impl == "dense"
            and not cfg.typed_edges):
        # Ship edge values in the compute dtype: the dense path scatters
        # them straight into a bf16 adjacency (dense_adjacency out_dtype),
        # and host-side f32->bf16 rounding is the same rounding the device
        # cast performs, so the adjacency is bit-identical while the values
        # array (the single largest wire field) halves. Confined to exactly
        # that path: the segment path multiplies exact f32 values inside
        # its f32 accumulator, and typed_edges scales values by learned
        # gains before the cast — both would see pre-rounded inputs and
        # drift from their f32-wire behavior. f32 compute keeps the f32
        # wire — the parity path is untouched.
        import ml_dtypes

        values = values.astype(ml_dtypes.bfloat16)
    batch["values"] = values
    if kinds is not None:
        # only shipped when the typed-edge extension is on — the flattened
        # default keeps the reference's exact wire format
        batch["edge_kinds"] = kinds

    valid = np.zeros(bs, dtype=bool)
    valid[:n_real] = True
    batch["valid"] = valid
    return batch


def epoch_batches(split: ProcessedSplit, cfg: FiraConfig, *,
                  batch_size: Optional[int] = None,
                  shuffle: bool = False,
                  seed: int = 0,
                  epoch: int = 0,
                  drop_remainder: bool = False) -> Iterator[Batch]:
    """One epoch of fixed-shape batches (shuffled like the reference's
    DataLoader(shuffle=True), run_model.py:387). Pass the epoch number so
    each epoch draws a fresh permutation (seed and epoch are folded together);
    a fixed (seed, epoch) pair is fully deterministic."""
    bs = batch_size or cfg.batch_size
    order = np.arange(len(split))
    if shuffle:
        np.random.RandomState((seed * 1_000_003 + epoch) % (2**31)).shuffle(order)
    for start in range(0, len(order), bs):
        chunk = order[start : start + bs]
        if drop_remainder and len(chunk) < bs:
            return
        yield make_batch(split, chunk, cfg, batch_size=bs)


def num_batches(n: int, batch_size: int, drop_remainder: bool = False) -> int:
    return n // batch_size if drop_remainder else (n + batch_size - 1) // batch_size


def prefetch_to_device(batches: Iterator[Batch], *, size: int = 2,
                       sharding=None) -> Iterator[tuple]:
    """Double-buffered host->device input pipeline.

    Keeps ``size`` batches in flight so the transfer of batch i+1 overlaps
    the compute of batch i (jax.device_put is asynchronous). Feeding numpy
    straight into a jitted step instead serializes each step's transfer
    (~8 ms/batch measured through the bench rig's host link at the flagship
    geometry, scripts/tpu_breakdown.py) with its compute (~107 ms); the
    slower the host link or the faster the step, the bigger the win. The
    reference's torch DataLoader has no device prefetch at all: it ships
    dense 650^2 adjacencies and blocks on .cuda() per batch
    (run_model.py:94-101).

    Yields ``(device_batch, n_valid)``; n_valid (the count of real rows,
    for throughput bookkeeping) is computed host-side BEFORE the transfer —
    reading it back from the device array would force a mid-epoch sync.

    ``sharding``: optional pytree of NamedShardings matching the batch (see
    parallel.mesh.batch_shardings) so multi-chip feeds land pre-sharded; a
    callable ``batch -> sharding-pytree-or-None`` handles streams that mix
    shapes (e.g. fused K-stacked groups followed by per-step tail batches).
    """
    import collections

    import jax

    def put(b: Batch):
        n_valid = int(b["valid"].sum())
        sh = sharding(b) if callable(sharding) else sharding
        dev = jax.device_put(b, sh) if sh is not None else jax.device_put(b)
        return dev, n_valid

    buf = collections.deque()
    it = iter(batches)
    try:
        while len(buf) < max(1, size):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        yield buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
