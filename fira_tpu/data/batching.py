"""Fixed-shape batch assembly for jitted TPU programs.

Every batch has a shape drawn from a SMALL FIXED FAMILY (XLA compiles once
per family member): by default the single full config geometry; under
``cfg.buckets`` (data/buckets.py, docs/BUCKETING.md) one of a declared set
of smaller padding geometries via ``make_batch(..., geom=...)``. The final
partial batch of an epoch is padded with zeroed samples whose labels are
all <pad>, so they contribute nothing to the masked loss; a ``valid`` bool
array marks real rows for eval bookkeeping. COO edges are padded per-sample
to cfg.max_edges (pad entries scatter zero — a no-op on device).

The reference instead ships a dense 650^2 float adjacency per sample through
a torch DataLoader (Dataset.py:336-343) — the batching fix called out in
SURVEY.md §7 hard-part 3.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.dataset import ProcessedSplit, ARRAY_FIELDS

Batch = Dict[str, np.ndarray]


def sort_edge_rows(senders, receivers, values, kinds, graph_len: int):
    """Row-wise sort of padded COO fields by linear cell index -> the
    device scatter's index stream is globally sorted (rows ascend, cells
    ascend within a row); pads (0,0,value 0) land first and still add
    nothing. ALL per-edge fields must ride the same permutation — kinds
    included when the typed-edge extension ships them."""
    order = np.argsort(
        senders.astype(np.int32) * graph_len + receivers, axis=1,
        kind="stable")
    senders = np.take_along_axis(senders, order, axis=1)
    receivers = np.take_along_axis(receivers, order, axis=1)
    values = np.take_along_axis(values, order, axis=1)
    if kinds is not None:
        kinds = np.take_along_axis(kinds, order, axis=1)
    return senders, receivers, values, kinds


def _gather_edges_loop(split: ProcessedSplit, indices: np.ndarray,
                       cfg: FiraConfig, bs: int, drop: int = 0):
    """Pre-refactor per-row edge gather — the GOLDEN REFERENCE the
    vectorized path is pinned bit-exact against (tests/
    test_batching_golden.py). Not called on any hot path.

    ``drop``: shorten every sample's ragged slice by this many TRAILING
    entries — under a bucketed geometry (data/buckets.py) the truncated
    pad nodes' self-loops sit exactly there (build_adjacency appends one
    self-loop per full-geometry node, ascending, after all family edges)."""
    senders = np.zeros((bs, cfg.max_edges), dtype=np.int16)
    receivers = np.zeros((bs, cfg.max_edges), dtype=np.int16)
    values = np.zeros((bs, cfg.max_edges), dtype=np.float32)
    # pad entries keep kind 0 — harmless, a pad edge's value is 0 so any
    # gain multiplies into nothing
    kinds = (np.zeros((bs, cfg.max_edges), dtype=np.int8)
             if cfg.typed_edges else None)
    offsets = split.arrays["edge_offsets"]
    for row, i in enumerate(indices):
        lo, hi = offsets[i], offsets[i + 1] - drop
        n = hi - lo
        if n < 0:
            raise ValueError(
                f"sample {i}: {offsets[i + 1] - offsets[i]} edges < "
                f"geometry drop {drop} — not a self-looped adjacency")
        if n > cfg.max_edges:
            raise ValueError(f"sample {i}: {n} edges > max_edges={cfg.max_edges}")
        senders[row, :n] = split.arrays["edge_senders"][lo:hi]
        receivers[row, :n] = split.arrays["edge_receivers"][lo:hi]
        values[row, :n] = split.arrays["edge_values"][lo:hi]
        if kinds is not None:
            kinds[row, :n] = split.arrays["edge_kinds"][lo:hi]
    return senders, receivers, values, kinds


# Mean-edges-per-row crossover between the two vectorized-gather regimes,
# measured by scripts/batch_assembly_bench.py on this host: a numpy fancy
# gather/scatter costs a few ns/ELEMENT plus ~10 bytes/element of
# temporary traffic, while a per-row contiguous slice copy costs a few
# us/ROW of interpreter overhead plus a near-free memcpy. Below the
# crossover (many rows, few edges — sparse-graph corpora, stacked-group
# assembly) the flat cumsum/np.repeat gather wins ~3-5x; above it (the
# flagship 650-node graphs at ~700+ edges/sample) per-row memcpy beats
# per-element fancy indexing and the temporaries' memory traffic, so the
# addressing stays vectorized but the copies stay slices. Conservative on
# purpose: a host with faster fancy indexing only leaves a little on the
# table, never regresses.
_VEC_EDGE_CROSSOVER = 64


def _gather_edges_vectorized(split: ProcessedSplit, indices: np.ndarray,
                             cfg: FiraConfig, bs: int, drop: int = 0):
    """Vectorized COO gather, bit-exact vs ``_gather_edges_loop``
    (identical destination arrays, identical source element order,
    identical dtype narrowing on assignment; pinned by the golden test).
    ``drop``: trailing pad-node self-loops to shed per sample — see the
    loop reference's docstring.

    Addressing (offsets, counts, the overflow check) is always vectorized.
    The copies pick a regime by mean edges per row (see
    ``_VEC_EDGE_CROSSOVER``): the flat cumsum/np.repeat gather — one
    address computation and four fancy-indexed copies replacing ~bs
    interpreter iterations — below it, per-row contiguous slice copies
    above it."""
    idx = np.asarray(indices, dtype=np.intp)
    offsets = split.arrays["edge_offsets"]
    lo = offsets[idx]
    counts = (offsets[idx + 1] - lo - drop).astype(np.intp)
    if counts.size and counts.min() < 0:
        row = int(np.argmax(counts < 0))
        raise ValueError(
            f"sample {idx[row]}: {counts[row] + drop} edges < geometry "
            f"drop {drop} — not a self-looped adjacency")
    if counts.size and counts.max() > cfg.max_edges:
        row = int(np.argmax(counts > cfg.max_edges))  # first offender, like the loop
        raise ValueError(
            f"sample {idx[row]}: {counts[row]} edges > max_edges={cfg.max_edges}")

    senders = np.zeros((bs, cfg.max_edges), dtype=np.int16)
    receivers = np.zeros((bs, cfg.max_edges), dtype=np.int16)
    values = np.zeros((bs, cfg.max_edges), dtype=np.float32)
    kinds = (np.zeros((bs, cfg.max_edges), dtype=np.int8)
             if cfg.typed_edges else None)
    if not counts.size:
        return senders, receivers, values, kinds

    arrays = split.arrays
    if counts.mean() > _VEC_EDGE_CROSSOVER:
        hi = lo + counts
        for row in range(len(idx)):  # copies only; addressing is above
            a, b = lo[row], hi[row]
            n = b - a
            senders[row, :n] = arrays["edge_senders"][a:b]
            receivers[row, :n] = arrays["edge_receivers"][a:b]
            values[row, :n] = arrays["edge_values"][a:b]
            if kinds is not None:
                kinds[row, :n] = arrays["edge_kinds"][a:b]
        return senders, receivers, values, kinds

    # flat regime: every real edge's flat source slot and flat destination
    # slot — col counts 0..n_row-1 within each row, src = lo + col,
    # dst = row*max_edges + col (strictly ascending, the cache-friendly
    # scatter order). 1-D raveled indexing with pre-cast right-hand sides:
    # 2-D advanced indexing and in-assignment dtype casts both fall off
    # numpy's fast path (each measured ~4x slower here).
    total = int(counts.sum())
    rows = np.repeat(np.arange(len(idx), dtype=np.intp), counts)
    cols = np.arange(total, dtype=np.intp) - np.repeat(
        np.cumsum(counts) - counts, counts)
    src = np.repeat(lo, counts) + cols
    dst = rows * cfg.max_edges + cols
    senders.ravel()[dst] = arrays["edge_senders"][src].astype(np.int16)
    receivers.ravel()[dst] = arrays["edge_receivers"][src].astype(np.int16)
    values.ravel()[dst] = arrays["edge_values"][src]
    if kinds is not None:
        kinds.ravel()[dst] = arrays["edge_kinds"][src].astype(np.int8)
    return senders, receivers, values, kinds


def make_batch(split: ProcessedSplit, indices: np.ndarray, cfg: FiraConfig,
               batch_size: Optional[int] = None, *,
               edge_gather: str = "vectorized",
               geom=None) -> Batch:
    """Gather + pad a batch. ``indices`` may be shorter than batch_size.

    ``edge_gather``: "vectorized" (default, the flat cumsum/np.repeat COO
    gather) or "loop" (the pre-refactor per-row reference — kept only so
    the golden test can pin bit-exactness through the full batch path).

    ``geom``: an optional ``data.buckets.BucketGeom`` — pad to THAT
    geometry instead of the config's full one: the ast_change node tail,
    msg/msg_tar positions, and the COO pad shrink to the bucket's
    (ast_len, max_edges, tar_len); the truncated pad nodes' self-loop
    edges (the trailing ``graph_len - bucket_graph_len`` entries of each
    ragged slice) are dropped with them. Exact for every real value —
    pinned by tests/test_buckets.py. A sample that does not FIT the
    geometry (nonzero data in a truncated region, an edge into a truncated
    node) raises loudly: the packer owns admissibility, this function
    enforces it."""
    drop = 0
    if geom is not None:
        from fira_tpu.data.buckets import BucketGeom, _validated

        g = _validated(cfg, BucketGeom(*geom))
        # the truncated pad nodes' self-loops are the ragged tail to shed
        drop = cfg.ast_change_len - g.ast_len
        cfg = cfg.replace(ast_change_len=g.ast_len, max_edges=g.max_edges,
                          tar_len=g.tar_len)
    bs = batch_size or len(indices)
    n_real = len(indices)
    if n_real > bs:
        raise ValueError(f"{n_real} indices exceed batch_size={bs}")
    # per-field bucketed width (None = full width stays)
    widths = ({"ast_change": cfg.ast_change_len, "msg": cfg.tar_len,
               "msg_tar": cfg.tar_len} if geom is not None else {})
    batch: Batch = {}
    for f in ARRAY_FIELDS:
        src = split.arrays[f][indices]
        w = widths.get(f)
        if w is not None and w < src.shape[1]:
            tail = src[:, w:]
            if tail.any():
                row = int(np.argmax(tail.any(axis=1)))
                raise ValueError(
                    f"sample {indices[row]}: nonzero {f!r} data beyond "
                    f"bucket width {w} — sample does not fit the geometry")
            src = src[:, :w]
        if n_real < bs:
            pad = np.zeros((bs - n_real,) + src.shape[1:], dtype=src.dtype)
            src = np.concatenate([src, pad])
        batch[f] = src

    # --- narrow wire dtypes: ids ship int16/int8 and consumers upcast on
    # device (the loss path casts labels to int32, scatters cast indices,
    # embeds take any integer dtype). H2D is a per-step cost on every rig
    # and THE cost on thin host links; ids are ~7% and edge arrays ~93% of
    # the batch bytes, so together with the compute-dtype edge values below
    # this takes ~9 MB/batch-170 to ~5. Preconditions are enforced loudly:
    # a config scaled past a narrow dtype's range must fail here, not wrap
    # silently on device.
    if cfg.output_vocab_size - 1 > np.iinfo(np.int16).max:
        raise ValueError(
            f"output_vocab_size={cfg.output_vocab_size} exceeds int16 wire "
            f"range (max id {np.iinfo(np.int16).max}); widen the id dtype")
    for f in ("diff", "msg", "msg_tar", "sub_token"):
        batch[f] = batch[f].astype(np.int16)
    # mark vocabulary is 0..3 today; guard like the int16 fields so a future
    # mark-vocabulary change fails loudly instead of wrapping on the wire
    if batch["diff_mark"].size and batch["diff_mark"].max() > np.iinfo(np.int8).max:
        raise ValueError(
            f"diff_mark max {batch['diff_mark'].max()} exceeds int8 wire "
            f"range (max {np.iinfo(np.int8).max}); widen the mark dtype")
    batch["diff_mark"] = batch["diff_mark"].astype(np.int8)
    if cfg.ast_change_vocab_size - 1 > np.iinfo(np.int16).max:
        raise ValueError(
            f"ast_change_vocab_size={cfg.ast_change_vocab_size} exceeds "
            f"int16 wire range; widen the id dtype")
    ast_dt = (np.int8 if cfg.ast_change_vocab_size - 1 <= np.iinfo(np.int8).max
              else np.int16)
    batch["ast_change"] = batch["ast_change"].astype(ast_dt)

    # int16 indices: graph_len caps at 650 << 32767, and edge arrays dominate
    # the per-step host->device transfer (the model upcasts on device).
    # Enforce the dtype's precondition: a config scaled past int16 range
    # must fail loudly here, not wrap around silently in the scatter.
    if cfg.graph_len - 1 > np.iinfo(np.int16).max:  # indices are 0..len-1
        raise ValueError(
            f"graph_len={cfg.graph_len} exceeds int16 edge-index range "
            f"(max index {np.iinfo(np.int16).max}); widen the edge dtype")
    gather = {"vectorized": _gather_edges_vectorized,
              "loop": _gather_edges_loop}[edge_gather]
    senders, receivers, values, kinds = gather(split, indices, cfg, bs, drop)
    if geom is not None and len(indices):
        # admissibility backstop: an edge into a truncated node would
        # scatter out of the bucket's adjacency — silently wrong on TPU
        hi = max(int(senders.max()), int(receivers.max()))
        if hi >= cfg.graph_len:
            raise ValueError(
                f"edge references node {hi} >= bucketed graph_len "
                f"{cfg.graph_len} — sample does not fit the geometry")
    if cfg.sort_edges:
        senders, receivers, values, kinds = sort_edge_rows(
            senders, receivers, values, kinds, cfg.graph_len)

    batch["senders"] = senders
    batch["receivers"] = receivers
    if (cfg.compute_dtype == "bfloat16" and cfg.adjacency_impl == "dense"
            and not cfg.typed_edges):
        # Ship edge values in the compute dtype: the dense path scatters
        # them straight into a bf16 adjacency (dense_adjacency out_dtype),
        # and host-side f32->bf16 rounding is the same rounding the device
        # cast performs, so the adjacency is bit-identical while the values
        # array (the single largest wire field) halves. Confined to exactly
        # that path: the segment path multiplies exact f32 values inside
        # its f32 accumulator, and typed_edges scales values by learned
        # gains before the cast — both would see pre-rounded inputs and
        # drift from their f32-wire behavior. f32 compute keeps the f32
        # wire — the parity path is untouched.
        import ml_dtypes

        values = values.astype(ml_dtypes.bfloat16)
    batch["values"] = values
    if kinds is not None:
        # only shipped when the typed-edge extension is on — the flattened
        # default keeps the reference's exact wire format
        batch["edge_kinds"] = kinds

    valid = np.zeros(bs, dtype=bool)
    valid[:n_real] = True
    batch["valid"] = valid
    return batch


def epoch_order(n: int, *, shuffle: bool = False, seed: int = 0,
                epoch: int = 0) -> np.ndarray:
    """The deterministic sample PERMUTATION of an epoch — the single
    source every packing strategy chunks from: ``epoch_index_chunks``
    slices it into fixed-size chunks, the bucket packer
    (data/buckets.packed_plan) walks the SAME permutation grouping by
    bucket. Seed and epoch fold together so each epoch draws a fresh but
    fully reproducible permutation (the reference's DataLoader
    shuffle=True, run_model.py:387)."""
    order = np.arange(n)
    if shuffle:
        np.random.RandomState((seed * 1_000_003 + epoch) % (2**31)).shuffle(order)
    return order


def epoch_index_chunks(n: int, cfg: FiraConfig, *,
                       batch_size: Optional[int] = None,
                       shuffle: bool = False,
                       seed: int = 0,
                       epoch: int = 0,
                       drop_remainder: bool = False) -> List[np.ndarray]:
    """The deterministic batch ORDER of an epoch, as a list of index chunks
    (see ``epoch_order`` for the permutation contract). This is the single
    source of truth for batch order: ``epoch_batches`` assembles these
    chunks inline, the async Feeder (data/feeder.py) assembles the SAME
    chunks on worker threads — byte-identical sequences either way."""
    bs = batch_size or cfg.batch_size
    order = epoch_order(n, shuffle=shuffle, seed=seed, epoch=epoch)
    chunks = [order[start : start + bs] for start in range(0, n, bs)]
    if drop_remainder and chunks and len(chunks[-1]) < bs:
        chunks.pop()
    return chunks


def epoch_batches(split: ProcessedSplit, cfg: FiraConfig, *,
                  batch_size: Optional[int] = None,
                  shuffle: bool = False,
                  seed: int = 0,
                  epoch: int = 0,
                  drop_remainder: bool = False) -> Iterator[Batch]:
    """One epoch of fixed-shape batches, assembled inline on the calling
    thread (see ``epoch_index_chunks`` for the order contract)."""
    bs = batch_size or cfg.batch_size
    for chunk in epoch_index_chunks(len(split), cfg, batch_size=bs,
                                    shuffle=shuffle, seed=seed, epoch=epoch,
                                    drop_remainder=drop_remainder):
        yield make_batch(split, chunk, cfg, batch_size=bs)


def num_batches(n: int, batch_size: int, drop_remainder: bool = False) -> int:
    return n // batch_size if drop_remainder else (n + batch_size - 1) // batch_size


def prefetch_to_device(batches: Iterator[Batch], *, size: int = 2,
                       sharding=None) -> Iterator[tuple]:
    """Double-buffered host->device pipeline over an ALREADY-ASSEMBLED
    batch stream — a compatibility shim over data/feeder.Feeder, which
    subsumed it (the feeder additionally moves batch ASSEMBLY off the
    consumer thread; train/dev/decode/bench all use it directly now, see
    docs/PIPELINE.md).

    Keeps up to ``size`` batches in flight so the transfer of batch i+1
    overlaps the compute of batch i (jax.device_put is asynchronous); the
    source iterator itself is drained on the feeder's dispatcher thread.
    Yields ``(device_batch, n_valid)``; n_valid (the count of real rows,
    for throughput bookkeeping) is computed host-side BEFORE the transfer —
    reading it back from the device array would force a mid-epoch sync.

    ``sharding``: optional pytree of NamedShardings matching the batch (see
    parallel.mesh.batch_shardings) so multi-chip feeds land pre-sharded; a
    callable ``batch -> sharding-pytree-or-None`` handles streams that mix
    shapes (e.g. fused K-stacked groups followed by per-step tail batches).
    """
    from fira_tpu.data.feeder import Feeder

    with Feeder.from_batches(batches, depth=max(1, size),
                             sharding=sharding) as feeder:
        for item in feeder:
            yield item.device, item.n_valid
