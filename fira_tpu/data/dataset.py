"""Corpus -> fixed-shape training examples (the reference's Dataset.py:96-334
pipeline, rebuilt around COO edge lists and ragged caching).

Per-commit processing order follows the reference exactly:
variable-placeholder substitution -> case normalization -> lemmatization (msg
only) -> id conversion -> <start>/<eos> wrapping -> padding -> sub-token dedup
-> copy labels -> adjacency assembly. Examples cache to a single compressed
.npz per split with ragged edge storage (concatenated COO + offsets) instead
of 90k scipy matrices pickled (Dataset.py:294,332) — one sequential read, a
fraction of the pickle's size.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
from typing import Dict, List, Optional

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data import graph_build
from fira_tpu.data.schema import (
    AST_CHANGE_VOCAB_FILE,
    Corpus,
    CommitRecord,
    SPLIT_INDEX_FILE,
    WORD_VOCAB_FILE,
)
from fira_tpu.data.vocab import (
    EOS_ID,
    LEMMATIZATION,
    PAD_ID,
    START_ID,
    Vocab,
    normalize_token,
    pad_sequence,
)

ARRAY_FIELDS = ("diff", "msg", "msg_tar", "diff_mark", "ast_change", "sub_token")


@dataclasses.dataclass
class Example:
    """One tensorized commit. Shapes are config-fixed except the COO edges."""

    diff: np.ndarray        # int32 [sou_len]
    msg: np.ndarray         # int32 [tar_len] (decoder input ids)
    msg_tar: np.ndarray     # int32 [tar_len] (labels incl. copy ids)
    diff_mark: np.ndarray   # int32 [sou_len] (0 pad, 1 del, 2 ctx, 3 add)
    ast_change: np.ndarray  # int32 [ast_change_len]
    sub_token: np.ndarray   # int32 [sub_token_len]
    senders: np.ndarray     # int32 [n_edges] (ragged)
    receivers: np.ndarray   # int32 [n_edges]
    values: np.ndarray      # float32 [n_edges]
    kinds: np.ndarray       # int8 [n_edges] (graph_build.EDGE_KIND_*)


def _substitute(tokens: List[str], var_map: Dict[str, str]) -> List[str]:
    """Dataset.py:125-129: placeholder substitution then case-normalize,
    applied to the substituted value."""
    out = []
    for tok in tokens:
        if tok in var_map:
            tok = var_map[tok]
        out.append(normalize_token(tok))
    return out


def process_record(record: CommitRecord, word_vocab: Vocab,
                   ast_change_vocab: Vocab, cfg: FiraConfig) -> Example:
    """Tensorize one commit (Dataset.py:111-303 semantics)."""
    raw_diff = _substitute(record.diff_tokens, record.var_map)
    raw_msg = _substitute(record.msg_tokens, record.var_map)
    raw_msg = [LEMMATIZATION.get(t, t) for t in raw_msg]  # Dataset.py:136-137

    diff_ids = word_vocab.convert_tokens_to_ids(raw_diff)
    diff = pad_sequence([START_ID] + diff_ids + [EOS_ID], cfg.sou_len)

    msg_ids = word_vocab.convert_tokens_to_ids(raw_msg)
    msg = pad_sequence([START_ID] + msg_ids + [EOS_ID], cfg.tar_len)

    mark = pad_sequence([2] + list(record.diff_marks) + [2], cfg.sou_len, pad_id=0)

    # ast + change share one node sequence (Dataset.py:168-171); the no_edit
    # ablation drops the change (edit-op) nodes.
    change_labels = list(record.change_labels) if cfg.use_edit else []
    ast_change_ids = ast_change_vocab.convert_tokens_to_ids(
        list(record.ast_labels) + change_labels
    )
    ast_change = pad_sequence(ast_change_ids, cfg.ast_change_len)

    sub_tokens, edge_sub_token = graph_build.dedup_sub_tokens(
        raw_diff, record.diff_atts
    )
    sub_token_ids = pad_sequence(
        word_vocab.convert_tokens_to_ids(sub_tokens), cfg.sub_token_len
    )

    labels = graph_build.copy_labels(
        msg_ids, raw_msg, raw_diff, sub_tokens,
        vocab_size=len(word_vocab), sou_len=cfg.sou_len,
        use_subtoken_copy=cfg.use_subtoken_copy,
        sub_token_len=cfg.sub_token_len,
    )
    msg_tar = pad_sequence([START_ID] + labels + [EOS_ID], cfg.tar_len)

    adj = graph_build.build_adjacency(
        sou_len=cfg.sou_len,
        sub_token_len=cfg.sub_token_len,
        ast_change_len=cfg.ast_change_len,
        raw_diff_len=len(raw_diff),
        n_ast=len(record.ast_labels),
        edge_change_code=record.edge_change_code,
        edge_change_ast=record.edge_change_ast,
        edge_ast_code=record.edge_ast_code,
        edge_ast=record.edge_ast,
        edge_sub_token=edge_sub_token,
        use_edit=cfg.use_edit,
    )

    as_i32 = lambda x: np.asarray(x, dtype=np.int32)
    return Example(
        diff=as_i32(diff), msg=as_i32(msg), msg_tar=as_i32(msg_tar),
        diff_mark=as_i32(mark), ast_change=as_i32(ast_change),
        sub_token=as_i32(sub_token_ids),
        senders=adj.senders, receivers=adj.receivers, values=adj.values,
        kinds=adj.kinds,
    )


class ProcessedSplit:
    """A split's examples as stacked arrays + ragged COO storage."""

    def __init__(self, arrays: Dict[str, np.ndarray]):
        self.arrays = arrays
        self.n = arrays["diff"].shape[0]

    def __len__(self) -> int:
        return self.n

    def edge_slice(self, i: int):
        lo, hi = self.arrays["edge_offsets"][i], self.arrays["edge_offsets"][i + 1]
        return (
            self.arrays["edge_senders"][lo:hi],
            self.arrays["edge_receivers"][lo:hi],
            self.arrays["edge_values"][lo:hi],
        )

    @classmethod
    def from_examples(cls, examples: List[Example]) -> "ProcessedSplit":
        arrays = {
            f: np.stack([getattr(e, f) for e in examples]) for f in ARRAY_FIELDS
        }
        offsets = np.zeros(len(examples) + 1, dtype=np.int64)
        for i, e in enumerate(examples):
            offsets[i + 1] = offsets[i] + e.senders.shape[0]
        arrays["edge_offsets"] = offsets
        arrays["edge_senders"] = np.concatenate([e.senders for e in examples])
        arrays["edge_receivers"] = np.concatenate([e.receivers for e in examples])
        arrays["edge_values"] = np.concatenate([e.values for e in examples])
        arrays["edge_kinds"] = np.concatenate([e.kinds for e in examples])
        return cls(arrays)

    def save(self, path: str) -> None:
        np.savez_compressed(path, **self.arrays)

    @classmethod
    def load(cls, path: str) -> "ProcessedSplit":
        with np.load(path) as z:
            return cls({k: z[k] for k in z.files})


class FiraDataset:
    """Corpus directory -> processed, cached, split dataset.

    Split indices honor an existing ``all_index`` file (the reference's frozen
    split, Dataset.py:305-313); otherwise a fresh shuffled split is drawn once
    and persisted, using the reference's 75000/8000/7661 proportions scaled to
    the corpus size.
    """

    SPLITS = ("train", "valid", "test")

    def __init__(self, data_dir: str, cfg: FiraConfig,
                 cache_dir: Optional[str] = None):
        self.data_dir = data_dir
        self.cache_dir = cache_dir or os.path.join(data_dir, "processed")
        self.word_vocab = Vocab.from_json(os.path.join(data_dir, WORD_VOCAB_FILE))
        ast_vocab_path = os.path.join(data_dir, AST_CHANGE_VOCAB_FILE)
        corpus = None
        if not os.path.exists(ast_vocab_path):
            corpus = Corpus.load(data_dir)
            Vocab.build_ast_change_vocab(corpus.streams["ast"]).to_json(ast_vocab_path)
        self.ast_change_vocab = Vocab.from_json(ast_vocab_path)
        self.cfg = cfg.replace(
            vocab_size=len(self.word_vocab),
            ast_change_vocab_size=len(self.ast_change_vocab),
        )

        self.split_indices = self._load_or_draw_split(corpus)
        self.splits: Dict[str, ProcessedSplit] = {}
        self._ensure_processed(corpus)

    # --- split bookkeeping ---

    def _load_or_draw_split(self, corpus: Optional[Corpus]) -> Dict[str, List[int]]:
        path = os.path.join(self.data_dir, SPLIT_INDEX_FILE)
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        corpus = corpus or Corpus.load(self.data_dir)
        n = len(corpus)
        # reference proportions 75000/8000/7661 of 90661 (Dataset.py:10-12)
        n_valid = max(1, round(n * 8000 / 90661))
        n_test = max(1, round(n * 7661 / 90661))
        n_train = n - n_valid - n_test
        index = list(range(n))
        random.Random(self.cfg.seed).shuffle(index)
        split = {
            "train": index[:n_train],
            "valid": index[n_train : n_train + n_valid],
            "test": index[n_train + n_valid :],
        }
        with open(path, "w") as f:
            json.dump(split, f)
        return split

    # --- processing / caching ---

    def _cache_path(self, split: str) -> str:
        tag = "full" if (self.cfg.use_edit and self.cfg.use_subtoken_copy) else (
            f"edit{int(self.cfg.use_edit)}_sub{int(self.cfg.use_subtoken_copy)}"
        )
        geom = f"{self.cfg.sou_len}x{self.cfg.tar_len}x{self.cfg.ast_change_len}x{self.cfg.sub_token_len}"
        # v2: edge_kinds added to the ragged edge storage (typed-edge opt-in)
        return os.path.join(self.cache_dir, f"{split}_{tag}_{geom}_v2.npz")

    def _ensure_processed(self, corpus: Optional[Corpus]) -> None:
        missing = [s for s in self.SPLITS if not os.path.exists(self._cache_path(s))]
        if missing:
            corpus = corpus or Corpus.load(self.data_dir)
            os.makedirs(self.cache_dir, exist_ok=True)
            for split in missing:
                examples = [
                    process_record(
                        corpus.record(i), self.word_vocab,
                        self.ast_change_vocab, self.cfg,
                    )
                    for i in self.split_indices[split]
                ]
                ProcessedSplit.from_examples(examples).save(self._cache_path(split))
        for split in self.SPLITS:
            self.splits[split] = ProcessedSplit.load(self._cache_path(split))
