"""Vocabularies and token normalization rules.

Rebuilds the reference's two-vocabulary scheme:
- word vocab: ids 0-3 are <pad>, <eos>, <start>, <unkm>, then corpus tokens
  (/root/reference/run_model.py:48-53, DataSet/word_vocab.json schema).
- ast/change vocab: ids 0-5 are <pad>, update, delete, add, move, match, then
  lower-cased AST type labels (Dataset.py:46-62).

Token normalization (Dataset.py:69-78,123-137): every token is lower-cased
unless it belongs to the case-preserved placeholder set; unknown tokens map to
<unkm>; commit messages additionally lemmatize added/fixed/removed (and -ing
forms) to their stems.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Sequence

PAD_TOKEN = "<pad>"
EOS_TOKEN = "<eos>"
START_TOKEN = "<start>"
UNK_TOKEN = "<unkm>"
WORD_SPECIALS = [PAD_TOKEN, EOS_TOKEN, START_TOKEN, UNK_TOKEN]

PAD_ID, EOS_ID, START_ID, UNK_ID = 0, 1, 2, 3

# Edit-operation labels occupy ids 1-5 of the ast/change vocab (Dataset.py:56).
CHANGE_LABELS = ["update", "delete", "add", "move", "match"]
AST_CHANGE_SPECIALS = [PAD_TOKEN] + CHANGE_LABELS

# Message lemmatization table (Dataset.py:15).
LEMMATIZATION = {
    "added": "add",
    "fixed": "fix",
    "removed": "remove",
    "adding": "add",
    "fixing": "fix",
    "removing": "remove",
}

# Case-preserved placeholder tokens (the reference's VOCAB_UPPER_CASE file,
# 163 entries). Three bare anonymization markers, numbered literal
# placeholders, and 33 corpus-derived label-like tokens that survived
# anonymization. Membership is all that matters (Dataset.py:72,128).
_LABEL_LIKE = [
    "withInt:", "TODO:", "Note:", "forString:", "initWithLong:",
    "ofItemAtPath:", "WALK:", "Zeros:", "withChar:", "SubjectDN:",
    "IssuerDN:", "nextParent:", "methodLoop:", "eachFont:", "READ:",
    "classLoop:", "handleKeyboard:", "initWithNSString:", "FIXME:",
    "mainLoop:", "Students:", "initWithInt:", "withNSString:",
    "Distribution:", "Normalized:", "Size:", "Uniform:", "VI:", "TBD:",
    "STARTWALK:", "DESTSTOPS:", "Fingerprint:", "checkSupertypes:",
]
CASE_PRESERVED_TOKENS = frozenset(
    ["NAMESPACE", "SINGLE", "COMMENT"]
    + [f"STRING{i}" for i in range(62)]
    + [f"NUMBER{i}" for i in range(52)]
    + [f"FLOAT{i}" for i in range(13)]
    + _LABEL_LIKE
)


def normalize_token(token: str) -> str:
    """Lower-case unless the token is a case-preserved placeholder."""
    return token if token in CASE_PRESERVED_TOKENS else token.lower()


class Vocab:
    """A frozen token->id mapping with the reference's conversion semantics."""

    def __init__(self, token_to_id: Dict[str, int]):
        self.token_to_id = dict(token_to_id)
        self.id_to_token = {i: t for t, i in self.token_to_id.items()}

    def __len__(self) -> int:
        return len(self.token_to_id)

    def __contains__(self, token: str) -> bool:
        return token in self.token_to_id

    def __getitem__(self, token: str) -> int:
        return self.token_to_id[token]

    def convert_tokens_to_ids(self, tokens: Iterable[str]) -> List[int]:
        """Dataset.py:69-78: case-normalize, then map with <unkm> fallback.

        The ast/change vocab has no <unkm> (the reference guarantees coverage
        by building it over the full corpus, Dataset.py:46-60) — an unknown
        there is a data bug and raises instead of silently mapping."""
        out = []
        for t in tokens:
            t = normalize_token(t)
            if t in self.token_to_id:
                out.append(self.token_to_id[t])
            elif UNK_TOKEN in self.token_to_id:
                out.append(self.token_to_id[UNK_TOKEN])
            else:
                raise KeyError(f"token {t!r} missing from un-UNK'd vocab")
        return out

    def convert_ids_to_tokens(self, ids: Iterable[int]) -> List[str]:
        return [self.id_to_token[i] for i in ids]

    # --- construction ---

    @classmethod
    def from_json(cls, path: str) -> "Vocab":
        with open(path) as f:
            return cls(json.load(f))

    def to_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.token_to_id, f, indent=1)

    @classmethod
    def build_word_vocab(
        cls, token_streams: Iterable[Sequence[str]], min_freq: int = 1
    ) -> "Vocab":
        """Frequency-ordered word vocab with the 4 specials up front."""
        freq: Dict[str, int] = {}
        for stream in token_streams:
            for tok in stream:
                tok = normalize_token(tok)
                freq[tok] = freq.get(tok, 0) + 1
        mapping = {t: i for i, t in enumerate(WORD_SPECIALS)}
        for tok in sorted(freq, key=lambda t: (-freq[t], t)):
            if freq[tok] >= min_freq and tok not in mapping:
                mapping[tok] = len(mapping)
        return cls(mapping)

    @classmethod
    def build_ast_change_vocab(
        cls, ast_label_streams: Iterable[Sequence[str]], threshold: int = 1
    ) -> "Vocab":
        """Dataset.py:46-60: specials then lower-cased AST labels >= threshold,
        in first-seen order (dict insertion order, as the reference iterates)."""
        counts: Dict[str, int] = {}
        for stream in ast_label_streams:
            for label in stream:
                label = label.lower()
                counts[label] = counts.get(label, 0) + 1
        mapping = {t: i for i, t in enumerate(AST_CHANGE_SPECIALS)}
        for label, c in counts.items():
            if c >= threshold and label not in mapping:
                mapping[label] = len(mapping)
        return cls(mapping)


def pad_sequence(seq: List[int], max_len: int, pad_id: int = PAD_ID) -> List[int]:
    """Dataset.py:80-86: right-pad or truncate to exactly max_len."""
    if len(seq) < max_len:
        return seq + [pad_id] * (max_len - len(seq))
    return seq[:max_len]
