"""Asynchronous host input pipeline: batch assembly + H2D off the hot loop.

The COO rewrite fixed the wire format (data/batching.py) and
``prefetch_to_device`` overlapped the H2D transfer, but batch ASSEMBLY
(gather + pad + narrow + sort) still ran serially on the consumer thread,
inside the step-dispatch interval. As the device step gets faster (fused
K-step scans, bf16 wire) that host work becomes the throughput ceiling —
the reference has the same disease terminally (torch DataLoader densifies
650^2 adjacencies per sample and blocks on .cuda() per batch,
run_model.py:94-101).

``Feeder`` is a bounded worker pool that runs assembly tasks ahead of the
training loop:

- **order**: the task sequence IS the batch order. Workers assemble out of
  order; the consumer side emits strictly in sequence, so the exact
  deterministic ``(seed, epoch)`` stream of ``data.batching.epoch_batches``
  is preserved byte-for-byte (pinned by tests/test_feeder.py).
- **bounding**: at most ``depth`` tasks are in flight (dispatched but not
  yet consumed) — host memory stays O(depth * batch_bytes).
- **transfer**: each worker finishes its task with a (sharded)
  ``jax.device_put``, which is asynchronous — the transfer of batch i+1
  overlaps the compute of batch i, same as the old prefetcher. A grouped
  dispatch item (data/grouping.py: a K-stacked same-geometry batch for the
  fused device loop / gradient accumulation) is assembled AND transferred
  by ONE task on one worker, so the whole K-group ships as a single
  ``device_put`` instead of K round-trips; ``n_valid`` sums the 2-D
  ``valid`` of a stacked group the same way it sums the 1-D one.
- **errors**: every task exception is wrapped in :class:`FeederTaskError`
  carrying the task's sequence number and its ``note`` (split positions,
  bucket geometry — set by the task generators), so a poisoned sample is
  identifiable from the traceback. A failing task is retried up to
  ``retries`` times with linear backoff first (transient faults are
  absorbed in the worker). Then, under the default ``on_error="raise"``,
  the first surviving exception re-raises at the consumer on its next
  ``__next__`` (not deferred until the failing sequence number comes up)
  — the historical fail-stop contract. Under ``on_error="record"`` (the
  serving path's PER-TASK ERROR CHANNEL, docs/FAULTS.md) the failing
  item is emitted in sequence with ``error`` set and ``host``/``device``
  None, and the stream continues: one bad sample no longer poisons the
  feed — the consumer sheds it (serve/server.py) instead of dying.
- **fault injection**: an armed robust.faults.FaultInjector checks the
  ``feeder.assemble`` / ``feeder.device_put`` sites around each task,
  keyed by (task sequence, attempt) so thread scheduling cannot reorder
  the deterministic draws; None (default) costs one is-None branch.
- **shutdown**: ``close()`` (or the context manager / end-of-stream /
  error paths, which call it) stops dispatch, unblocks and joins every
  thread — no live threads remain (pinned by tests/test_feeder.py).
- **observability**: every item carries ``stall_s`` (how long the consumer
  blocked waiting for it — the feed-stall numerator train/loop.py feeds
  into profiling.Meter) and ``queue_depth`` (ready-but-unconsumed batches
  when the consumer arrived — persistently 0 means the feed can't keep
  up); ``stats()`` aggregates them.

``num_workers=0`` is the synchronous mode: same interface, tasks run
inline on the consumer thread (assembly time then IS stall), no threads
created. It is both the debug fallback and the control leg bench.py
measures ``feed_stall_frac`` against.

Sync boundaries: the feeder itself never syncs with the device — workers
only *enqueue* transfers; ``n_valid`` is computed host-side from the numpy
batch BEFORE the transfer (reading it back would force a mid-epoch sync).
See docs/PIPELINE.md.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

Batch = Dict[str, Any]
Task = Callable[[], Batch]


class FeederTaskError(RuntimeError):
    """One assembly task failed (after its retry budget): carries the
    task's sequence number and its generator-set ``note`` — split
    positions, bucket geometry, site — so the poisoned sample is
    identifiable from the traceback instead of an anonymous re-raise."""

    def __init__(self, index: int, note: Optional[str],
                 original: BaseException) -> None:
        where = f" ({note})" if note else ""
        super().__init__(
            f"feeder task {index}{where} failed: "
            f"{type(original).__name__}: {original}")
        self.index = index
        self.note = note
        self.original = original


@dataclasses.dataclass
class FedBatch:
    """One emitted pipeline item."""

    index: int          # position in the deterministic batch order
    host: Optional[Batch]  # the assembled numpy batch (for host-side
                        # fields, incl. "_"-prefixed host-only metadata);
                        # None on an error-carrying item (record mode)
    device: Any         # jax.device_put result, "_" keys stripped
                        # (== host when put=False)
    n_valid: int        # real (non-pad) rows, computed pre-transfer
    stall_s: float      # consumer time blocked waiting for THIS item
    queue_depth: int    # ready-but-unconsumed items when consumer arrived
    error: Optional[BaseException] = None  # FeederTaskError in record mode
    retries: int = 0    # assembly attempts beyond the first this item took
    task_s: float = 0.0  # worker-side wall seconds of the successful
                        # assembly attempt (task + device_put enqueue) —
                        # the per-task cost meter the ingest worker-
                        # scaling rows divide stall against; 0 on
                        # error-carrying items


class Feeder:
    """Bounded-queue background batch assembly + H2D pipeline.

    ``tasks``: iterable of zero-arg callables, each returning one host
    batch; the iterable is drained lazily on the dispatcher thread, so a
    generator is fine. ``sharding``: pytree of NamedShardings or a callable
    ``batch -> sharding-or-None`` (mixed-shape streams). ``put=False``
    skips the device transfer (host-only pipelines, e.g. tests).
    """

    def __init__(self, tasks: Iterable[Task], *, num_workers: int = 2,
                 depth: int = 4, sharding=None, put: bool = True,
                 on_error: str = "raise", retries: int = 0,
                 retry_backoff_s: Optional[float] = None, faults=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if num_workers < 0:
            raise ValueError(f"num_workers must be >= 0, got {num_workers}")
        if on_error not in ("raise", "record"):
            raise ValueError(f"on_error {on_error!r} not in "
                             f"{{'raise', 'record'}}")
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        self._sharding = sharding
        self._put = put
        self._num_workers = num_workers
        self._depth = depth
        self._on_error = on_error
        self._retries = retries
        self._retry_backoff_s = retry_backoff_s
        self._faults = faults          # robust.faults.FaultInjector or None
        self._next = 0                 # next sequence number to emit
        self._n_stalls = 0
        self._stall_s = 0.0
        self._stall_max = 0.0
        self._depth_sum = 0
        self._depth_min: Optional[int] = None
        self._n_task_errors = 0
        self._n_task_retries = 0
        self._task_s = 0.0
        self._closed = False
        # resource-lifecycle sanitizer: armed, every pipeline thread is
        # ledgered at start and retired at join, so a close() path that
        # skips a join shows up at teardown with this start site named
        # (analysis.sanitizer.LeakGuard; static twin: RES-LEAK)
        from fira_tpu.analysis.sanitizer import leak_guard

        self._leaks = leak_guard()

        if num_workers == 0:
            self._task_iter: Iterator[Task] = iter(tasks)
            self._threads: list = []
            return

        self._cond = threading.Condition()
        self._ready: Dict[int, FedBatch] = {}
        # lock-discipline sanitizer (--sanitize / tests): the ordered-
        # ready channel is the one structure every worker AND the
        # consumer mutate — armed, a write outside `with self._cond`
        # raises at the line (analysis.sanitizer.ThreadGuard)
        from fira_tpu.analysis.sanitizer import guard_structures

        self._cond, (self._ready,) = guard_structures(
            self, self._cond, [(self._ready, "_ready")],
            lock_label="_cond")
        self._error: Optional[BaseException] = None
        self._total: Optional[int] = None   # set when tasks exhaust
        self._stop = threading.Event()
        self._inflight = threading.Semaphore(depth)
        self._task_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads = [
            threading.Thread(target=self._dispatch, args=(iter(tasks),),
                             name="fira-feeder-dispatch", daemon=True)
        ] + [
            threading.Thread(target=self._work, name=f"fira-feeder-worker-{i}",
                             daemon=True)
            for i in range(num_workers)
        ]
        for t in self._threads:
            t.start()
            if self._leaks is not None:
                self._leaks.track_thread(t)

    # --- pipeline threads ---

    def _dispatch(self, tasks: Iterator[Task]) -> None:
        seq = 0
        try:
            for task in tasks:
                # bound in-flight work; poll so close() can interrupt a
                # dispatcher blocked on a full pipeline
                while not self._stop.is_set():
                    if self._inflight.acquire(timeout=0.05):
                        break
                if self._stop.is_set():
                    return
                self._task_q.put((seq, task))
                seq += 1
        except BaseException as e:  # a raising tasks generator poisons the feed
            self._poison(e)
            return
        finally:
            for _ in range(self._num_workers):
                self._task_q.put(None)
        with self._cond:
            self._total = seq
            self._cond.notify_all()

    def _work(self) -> None:
        while True:
            got = self._task_q.get()
            if got is None or self._stop.is_set():
                return
            seq, task = got
            try:
                item = self._execute(seq, task)
            except BaseException as e:
                self._poison(e)
                return
            with self._cond:
                self._ready[seq] = item
                self._cond.notify_all()

    def _execute(self, seq: int, task: Task) -> FedBatch:
        """Run ONE assembly task under the retry/fault policy. Transient
        failures burn the retry budget with linear backoff; a surviving
        exception is wrapped with the task's identity (FeederTaskError)
        and either raised (``on_error="raise"``, the fail-stop default)
        or returned as an error-carrying item (``"record"`` — the
        per-task error channel the serving path sheds on)."""
        attempt = 0
        while True:
            try:
                t0 = time.perf_counter()
                if self._faults is not None:
                    self._faults.check("feeder.assemble", key=(seq, attempt))
                host = task()
                if self._faults is not None:
                    host = self._faults.corrupt("feeder.assemble", seq, host)
                # host-side row count BEFORE the transfer — reading it
                # back from the device array would force a mid-epoch sync
                n_valid = int(host["valid"].sum())
                if self._faults is not None:
                    self._faults.check("feeder.device_put",
                                       key=(seq, attempt))
                device = self._device_put(host)
                return FedBatch(seq, host, device, n_valid, 0.0, 0,
                                retries=attempt,
                                task_s=time.perf_counter() - t0)
            except Exception as e:
                if attempt < self._retries:
                    attempt += 1
                    if self._retry_backoff_s is not None:
                        time.sleep(self._retry_backoff_s * attempt)  # firacheck: allow[SCHED-BLOCK] worker-side quarantine retry backoff: the WORKER thread is the right place to sleep — siblings keep assembling and the consumer only ever waits on the ordered-ready condition
                    else:
                        # the shared quarantine backoff curve — one
                        # definition for every retry site (docs/FAULTS.md)
                        from fira_tpu.robust.faults import backoff_s

                        time.sleep(backoff_s(attempt))  # firacheck: allow[SCHED-BLOCK] same worker-side retry backoff as above (the shared docs/FAULTS.md curve)
                    continue
                err = FeederTaskError(seq, getattr(task, "note", None), e)
                if self._on_error == "record":
                    return FedBatch(seq, None, None, 0, 0.0, 0, error=err,
                                    retries=attempt)
                raise err from e

    def _device_put(self, host: Batch):
        if not self._put:
            return host
        import jax

        # keys starting with "_" are HOST-ONLY metadata (bucket packer
        # positions/tags, data/buckets.py): they never ship to the device
        # and never reach the sharding callable — the wire pytree keeps the
        # exact structure the jitted programs were traced with
        wire = ({k: v for k, v in host.items() if not k.startswith("_")}
                if isinstance(host, dict) else host)
        sh = self._sharding(wire) if callable(self._sharding) else self._sharding
        return jax.device_put(wire, sh) if sh is not None else jax.device_put(wire)

    def _poison(self, e: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = e
            self._cond.notify_all()
        self._stop.set()

    # --- consumer side ---

    def __iter__(self) -> "Feeder":
        return self

    def __next__(self) -> FedBatch:
        if self._num_workers == 0:
            return self._next_sync()
        t0 = time.perf_counter()
        with self._cond:
            depth_seen = len(self._ready)
            while True:
                if self._error is not None:
                    err = self._error
                    break
                if self._next in self._ready:
                    err = None
                    item = self._ready.pop(self._next)
                    break
                if self._total is not None and self._next >= self._total:
                    err = StopIteration()
                    break
                self._cond.wait()  # firacheck: allow[SCHED-BLOCK] this wait IS the metered feed stall (stall_s): the consumer blocks exactly until the next in-order item, and close()/_poison notify_all so it can never wedge
        if err is not None:
            self.close()
            raise err
        stall = time.perf_counter() - t0
        self._next += 1
        self._inflight.release()
        item.stall_s = stall
        item.queue_depth = depth_seen
        self._record(item, stall, depth_seen)
        return item

    def _next_sync(self) -> FedBatch:
        t0 = time.perf_counter()
        try:
            task = next(self._task_iter)
        except StopIteration:
            self._closed = True
            raise
        item = self._execute(self._next, task)
        stall = time.perf_counter() - t0
        self._next += 1
        item.stall_s = stall
        self._record(item, stall, 0)
        return item

    def _record(self, item: FedBatch, stall: float, depth_seen: int) -> None:
        self._n_stalls += 1
        self._stall_s += stall
        self._stall_max = max(self._stall_max, stall)
        self._depth_sum += depth_seen
        self._depth_min = (depth_seen if self._depth_min is None
                           else min(self._depth_min, depth_seen))
        self._n_task_retries += item.retries
        self._task_s += item.task_s
        if item.error is not None:
            self._n_task_errors += 1

    # --- lifecycle ---

    def close(self) -> None:
        """Stop dispatch, unblock and join every pipeline thread. Idempotent;
        called automatically at end-of-stream, on error, and by the context
        manager — callers that break out of iteration early must call it (or
        use ``with``)."""
        if self._closed:
            return
        self._closed = True
        if not self._threads:
            return
        self._stop.set()
        for _ in range(self._num_workers):
            self._task_q.put(None)   # unblock workers parked on get()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join()
            if self._leaks is not None:
                self._leaks.note_joined(t)
        self._threads = []

    def __enter__(self) -> "Feeder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort: never leave threads parked forever
        try:
            self.close()
        except Exception:
            pass

    # --- observability ---

    def stats(self) -> Dict[str, float]:
        """Aggregate feed-stall / queue-depth stats over the items emitted
        so far. ``feed_stall_s`` is the numerator of ``feed_stall_frac``
        (profiling.Meter owns the interval-time denominator)."""
        n = self._n_stalls
        return {
            "batches": float(n),
            "feed_stall_s": self._stall_s,
            "feed_stall_max_ms": 1e3 * self._stall_max,
            "queue_depth_sum": float(self._depth_sum),
            "queue_depth_mean": (self._depth_sum / n) if n else 0.0,
            "queue_depth_min": float(self._depth_min or 0),
            "num_workers": float(self._num_workers),
            "depth": float(self._depth),
            # per-task error channel accounting (docs/FAULTS.md): items
            # emitted with a recorded error (record mode) and assembly
            # retry attempts absorbed in the workers
            "task_errors": float(self._n_task_errors),
            "task_retries": float(self._n_task_retries),
            # total worker-side assembly seconds over the emitted items:
            # task_s / (workers x wall) is pool utilization — the meter
            # the ingest worker-scaling rows read next to stall_frac
            "task_s": self._task_s,
        }

    # --- adapters ---

    @classmethod
    def from_batches(cls, batches: Iterable[Batch], *, depth: int = 2,
                     num_workers: int = 1, sharding=None,
                     put: bool = True) -> "Feeder":
        """Wrap an ALREADY-ASSEMBLED batch stream (generator or list): the
        stream is drained on the dispatcher thread and each batch's
        device_put runs on a worker — the contract of the old
        ``prefetch_to_device``, which is now a shim over this."""
        tasks = ((lambda b=b: b) for b in batches)
        return cls(tasks, num_workers=num_workers, depth=depth,
                   sharding=sharding, put=put)


def task_note(positions, *, geom_tag: Optional[str] = None,
              site: Optional[str] = None) -> str:
    """Human-readable task identity for FeederTaskError: the split
    positions the task assembles (truncated), plus the bucket geometry
    and call site when known — enough to name the poisoned sample from
    the traceback alone."""
    pos = [int(p) for p in positions]  # firacheck: allow[HOST-SYNC] positions are host-side planning ints (index chunks / request ids); no device value exists here
    shown = ", ".join(str(p) for p in pos[:6])
    if len(pos) > 6:
        shown += f", ... {len(pos) - 6} more"
    parts = [f"split positions [{shown}]"]
    if geom_tag:
        parts.append(f"bucket {geom_tag}")
    if site:
        parts.append(site)
    return "; ".join(parts)


def assembly_tasks(split, chunks, cfg, *, batch_size: Optional[int] = None,
                   stamp: Optional[Callable[[Batch], Batch]] = None
                   ) -> Iterator[Task]:
    """One ``make_batch`` task per index chunk (see
    data.batching.epoch_index_chunks for the order contract). Each task
    carries a ``note`` naming its split positions, so a failing worker's
    FeederTaskError identifies the poisoned chunk.

    ``stamp``: optional post-assembly hook applied WORKER-side (it runs
    inside the task, on the pool thread) — the decode drivers pass
    decode.prefix_cache.stamp_digests here when ``cfg.prefix_cache`` is
    armed, so payload content digests are computed off the scheduler
    thread like the rest of batch assembly."""
    from fira_tpu.data.batching import make_batch

    for chunk in chunks:
        def task(c=chunk):
            b = make_batch(split, c, cfg, batch_size=batch_size)
            return stamp(b) if stamp is not None else b
        task.note = task_note(chunk, site="assembly_tasks")
        yield task
