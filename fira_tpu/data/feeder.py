"""Asynchronous host input pipeline: batch assembly + H2D off the hot loop.

The COO rewrite fixed the wire format (data/batching.py) and
``prefetch_to_device`` overlapped the H2D transfer, but batch ASSEMBLY
(gather + pad + narrow + sort) still ran serially on the consumer thread,
inside the step-dispatch interval. As the device step gets faster (fused
K-step scans, bf16 wire) that host work becomes the throughput ceiling —
the reference has the same disease terminally (torch DataLoader densifies
650^2 adjacencies per sample and blocks on .cuda() per batch,
run_model.py:94-101).

``Feeder`` is a bounded worker pool that runs assembly tasks ahead of the
training loop:

- **order**: the task sequence IS the batch order. Workers assemble out of
  order; the consumer side emits strictly in sequence, so the exact
  deterministic ``(seed, epoch)`` stream of ``data.batching.epoch_batches``
  is preserved byte-for-byte (pinned by tests/test_feeder.py).
- **bounding**: at most ``depth`` tasks are in flight (dispatched but not
  yet consumed) — host memory stays O(depth * batch_bytes).
- **transfer**: each worker finishes its task with a (sharded)
  ``jax.device_put``, which is asynchronous — the transfer of batch i+1
  overlaps the compute of batch i, same as the old prefetcher. A grouped
  dispatch item (data/grouping.py: a K-stacked same-geometry batch for the
  fused device loop / gradient accumulation) is assembled AND transferred
  by ONE task on one worker, so the whole K-group ships as a single
  ``device_put`` instead of K round-trips; ``n_valid`` sums the 2-D
  ``valid`` of a stacked group the same way it sums the 1-D one.
- **errors**: the first worker/dispatcher exception is re-raised at the
  consumer on its next ``__next__`` (not deferred until the failing
  sequence number comes up), so a poisoned pipeline surfaces within one
  step.
- **shutdown**: ``close()`` (or the context manager / end-of-stream /
  error paths, which call it) stops dispatch, unblocks and joins every
  thread — no live threads remain (pinned by tests/test_feeder.py).
- **observability**: every item carries ``stall_s`` (how long the consumer
  blocked waiting for it — the feed-stall numerator train/loop.py feeds
  into profiling.Meter) and ``queue_depth`` (ready-but-unconsumed batches
  when the consumer arrived — persistently 0 means the feed can't keep
  up); ``stats()`` aggregates them.

``num_workers=0`` is the synchronous mode: same interface, tasks run
inline on the consumer thread (assembly time then IS stall), no threads
created. It is both the debug fallback and the control leg bench.py
measures ``feed_stall_frac`` against.

Sync boundaries: the feeder itself never syncs with the device — workers
only *enqueue* transfers; ``n_valid`` is computed host-side from the numpy
batch BEFORE the transfer (reading it back would force a mid-epoch sync).
See docs/PIPELINE.md.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

Batch = Dict[str, Any]
Task = Callable[[], Batch]


@dataclasses.dataclass
class FedBatch:
    """One emitted pipeline item."""

    index: int          # position in the deterministic batch order
    host: Batch         # the assembled numpy batch (for host-side fields,
                        # incl. "_"-prefixed host-only metadata)
    device: Any         # jax.device_put result, "_" keys stripped
                        # (== host when put=False)
    n_valid: int        # real (non-pad) rows, computed pre-transfer
    stall_s: float      # consumer time blocked waiting for THIS item
    queue_depth: int    # ready-but-unconsumed items when consumer arrived


class Feeder:
    """Bounded-queue background batch assembly + H2D pipeline.

    ``tasks``: iterable of zero-arg callables, each returning one host
    batch; the iterable is drained lazily on the dispatcher thread, so a
    generator is fine. ``sharding``: pytree of NamedShardings or a callable
    ``batch -> sharding-or-None`` (mixed-shape streams). ``put=False``
    skips the device transfer (host-only pipelines, e.g. tests).
    """

    def __init__(self, tasks: Iterable[Task], *, num_workers: int = 2,
                 depth: int = 4, sharding=None, put: bool = True):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if num_workers < 0:
            raise ValueError(f"num_workers must be >= 0, got {num_workers}")
        self._sharding = sharding
        self._put = put
        self._num_workers = num_workers
        self._depth = depth
        self._next = 0                 # next sequence number to emit
        self._n_stalls = 0
        self._stall_s = 0.0
        self._stall_max = 0.0
        self._depth_sum = 0
        self._depth_min: Optional[int] = None
        self._closed = False

        if num_workers == 0:
            self._task_iter: Iterator[Task] = iter(tasks)
            self._threads: list = []
            return

        self._cond = threading.Condition()
        self._ready: Dict[int, FedBatch] = {}
        self._error: Optional[BaseException] = None
        self._total: Optional[int] = None   # set when tasks exhaust
        self._stop = threading.Event()
        self._inflight = threading.Semaphore(depth)
        self._task_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads = [
            threading.Thread(target=self._dispatch, args=(iter(tasks),),
                             name="fira-feeder-dispatch", daemon=True)
        ] + [
            threading.Thread(target=self._work, name=f"fira-feeder-worker-{i}",
                             daemon=True)
            for i in range(num_workers)
        ]
        for t in self._threads:
            t.start()

    # --- pipeline threads ---

    def _dispatch(self, tasks: Iterator[Task]) -> None:
        seq = 0
        try:
            for task in tasks:
                # bound in-flight work; poll so close() can interrupt a
                # dispatcher blocked on a full pipeline
                while not self._stop.is_set():
                    if self._inflight.acquire(timeout=0.05):
                        break
                if self._stop.is_set():
                    return
                self._task_q.put((seq, task))
                seq += 1
        except BaseException as e:  # a raising tasks generator poisons the feed
            self._poison(e)
            return
        finally:
            for _ in range(self._num_workers):
                self._task_q.put(None)
        with self._cond:
            self._total = seq
            self._cond.notify_all()

    def _work(self) -> None:
        while True:
            got = self._task_q.get()
            if got is None or self._stop.is_set():
                return
            seq, task = got
            try:
                host = task()
                # host-side row count BEFORE the transfer — reading it back
                # from the device array would force a mid-epoch sync
                n_valid = int(host["valid"].sum())
                device = self._device_put(host)
            except BaseException as e:
                self._poison(e)
                return
            with self._cond:
                self._ready[seq] = FedBatch(seq, host, device, n_valid,
                                            0.0, 0)
                self._cond.notify_all()

    def _device_put(self, host: Batch):
        if not self._put:
            return host
        import jax

        # keys starting with "_" are HOST-ONLY metadata (bucket packer
        # positions/tags, data/buckets.py): they never ship to the device
        # and never reach the sharding callable — the wire pytree keeps the
        # exact structure the jitted programs were traced with
        wire = ({k: v for k, v in host.items() if not k.startswith("_")}
                if isinstance(host, dict) else host)
        sh = self._sharding(wire) if callable(self._sharding) else self._sharding
        return jax.device_put(wire, sh) if sh is not None else jax.device_put(wire)

    def _poison(self, e: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = e
            self._cond.notify_all()
        self._stop.set()

    # --- consumer side ---

    def __iter__(self) -> "Feeder":
        return self

    def __next__(self) -> FedBatch:
        if self._num_workers == 0:
            return self._next_sync()
        t0 = time.perf_counter()
        with self._cond:
            depth_seen = len(self._ready)
            while True:
                if self._error is not None:
                    err = self._error
                    break
                if self._next in self._ready:
                    err = None
                    item = self._ready.pop(self._next)
                    break
                if self._total is not None and self._next >= self._total:
                    err = StopIteration()
                    break
                self._cond.wait()
        if err is not None:
            self.close()
            raise err
        stall = time.perf_counter() - t0
        self._next += 1
        self._inflight.release()
        item.stall_s = stall
        item.queue_depth = depth_seen
        self._record(stall, depth_seen)
        return item

    def _next_sync(self) -> FedBatch:
        t0 = time.perf_counter()
        try:
            task = next(self._task_iter)
            host = task()
            n_valid = int(host["valid"].sum())
            device = self._device_put(host)
        except StopIteration:
            self._closed = True
            raise
        stall = time.perf_counter() - t0
        seq = self._next
        self._next += 1
        self._record(stall, 0)
        return FedBatch(seq, host, device, n_valid, stall, 0)

    def _record(self, stall: float, depth_seen: int) -> None:
        self._n_stalls += 1
        self._stall_s += stall
        self._stall_max = max(self._stall_max, stall)
        self._depth_sum += depth_seen
        self._depth_min = (depth_seen if self._depth_min is None
                           else min(self._depth_min, depth_seen))

    # --- lifecycle ---

    def close(self) -> None:
        """Stop dispatch, unblock and join every pipeline thread. Idempotent;
        called automatically at end-of-stream, on error, and by the context
        manager — callers that break out of iteration early must call it (or
        use ``with``)."""
        if self._closed:
            return
        self._closed = True
        if not self._threads:
            return
        self._stop.set()
        for _ in range(self._num_workers):
            self._task_q.put(None)   # unblock workers parked on get()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join()
        self._threads = []

    def __enter__(self) -> "Feeder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort: never leave threads parked forever
        try:
            self.close()
        except Exception:
            pass

    # --- observability ---

    def stats(self) -> Dict[str, float]:
        """Aggregate feed-stall / queue-depth stats over the items emitted
        so far. ``feed_stall_s`` is the numerator of ``feed_stall_frac``
        (profiling.Meter owns the interval-time denominator)."""
        n = self._n_stalls
        return {
            "batches": float(n),
            "feed_stall_s": self._stall_s,
            "feed_stall_max_ms": 1e3 * self._stall_max,
            "queue_depth_sum": float(self._depth_sum),
            "queue_depth_mean": (self._depth_sum / n) if n else 0.0,
            "queue_depth_min": float(self._depth_min or 0),
            "num_workers": float(self._num_workers),
            "depth": float(self._depth),
        }

    # --- adapters ---

    @classmethod
    def from_batches(cls, batches: Iterable[Batch], *, depth: int = 2,
                     num_workers: int = 1, sharding=None,
                     put: bool = True) -> "Feeder":
        """Wrap an ALREADY-ASSEMBLED batch stream (generator or list): the
        stream is drained on the dispatcher thread and each batch's
        device_put runs on a worker — the contract of the old
        ``prefetch_to_device``, which is now a shim over this."""
        tasks = ((lambda b=b: b) for b in batches)
        return cls(tasks, num_workers=num_workers, depth=depth,
                   sharding=sharding, put=put)


def assembly_tasks(split, chunks, cfg, *, batch_size: Optional[int] = None
                   ) -> Iterator[Task]:
    """One ``make_batch`` task per index chunk (see
    data.batching.epoch_index_chunks for the order contract)."""
    from fira_tpu.data.batching import make_batch

    for chunk in chunks:
        yield (lambda c=chunk: make_batch(split, c, cfg,
                                          batch_size=batch_size))
