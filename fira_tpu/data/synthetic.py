"""Deterministic synthetic corpus in the reference DataSet/ schema.

The reference's corpus blobs are stripped from the mount (SURVEY.md caveat),
so tests and the fira-tiny config run on generated commits that are
structurally faithful to Appendix A: <nb>/<nl> sentinel blocks with mark-2
headers, deleted/added/context runs, camelCase sub-token splits, variable
anonymization maps, a small AST with parent-child edges, AST->code leaf
edges, and change (edit-op) nodes wired to both code and AST — i.e. every
edge family the graph builder assembles.
"""

from __future__ import annotations

import random
from typing import Dict, List, Tuple

from fira_tpu.data.schema import Corpus
from fira_tpu.data.vocab import LEMMATIZATION, Vocab, normalize_token

_PARTS = [
    "get", "set", "add", "remove", "update", "check", "user", "name",
    "count", "value", "index", "list", "node", "item", "cache", "parser",
    "token", "buffer", "handler", "config", "state", "map", "size", "flag",
]
_TYPES = ["int", "long", "boolean", "String", "void", "Object"]
_MSG_VERBS = ["fixed", "added", "removed", "update", "refactor", "use", "handle"]
_MSG_NOUNS = ["bug", "npe", "leak", "test", "check", "logic", "default", "case"]
_AST_LABELS = [
    "typedeclaration", "methoddeclaration", "block",
    "variabledeclarationstatement", "methodinvocation", "simplename",
    "ifstatement", "returnstatement", "assignment", "expressionstatement",
]
_CHANGE_KINDS = ["match", "update", "move", "delete", "add"]

# --- planted-signal mode (generate_corpus(signal=True)) ---
# The quality-parity campaign needs a corpus where each ablated channel
# carries information recoverable ONLY through that channel, so the Table-3
# ablation ORDERING becomes a test of whether the architecture exploits the
# channel — the mechanism the paper's ablations demonstrate — rather than a
# coin flip on signal-free noise:
#   edit channel: the message verb is (usually) a function of the change
#     NODES' kind labels, which are what use_edit=False removes; the kinds
#     are sampled independently of the diff text, so nothing else reveals
#     them.
#   sub-token channel: messages (usually) include a camelCase part of an
#     identifier present in THIS commit, drawn from a pool with a rare tail
#     — the generation path can't learn rare parts seen once, the sub-token
#     copy pointer reads them off the diff.
_KIND_VERB = {"delete": "removed", "add": "added", "update": "update",
              "move": "refactor", "match": "handle"}
_KIND_PRIORITY = ["delete", "add", "update", "move", "match"]
# ~13.8k entries: over a 90k-commit corpus each appears only a few times,
# so the generation softmax can't learn it but the copy pointer can read it
_PARTS_RARE = [p + q + r for p in _PARTS for q in _PARTS for r in _PARTS]


def _camel(rng: random.Random, n_parts: int = 2) -> Tuple[str, List[str]]:
    parts = [rng.choice(_PARTS) for _ in range(n_parts)]
    name = parts[0] + "".join(p.capitalize() for p in parts[1:])
    return name, parts


def _atts_for(token: str, split_map: Dict[str, List[str]]) -> List[str]:
    return list(split_map.get(token, []))


def generate_corpus(n_commits: int, seed: int = 0,
                    signal: bool = False) -> Corpus:
    """``signal=False`` (default) is byte-stable for a given seed — tests
    and pinned artifacts depend on it. ``signal=True`` plants the
    channel-specific message signal described above for the ablation
    campaign; it draws extra randomness, so it is a different corpus."""
    rng = random.Random(seed)
    streams: Dict[str, list] = {
        k: [] for k in [
            "difftoken", "diffmark", "diffatt", "msg", "variable", "ast",
            "change", "edge_ast", "edge_ast_code", "edge_change_ast",
            "edge_change_code",
        ]
    }

    for _ in range(n_commits):
        split_map: Dict[str, List[str]] = {}

        def ident(n_parts=2):
            if signal and rng.random() < 0.25:
                # rare-tail part: seen in ~a handful of commits corpus-wide,
                # so only the sub-token copy pointer can reproduce it
                parts = [rng.choice(_PARTS), rng.choice(_PARTS_RARE)]
                name = parts[0] + parts[1].capitalize()
            else:
                name, parts = _camel(rng, n_parts)
            if len(parts) > 1:
                split_map[name] = parts
            return name

        cls = ident(2).capitalize()
        method = ident(2)
        old_var = ident(2)
        new_var = ident(2)
        typ = rng.choice(_TYPES)

        # header block: <nb> ... <nl>, all context (mark 2)
        tokens: List[str] = ["<nb>", "class", cls, "<nl>"]
        marks: List[int] = [2, 2, 2, 2]

        def emit(toks: List[str], mark: int):
            tokens.extend(toks)
            marks.extend([mark] * len(toks))

        emit(["public", typ, method, "(", ")", "{"], 2)
        emit(["int", old_var, "=", f"NUMBER{rng.randrange(4)}", ";"], 1)   # deleted
        emit(["int", new_var, "=", f"NUMBER{rng.randrange(4)}", ";"], 3)   # added
        if rng.random() < 0.5:
            extra = ident(2)
            emit(["return", extra, ";"], rng.choice([1, 2, 3]))
        emit(["}"], 2)

        diff_atts = [_atts_for(t, split_map) for t in tokens]

        # variable anonymization: occasionally map an identifier to a placeholder
        var_map: Dict[str, str] = {}
        if rng.random() < 0.4:
            secret = method
            var_map[secret] = f"STRING{rng.randrange(8)}"
            split_map.pop(secret, None)
            for j, t in enumerate(tokens):
                if t == secret:
                    diff_atts[j] = []

        # message: verbs trigger lemmatization; copyable identifiers + subtoken parts
        msg = [rng.choice(_MSG_VERBS), rng.choice(_MSG_NOUNS)]
        if rng.random() < 0.7:
            msg += ["in", rng.choice([method, old_var, new_var])]
        if rng.random() < 0.5:
            msg += [rng.choice(_PARTS)]  # often a sub-token of something

        # small AST over the method: indices into ast list
        n_ast = rng.randint(3, 6)
        ast = [rng.choice(_AST_LABELS) for _ in range(n_ast)]
        ast[0] = "typedeclaration"
        edge_ast = [[rng.randrange(i), i] for i in range(1, n_ast)]  # tree edges

        # AST leaves point at identifier positions in the raw diff
        ident_positions = [
            j for j, t in enumerate(tokens)
            if t not in ("<nb>", "<nl>") and marks[j] in (1, 2, 3) and t[0].isalpha()
        ]
        rng.shuffle(ident_positions)
        edge_ast_code = []
        used_code = set()
        for a in range(n_ast):
            if rng.random() < 0.6 and ident_positions:
                pos = ident_positions.pop()
                if pos not in used_code:
                    used_code.add(pos)
                    edge_ast_code.append([a, pos])

        # change nodes: each touches either a code position or an ast node
        n_change = rng.randint(1, 3)
        change = [rng.choice(_CHANGE_KINDS) for _ in range(n_change)]
        edge_change_code = []
        edge_change_ast = []
        for c in range(n_change):
            if rng.random() < 0.5 and ident_positions:
                pos = ident_positions.pop()
                if pos not in used_code:
                    used_code.add(pos)
                    edge_change_code.append([c, pos])
                    continue
            edge_change_ast.append([c, rng.randrange(n_ast)])

        if signal:
            # edit-channel plant: the verb follows the change nodes' kind
            # labels (sampled independently of the diff text, so ONLY the
            # change nodes — what use_edit=False removes — reveal it)
            for kind in _KIND_PRIORITY:
                if kind in change:
                    if rng.random() < 0.85:
                        msg[0] = _KIND_VERB[kind]
                    break
            # sub-token-channel plant: a camelCase part of an identifier in
            # THIS commit; the rare tail makes the copy pointer the only
            # reliable route
            parts_pool = [p for nm in (method, old_var, new_var)
                          for p in split_map.get(nm, [])]
            if parts_pool and rng.random() < 0.8:
                msg.append(rng.choice(parts_pool))

        streams["difftoken"].append(tokens)
        streams["diffmark"].append(marks)
        streams["diffatt"].append(diff_atts)
        streams["msg"].append(msg)
        streams["variable"].append(var_map)
        streams["ast"].append(ast)
        streams["change"].append(change)
        streams["edge_ast"].append(edge_ast)
        streams["edge_ast_code"].append(edge_ast_code)
        streams["edge_change_ast"].append(edge_change_ast)
        streams["edge_change_code"].append(edge_change_code)

    return Corpus(streams)


def build_vocabs(corpus: Corpus, min_freq: int = 1) -> Tuple[Vocab, Vocab]:
    """Word + ast/change vocabs over the processed token space (substituted,
    case-normalized, lemmatized), mirroring what the reference ships."""
    from fira_tpu.data.dataset import _substitute

    word_streams = []
    for i in range(len(corpus)):
        var_map = corpus.streams["variable"][i]
        diff = _substitute(corpus.streams["difftoken"][i], var_map)
        msg = [
            LEMMATIZATION.get(t, t)
            for t in _substitute(corpus.streams["msg"][i], var_map)
        ]
        subs = [p for att in corpus.streams["diffatt"][i] for p in att]
        word_streams.extend([diff, msg, subs])
    word_vocab = Vocab.build_word_vocab(word_streams, min_freq=min_freq)
    ast_vocab = Vocab.build_ast_change_vocab(corpus.streams["ast"])
    return word_vocab, ast_vocab


def write_corpus_dir(data_dir: str, n_commits: int, seed: int = 0,
                     min_freq: int = 1, signal: bool = False) -> Corpus:
    """Generate and persist a DataSet/-layout corpus directory."""
    corpus = generate_corpus(n_commits, seed=seed, signal=signal)
    corpus.save(data_dir)
    word_vocab, ast_vocab = build_vocabs(corpus, min_freq=min_freq)
    import os

    word_vocab.to_json(os.path.join(data_dir, "word_vocab.json"))
    ast_vocab.to_json(os.path.join(data_dir, "ast_change_vocab.json"))
    return corpus


def write_extracted_corpus_dir(data_dir: str, n_commits: int, seed: int = 0,
                               min_freq: int = 1) -> Corpus:
    """A corpus whose graph streams come from the REAL extraction
    pipeline instead of the random synthetic ones: the synthetic
    difftoken/diffmark/msg/variable streams are kept, ``diffatt`` is
    re-derived (pipeline.derive_diffatt — the reference convention), and
    ast/change/edge_* are produced by ``pipeline.process_commits`` (FSM +
    native astdiff extraction, per-commit degradation included).

    This is the ROUND-TRIP corpus of the ingest equivalence contract
    (docs/INGEST.md): a commit's reconstructed unified diff pushed
    through ``fira_tpu/ingest`` re-runs the same FSM/extraction and must
    reproduce these exact streams — hence byte-identical wire payloads
    and served output (tests/test_ingest.py, check.sh ingest smoke)."""
    import os

    from fira_tpu.preprocess.pipeline import derive_diffatt, process_commits

    corpus = generate_corpus(n_commits, seed=seed)
    corpus.streams["diffatt"] = derive_diffatt(corpus.streams["difftoken"])
    # index_offset clears the reference's per-corpus commit-70 hack
    # (extract.ast_code_edges commit_index==70 'nextParent' special
    # case): ingest extracts requests index-FREE, so the round-trip
    # corpus must be extracted index-independently too or the byte
    # contract would silently depend on a corpus index
    streams, _errors = process_commits(corpus.streams["difftoken"],
                                       corpus.streams["diffmark"],
                                       0, n_commits,
                                       index_offset=1_000_000)
    corpus.streams.update(streams)
    corpus.save(data_dir)
    word_vocab, ast_vocab = build_vocabs(corpus, min_freq=min_freq)
    word_vocab.to_json(os.path.join(data_dir, "word_vocab.json"))
    ast_vocab.to_json(os.path.join(data_dir, "ast_change_vocab.json"))
    return corpus


def make_memory_split(cfg, n: int, seed: int = 0, pad_vocab_to: int = 0,
                      pad_ast_vocab_to: int = 0):
    """Generate a fully in-memory ProcessedSplit (no disk): returns
    (cfg with vocab sizes filled in, split, word_vocab).

    ``pad_vocab_to`` / ``pad_ast_vocab_to`` inflate the vocab sizes so
    benchmark runs match the reference's 24,650-word / 71-label vocab compute
    without its corpus."""
    from fira_tpu.data.dataset import ProcessedSplit, process_record

    corpus = generate_corpus(n, seed=seed)
    word_vocab, ast_vocab = build_vocabs(corpus)
    cfg = cfg.replace(
        vocab_size=max(len(word_vocab), pad_vocab_to),
        ast_change_vocab_size=max(len(ast_vocab), pad_ast_vocab_to),
    )
    examples = [
        process_record(corpus.record(i), word_vocab, ast_vocab, cfg)
        for i in range(n)
    ]
    return cfg, ProcessedSplit.from_examples(examples), word_vocab


def thin_edges(split, k: int):
    """Copy of a ProcessedSplit with each sample's edges truncated to at
    most k — drops the mean edge count below the batching gather's
    flat-regime crossover (data/batching._VEC_EDGE_CROSSOVER) so the
    golden test and the assembly microbench can exercise both copy
    regimes on one corpus."""
    import numpy as np

    from fira_tpu.data.dataset import ProcessedSplit

    arr = split.arrays
    off = arr["edge_offsets"]
    counts = np.minimum(np.diff(off), k)
    new = dict(arr)
    new["edge_offsets"] = np.concatenate(
        [[0], np.cumsum(counts)]).astype(off.dtype)
    for f in ("edge_senders", "edge_receivers", "edge_values", "edge_kinds"):
        new[f] = np.concatenate(
            [arr[f][off[i] : off[i] + counts[i]] for i in range(len(counts))])
    return ProcessedSplit(new)


def make_memory_batch(cfg, n: int, seed: int = 0, pad_vocab_to: int = 0):
    """One in-memory batch of n fresh synthetic commits (no disk)."""
    from fira_tpu.data.batching import make_batch

    import numpy as np

    cfg, split, word_vocab = make_memory_split(cfg, n, seed=seed,
                                               pad_vocab_to=pad_vocab_to)
    return cfg, make_batch(split, np.arange(n), cfg), word_vocab
