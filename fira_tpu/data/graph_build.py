"""Pure graph-assembly functions: sub-token dedup, copy labels, edge COO.

This is the parity-critical heart of the data layer, a function-for-invariant
rebuild of the reference's per-commit tensorization (Dataset.py:96-334) with
one deliberate representation change: the adjacency leaves the host as a
normalized COO edge list (senders/receivers/values), never as a dense
graph_len^2 array. The reference densifies every sample on the host
(Dataset.py:336-343; ~287 MB per 170-batch) — on TPU we scatter the COO into
a dense batch once per step inside the jitted program, so the host->device
transfer is ~100x smaller and the MXU still sees a dense bmm.

Node index space (Dataset.py:225-266 offset arithmetic), for the full config:
  [0, sou_len)                         diff tokens (incl. <start> at 0)
  [sou_len, sou_len+sub_token_len)     sub-token nodes
  [sou_len+sub_token_len, graph_len)   AST-type nodes, then change nodes
                                       (change nodes start at +len(ast_labels))

Replicated quirks (SURVEY.md Appendix B):
- the six edge families collapse into ONE untyped adjacency (process_edge's
  `kind` argument is dead, Dataset.py:346-357);
- code-side skip rule `p2 >= sou_len` applies to change->code and ast->code
  edges only (Dataset.py:228,243); sub-token and sequential edges are NOT
  range-checked by the reference. We check only the graph_len bound (indices
  beyond it would have crashed the reference's scipy constructor, so raising
  preserves crash parity); an over-long diff or sub-token list whose edges
  bleed across region boundaries but stay inside the graph is wired exactly
  as the reference wires it — silently;
- diff copy labels carry a +1 <start> shift, sub-token labels do not
  (Dataset.py:202,213), and diff copies take precedence (Dataset.py:210-211);
- symmetric degree normalization 1/sqrt(deg_row)/sqrt(deg_col) computed over
  the deduplicated, self-looped edge multiset (Dataset.py:277-291).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Sequence, Tuple

import numpy as np


class GraphBuildError(ValueError):
    pass


def dedup_sub_tokens(
    diff_tokens: Sequence[str], diff_atts: Sequence[Sequence[str]]
) -> Tuple[List[str], List[Tuple[int, int]]]:
    """Sub-token node list + (token_pos, sub_pos) edges with per-token dedup.

    Dataset.py:173-196: a repeated integral token reuses its existing
    sub-token nodes and only contributes new edges. Positions are relative to
    the raw (unshifted, unpadded) diff.
    """
    sub_tokens: List[str] = []
    edges: List[Tuple[int, int]] = []
    seen: Dict[str, List[int]] = {}
    for j, att in enumerate(diff_atts):
        if not att:
            continue
        for part in att:
            # crash parity with Dataset.py:148-151: a non-lowercase sub-token
            # would silently miss copy-label matches against normalized
            # message tokens, so fail loudly like the reference does.
            if not part.islower():
                raise GraphBuildError(
                    f"sub-token {part!r} of token {diff_tokens[j]!r} is not "
                    f"lower-case"
                )
        token = diff_tokens[j]
        if token in seen:
            existing = [sub_tokens[k] for k in seen[token]]
            if existing != list(att):
                raise GraphBuildError(
                    f"token {token!r} repeated with different sub-tokens: "
                    f"{existing} vs {list(att)}"
                )
            for k in seen[token]:
                edges.append((j, k))
        else:
            start = len(sub_tokens)
            seen[token] = list(range(start, start + len(att)))
            sub_tokens.extend(att)
            for k in seen[token]:
                edges.append((j, k))
    return sub_tokens, edges


def copy_labels(
    msg_ids: Sequence[int],
    msg_tokens: Sequence[str],
    diff_tokens: Sequence[str],
    sub_tokens: Sequence[str],
    vocab_size: int,
    sou_len: int,
    use_subtoken_copy: bool = True,
    sub_token_len: int = None,
) -> List[int]:
    """Per-position target labels with copy ids (Dataset.py:199-213).

    A message token found among the diff tokens gets label
    ``vocab_size + diff_index + 1`` (the +1 mirrors the <start> shift of the
    padded diff). One found among sub-tokens gets
    ``vocab_size + sou_len + sub_index`` — unless a diff copy already claimed
    the position (diff precedence). Otherwise the label stays the vocab id.

    Replicated quirk: indices come from the UNtruncated diff/sub-token lists
    (Dataset.py:202,209 search the raw lists), so a first occurrence past the
    padded length yields a label in the wrong copy span — exactly as the
    reference supervises it. A label beyond the fused distribution entirely
    (diff index >= sou_len + sub_token_len - 1) made the reference's torch
    NLL crash loudly; XLA gathers clamp silently, so when ``sub_token_len``
    is given we raise instead.
    """
    labels = list(msg_ids)
    for k, token in enumerate(msg_tokens):
        if token in diff_tokens:
            labels[k] = diff_tokens.index(token) + vocab_size + 1
    if use_subtoken_copy:
        for k, token in enumerate(msg_tokens):
            if token in sub_tokens:
                if labels[k] >= vocab_size:
                    continue  # diff copy wins (Dataset.py:210-211)
                labels[k] = sub_tokens.index(token) + vocab_size + sou_len
    if sub_token_len is not None:
        width = vocab_size + sou_len + sub_token_len
        for k, label in enumerate(labels):
            if label >= width:
                raise GraphBuildError(
                    f"copy label {label} at msg position {k} exceeds the "
                    f"fused distribution width {width}"
                )
    return labels


# Edge-family kinds, in the reference's insertion order (Dataset.py:220-275).
# The reference COMPUTES these six families then flattens them (process_edge's
# `kind` argument is dead, Dataset.py:346-357); kinds are retained here so the
# opt-in typed-edge extension (cfg.typed_edges) can weight families — with all
# weights 1 it reproduces the flattened reference graph exactly.
EDGE_KIND_CHANGE_CODE = 0
EDGE_KIND_CHANGE_AST = 1
EDGE_KIND_AST_CODE = 2
EDGE_KIND_AST_AST = 3
EDGE_KIND_CODE_SUBTOKEN = 4
EDGE_KIND_SEQUENTIAL = 5
EDGE_KIND_SELF_LOOP = 6
N_EDGE_KINDS = 7


@dataclasses.dataclass
class CooAdjacency:
    """Symmetric, degree-normalized adjacency as COO triplets."""

    senders: np.ndarray    # int32 [n_edges]
    receivers: np.ndarray  # int32 [n_edges]
    values: np.ndarray     # float32 [n_edges]
    kinds: np.ndarray      # int8 [n_edges] (EDGE_KIND_*; first family wins
                           # on dedup, like the reference's first-insert)

    @property
    def n_edges(self) -> int:
        return int(self.senders.shape[0])

    def to_dense(self, n: int) -> np.ndarray:
        dense = np.zeros((n, n), dtype=np.float32)
        dense[self.senders, self.receivers] = self.values
        return dense


def build_adjacency(
    *,
    sou_len: int,
    sub_token_len: int,
    ast_change_len: int,
    raw_diff_len: int,
    n_ast: int,
    edge_change_code: Sequence[Tuple[int, int]],
    edge_change_ast: Sequence[Tuple[int, int]],
    edge_ast_code: Sequence[Tuple[int, int]],
    edge_ast: Sequence[Tuple[int, int]],
    edge_sub_token: Sequence[Tuple[int, int]],
    use_edit: bool = True,
) -> CooAdjacency:
    """Assemble the per-commit adjacency exactly as Dataset.py:220-294.

    Families are appended in the reference's order (change-code, change-ast,
    ast-code, ast-ast, code-subtoken, sequential chain, self-loops), each edge
    inserted symmetrically once, then symmetrically degree-normalized.
    ``use_edit=False`` drops the two change families (no_edit ablation).
    """
    graph_len = sou_len + sub_token_len + ast_change_len
    ast_base = sou_len + sub_token_len
    change_base = ast_base + n_ast

    pairs: List[Tuple[int, int]] = []
    kinds: List[int] = []
    seen = set()

    def add(p1: int, p2: int, kind: int) -> None:
        # process_edge (Dataset.py:346-357): both directions, dedup, weight 1.
        if not (0 <= p1 < graph_len and 0 <= p2 < graph_len):
            raise GraphBuildError(
                f"edge ({p1},{p2}) outside graph of {graph_len} nodes"
            )
        if (p1, p2) not in seen:
            seen.add((p1, p2))
            pairs.append((p1, p2))
            kinds.append(kind)
        if (p2, p1) not in seen:
            seen.add((p2, p1))
            pairs.append((p2, p1))
            kinds.append(kind)

    if use_edit:
        for c, j in edge_change_code:          # Dataset.py:225-230
            p2 = j + 1
            if p2 >= sou_len:
                continue
            add(change_base + c, p2, EDGE_KIND_CHANGE_CODE)
        for c, a in edge_change_ast:           # Dataset.py:233-237
            add(change_base + c, ast_base + a, EDGE_KIND_CHANGE_AST)
    for a, j in edge_ast_code:                 # Dataset.py:240-245
        p2 = j + 1
        if p2 >= sou_len:
            continue
        add(ast_base + a, p2, EDGE_KIND_AST_CODE)
    for a1, a2 in edge_ast:                    # Dataset.py:248-252
        add(ast_base + a1, ast_base + a2, EDGE_KIND_AST_AST)
    for j, k in edge_sub_token:                # Dataset.py:255-259
        add(j + 1, sou_len + k, EDGE_KIND_CODE_SUBTOKEN)
    for j in range(raw_diff_len + 2 - 1):      # Dataset.py:263-266
        add(j, j + 1, EDGE_KIND_SEQUENTIAL)

    for i in range(graph_len):                 # Dataset.py:271-275
        if (i, i) in seen:
            raise GraphBuildError(f"explicit self-edge on node {i} before self-loops")
        pairs.append((i, i))
        kinds.append(EDGE_KIND_SELF_LOOP)

    rows = np.fromiter((p[0] for p in pairs), dtype=np.int32, count=len(pairs))
    cols = np.fromiter((p[1] for p in pairs), dtype=np.int32, count=len(pairs))
    # symmetric degree normalization (Dataset.py:277-291)
    deg_row = np.bincount(rows, minlength=graph_len).astype(np.float64)
    deg_col = np.bincount(cols, minlength=graph_len).astype(np.float64)
    values = 1.0 / np.sqrt(deg_row[rows]) / np.sqrt(deg_col[cols])
    return CooAdjacency(rows, cols, values.astype(np.float32),
                        np.asarray(kinds, dtype=np.int8))


