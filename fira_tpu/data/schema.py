"""Typed schema for the 11-stream commit corpus (SURVEY.md Appendix A).

The reference keeps the corpus as 11 index-aligned JSON lists under DataSet/
(Dataset.py:30-44). ``CommitRecord`` is the per-commit view; ``Corpus`` loads,
validates, and iterates the directory layout. The same layout is produced by
the synthetic generator and by the preprocessing pipeline, so everything
downstream is source-agnostic.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterator, List, Tuple

# file name -> (json key used internally)
CORPUS_FILES = [
    "difftoken.json",       # [str] diff tokens with <nb>/<nl> sentinels
    "diffmark.json",        # [int] 1=deleted, 2=context, 3=added
    "diffatt.json",         # [[str]] per-token sub-token lists ([] if none)
    "msg.json",             # [str] first-sentence commit message tokens
    "variable.json",        # {orig_identifier: placeholder}
    "ast.json",             # [str] AST internal-node type labels
    "change.json",          # [str] edit-op labels (match/update/move/delete/add)
    "edge_ast.json",        # [[i,j]] AST parent->child (indices into ast)
    "edge_ast_code.json",   # [[ast_i, code_j]] AST-leaf-parent -> raw diff pos
    "edge_change_ast.json", # [[change_i, ast_j]]
    "edge_change_code.json" # [[change_i, code_j]]
]

WORD_VOCAB_FILE = "word_vocab.json"
AST_CHANGE_VOCAB_FILE = "ast_change_vocab.json"
SPLIT_INDEX_FILE = "all_index"  # {'train': [...], 'valid': [...], 'test': [...]}


@dataclasses.dataclass
class CommitRecord:
    """One commit's change representation (pre-tensorization)."""

    diff_tokens: List[str]
    diff_marks: List[int]
    diff_atts: List[List[str]]
    msg_tokens: List[str]
    var_map: Dict[str, str]
    ast_labels: List[str]
    change_labels: List[str]
    edge_ast: List[Tuple[int, int]]
    edge_ast_code: List[Tuple[int, int]]
    edge_change_ast: List[Tuple[int, int]]
    edge_change_code: List[Tuple[int, int]]


class Corpus:
    """The 11 index-aligned streams, loaded whole (they are small per-commit)."""

    def __init__(self, streams: Dict[str, list]):
        self.streams = streams
        lengths = {k: len(v) for k, v in streams.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"corpus streams disagree on length: {lengths}")
        self.num_commits = next(iter(lengths.values()))

    @classmethod
    def load(cls, data_dir: str) -> "Corpus":
        streams = {}
        for fname in CORPUS_FILES:
            with open(os.path.join(data_dir, fname)) as f:
                streams[fname.removesuffix(".json")] = json.load(f)
        return cls(streams)

    def save(self, data_dir: str) -> None:
        os.makedirs(data_dir, exist_ok=True)
        for fname in CORPUS_FILES:
            key = fname.removesuffix(".json")
            with open(os.path.join(data_dir, fname), "w") as f:
                json.dump(self.streams[key], f)

    def __len__(self) -> int:
        return self.num_commits

    def record(self, i: int) -> CommitRecord:
        s = self.streams
        return CommitRecord(
            diff_tokens=list(s["difftoken"][i]),
            diff_marks=list(s["diffmark"][i]),
            diff_atts=[list(a) for a in s["diffatt"][i]],
            msg_tokens=list(s["msg"][i]),
            var_map=dict(s["variable"][i]),
            ast_labels=list(s["ast"][i]),
            change_labels=list(s["change"][i]),
            edge_ast=[tuple(e) for e in s["edge_ast"][i]],
            edge_ast_code=[tuple(e) for e in s["edge_ast_code"][i]],
            edge_change_ast=[tuple(e) for e in s["edge_change_ast"][i]],
            edge_change_code=[tuple(e) for e in s["edge_change_code"][i]],
        )

    def records(self) -> Iterator[CommitRecord]:
        for i in range(self.num_commits):
            yield self.record(i)
