"""Bucketed-geometry compilation + length-aware batch packing.

Every batch used to be padded to the worst-case geometry — the full
``ast_change_len`` node tail, ``max_edges`` COO slots, ``tar_len`` message
positions — yet the corpus is dominated by small commits, so most device
FLOPs multiplied pad zeros (the reference pays the same tax with dense
per-sample adjacencies, Dataset.py:336-343). This module declares a SMALL
FIXED FAMILY of padding geometries ("buckets"), assigns each sample to the
smallest admissible bucket, and packs same-bucket samples into batches, so
XLA compiles one program per bucket (N programs total, pre-warmed once at
startup — still ZERO post-warmup retraces, the PR-1 invariant).

Which axes are bucketable
-------------------------
A bucket is ``(ast_len, max_edges, tar_len)``:

- ``ast_len``  truncates the AST+change node region — the only node region
  that CAN shrink: ``sou_len`` and ``sub_token_len`` are baked into the
  copy-label id space (``vocab + diff_pos`` / ``vocab + sou_len + sub_pos``,
  graph_build.copy_labels) and into the fused output width, so shrinking
  them would re-key the supervision. Truncating the ast tail is exact for
  every real node: pad ast nodes only ever connect to themselves (the
  reference's unconditional self-loops, Dataset.py:271-275), so dropping
  them removes zero-contribution rows/columns of the adjacency.
- ``max_edges``  shrinks the COO pad; pad edges scatter exact zeros, so
  fewer of them change nothing.
- ``tar_len``  truncates decoder positions past the sample's message; the
  loss masks them to exactly zero and causal attention keeps real-position
  outputs bit-identical. Decode does NOT bucket this axis (the model
  decides the output length, which must not be clipped): decode buckets
  are ``(ast_len, max_edges, full tar_len)``.

The edge/node coupling: ``build_adjacency`` appends one self-loop per node
of the FULL geometry, ascending, AFTER all family edges — so the edges of
the truncated node tail are exactly the LAST ``graph_len - bucket_graph_len``
entries of each sample's ragged edge slice, and ``make_batch`` drops them
by shortening the slice (data/batching.py, ``geom=``). Bit-exactness of
loss and decoded tokens at bucket geometry vs full pad is pinned by
tests/test_buckets.py.

Determinism contract (extends the PR-2 feeder contract)
-------------------------------------------------------
The packed batch order is a pure function of ``(seed, epoch, bucket
table)``: the packer starts from the SAME permutation
``data.batching.epoch_order`` draws, walks it greedily appending each
sample to its bucket's open chunk, and emits a chunk the moment it fills
(tails flush in table order). With ``shuffle=False`` (dev/decode) packing
is a stable partition by bucket — sort-by-length packing that preserves
in-bucket corpus order; drivers restore output order from the
``_positions`` host-only field each batch carries. ``cfg.buckets = ()``
bypasses this module entirely: the single-geometry path is byte-identical
to before.

Sanitizer / firacheck interplay: see docs/BUCKETING.md. Each bucket's
programs get their own compile-guard label (``train_step[a16.e256.t8]``),
drivers pre-warm and then ``CompileGuard.declare`` the family, and a
dispatch outside the declared family raises — geometry drift is a
machine-enforced non-event, not a recompile storm.

Composition with the grouped device programs (fused_steps / accum_steps)
lives in data/grouping.py: its scheduler walks the same permutation,
reuses this module's table/assignment/extents machinery, and packs
bucket-HOMOGENEOUS K-groups so the padding win and the dispatch-
amortization win stack. ``packed_plan`` below stays the dev/decode packer
(stable partition, sort-by-length) and the ``group_size == 1`` reference
the grouped plan is pinned equal to.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.dataset import ProcessedSplit


class BucketGeom(NamedTuple):
    """One padding geometry: the bucketable axes of a batch."""

    ast_len: int     # AST+change node region length (<= cfg.ast_change_len)
    max_edges: int   # per-sample COO pad length (<= cfg.max_edges)
    tar_len: int     # message positions (<= cfg.tar_len)


def geom_tag(geom: BucketGeom) -> str:
    """Stable label fragment for guard labels / reports: 'a16.e256.t8'."""
    return f"a{geom.ast_len}.e{geom.max_edges}.t{geom.tar_len}"


def full_geom(cfg: FiraConfig) -> BucketGeom:
    return BucketGeom(cfg.ast_change_len, cfg.max_edges, cfg.tar_len)


def geom_cost(cfg: FiraConfig, geom: BucketGeom) -> float:
    """Per-sample FLOP proxy at a geometry — the packer's and the padding
    metric's unit of account. Mirrors the geometry-dependent MXU terms of
    bench._analytic_flops (GCN fc + dense A.x, decoder attention/FFN,
    fused head) plus a small per-edge scatter term; constant terms
    (Combination, source-side projections) are included so padding
    fractions are not overstated."""
    d, L = cfg.embedding_dim, cfg.num_layers
    s = cfg.sou_len + cfg.sub_token_len          # copy span: not bucketable
    g = s + geom.ast_len                          # bucketed node count
    t = geom.tar_len
    v = cfg.vocab_size + s
    enc = L * (2 * g * g * d                      # dense A.x bmm
               + 2 * g * d * d * 2                # GCN fc1/fc2
               + 4 * cfg.sou_len * d * d * 2)     # Combination projections
    dec = L * ((6 * t + 2 * s) * d * d * 2
               + 2 * (t * t + t * s) * d * 2
               + 2 * t * d * cfg.ffn_mult * d * 2)
    head = (t * d * v * 2 + s * d * d * 2 + t * d * d * 2 + t * s * d * 2)
    return float(enc + dec + head + 8.0 * geom.max_edges)


def _validated(cfg: FiraConfig, geom: BucketGeom) -> BucketGeom:
    full = full_geom(cfg)
    g = BucketGeom(*(int(x) for x in geom))  # firacheck: allow[HOST-SYNC] config ints from the declared bucket table; no device value exists in the packer
    if not (1 <= g.ast_len <= full.ast_len):
        raise ValueError(f"bucket ast_len {g.ast_len} outside "
                         f"[1, {full.ast_len}]")
    if not (1 <= g.tar_len <= full.tar_len):
        raise ValueError(f"bucket tar_len {g.tar_len} outside "
                         f"[1, {full.tar_len}]")
    min_edges = cfg.sou_len + cfg.sub_token_len + g.ast_len
    if not (min_edges <= g.max_edges <= full.max_edges):
        # every sample carries one self-loop per node of its geometry, so a
        # bucket with fewer edge slots than nodes can never admit anything
        raise ValueError(
            f"bucket max_edges {g.max_edges} outside "
            f"[{min_edges} (= nodes at ast_len {g.ast_len}, the self-loop "
            f"floor), {full.max_edges}]")
    return g


def bucket_table(cfg: FiraConfig) -> Tuple[BucketGeom, ...]:
    """The effective bucket family: cfg.buckets validated, sorted by FLOP
    cost ascending, with the full geometry appended as the always-admissible
    fallback. ``cfg.buckets = ()`` yields just the full geometry."""
    full = full_geom(cfg)
    geoms = []
    for entry in cfg.buckets:
        g = _validated(cfg, BucketGeom(*entry))
        if g != full and g not in geoms:
            geoms.append(g)
    geoms.sort(key=lambda g: geom_cost(cfg, g))
    return tuple(geoms) + (full,)


# --------------------------------------------------------------------------
# per-sample extents + admissibility
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SampleExtents:
    """Per-sample used lengths along the bucketable axes (full-geometry
    edge counts; use :meth:`edges_at` for a truncated node region)."""

    ast: np.ndarray    # used AST+change nodes (labels OR family edges)
    edges: np.ndarray  # ragged edge count at FULL geometry (incl. all
                       # self-loops; the truncated tail subtracts off)
    msg: np.ndarray    # used msg/msg_tar positions (START..EOS inclusive)
    ast_change_len: int  # the full region length the counts were taken at

    def edges_at(self, ast_len: int) -> np.ndarray:
        """Edge counts once the node tail is truncated to ``ast_len``: the
        dropped pad nodes carried exactly one self-loop each."""
        return self.edges - (self.ast_change_len - ast_len)

    def admissible(self, geom: BucketGeom, *, use_msg: bool = True
                   ) -> np.ndarray:
        ok = (self.ast <= geom.ast_len) \
            & (self.edges_at(geom.ast_len) <= geom.max_edges)
        if use_msg:
            ok = ok & (self.msg <= geom.tar_len)
        return ok


def _last_nonzero_extent(a: np.ndarray) -> np.ndarray:
    """Per-row index-past-last-nonzero (0 for all-zero rows)."""
    nz = a != 0
    return np.where(nz.any(axis=1),
                    a.shape[1] - np.argmax(nz[:, ::-1], axis=1), 0)


def sample_extents(split: ProcessedSplit, cfg: FiraConfig) -> SampleExtents:
    from fira_tpu.data.graph_build import EDGE_KIND_SELF_LOOP

    arr = split.arrays
    n = len(split)
    offsets = arr["edge_offsets"]
    counts = np.diff(offsets).astype(np.int64)

    # used ast nodes: nonzero labels, cross-checked against where family
    # (non-self-loop) edges actually point — belt and braces, both are
    # supposed to agree for graph_build output
    ast_ext = _last_nonzero_extent(arr["ast_change"]).astype(np.int64)
    ast_base = cfg.sou_len + cfg.sub_token_len
    hi_node = np.maximum(arr["edge_senders"], arr["edge_receivers"]
                         ).astype(np.int64)
    fam = (arr["edge_kinds"] != EDGE_KIND_SELF_LOOP) & (hi_node >= ast_base)
    if fam.any():
        owner = np.repeat(np.arange(n), counts)
        edge_ext = np.zeros(n, dtype=np.int64)
        np.maximum.at(edge_ext, owner[fam], hi_node[fam] - ast_base + 1)
        ast_ext = np.maximum(ast_ext, edge_ext)

    msg_ext = np.maximum(_last_nonzero_extent(arr["msg"]),
                         _last_nonzero_extent(arr["msg_tar"])).astype(np.int64)
    return SampleExtents(ast=ast_ext, edges=counts, msg=msg_ext,
                         ast_change_len=cfg.ast_change_len)


def assign_buckets(extents: SampleExtents, table: Sequence[BucketGeom], *,
                   use_msg: bool = True) -> np.ndarray:
    """Smallest admissible bucket per sample (table sorted cost-ascending;
    the trailing full geometry admits everything)."""
    n = len(extents.ast)
    out = np.full(n, len(table) - 1, dtype=np.int64)
    unassigned = np.ones(n, dtype=bool)
    for b, geom in enumerate(table[:-1]):
        fit = unassigned & extents.admissible(geom, use_msg=use_msg)
        out[fit] = b
        unassigned &= ~fit
    return out


def _round_up(x: int, unit: int) -> int:
    return ((int(x) + unit - 1) // unit) * unit  # firacheck: allow[HOST-SYNC] host numpy quantile scalar; the packer never holds device values


def choose_buckets(split: ProcessedSplit, cfg: FiraConfig,
                   n_buckets: int = 3) -> Tuple[Tuple[int, int, int], ...]:
    """Bucket table from the split's length histograms: per-axis quantiles
    at evenly spaced levels, rounded up to lane-friendly units (ast -> 8,
    edges -> 64, msg -> 4) and capped at the full geometry. Deterministic
    for a given split. The returned tuples go into ``cfg.buckets``; the
    full geometry stays the implicit fallback and is never declared."""
    if n_buckets < 1:
        raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
    ext = sample_extents(split, cfg)
    full = full_geom(cfg)
    out: List[Tuple[int, int, int]] = []
    for i in range(n_buckets):
        q = (i + 1) / n_buckets
        ast = min(full.ast_len,
                  max(1, _round_up(np.quantile(ext.ast, q), 8)))
        tar = min(full.tar_len,
                  max(2, _round_up(np.quantile(ext.msg, q), 4)))
        edges = min(full.max_edges,
                    _round_up(np.quantile(ext.edges_at(ast), q), 64))
        edges = max(edges, cfg.sou_len + cfg.sub_token_len + ast)
        geom = (ast, edges, tar)
        if geom != tuple(full) and geom not in out:
            out.append(geom)
    return tuple(out)


# --------------------------------------------------------------------------
# packing
# --------------------------------------------------------------------------

Plan = List[Tuple[np.ndarray, BucketGeom]]


def packed_plan(split: ProcessedSplit, cfg: FiraConfig, *,
                batch_size: Optional[int] = None,
                shuffle: bool = False,
                seed: int = 0,
                epoch: int = 0,
                table: Optional[Sequence[BucketGeom]] = None,
                extents: Optional[SampleExtents] = None,
                assignment: Optional[np.ndarray] = None,
                use_msg: bool = True) -> Plan:
    """The deterministic bucketed batch order of one epoch: a list of
    (index chunk, bucket geometry) pairs.

    shuffle=True (train): the exact ``epoch_order(seed, epoch)``
    permutation is walked greedily — each sample joins its bucket's open
    chunk, which is emitted the moment it fills; tails flush in table
    order. shuffle=False (dev/decode): a stable partition by bucket
    (in-bucket corpus order preserved) — sort-by-length packing.
    """
    from fira_tpu.data.batching import epoch_order

    bs = batch_size or cfg.batch_size
    table = tuple(table) if table is not None else bucket_table(cfg)
    if assignment is None:
        extents = extents or sample_extents(split, cfg)
        assignment = assign_buckets(extents, table, use_msg=use_msg)
    order = epoch_order(len(split), shuffle=shuffle, seed=seed, epoch=epoch)

    plan: Plan = []
    if shuffle:
        open_chunks: List[List[int]] = [[] for _ in table]
        for i in order:
            b = int(assignment[i])  # firacheck: allow[HOST-SYNC] host numpy assignment array — the packer runs on host index data only, never device values
            open_chunks[b].append(int(i))  # firacheck: allow[HOST-SYNC] host numpy permutation entry, same packer-side data
            if len(open_chunks[b]) == bs:
                plan.append((np.asarray(open_chunks[b]), table[b]))  # firacheck: allow[HOST-SYNC] list-of-host-ints to numpy chunk; no device round-trip
                open_chunks[b] = []
        for b, chunk in enumerate(open_chunks):
            if chunk:
                plan.append((np.asarray(chunk), table[b]))  # firacheck: allow[HOST-SYNC] same host-side tail flush as above
        return plan
    for b, geom in enumerate(table):
        members = order[assignment[order] == b]
        for start in range(0, len(members), bs):
            plan.append((members[start : start + bs], geom))
    return plan


def bucketed_assembly_tasks(split: ProcessedSplit, plan: Plan,
                            cfg: FiraConfig, *,
                            batch_size: Optional[int] = None,
                            stamp=None) -> Iterator:
    """One ``make_batch(geom=...)`` task per plan entry, for the async
    Feeder. Each batch carries two HOST-ONLY fields (stripped before
    device_put, data/feeder.py): ``_positions`` — the split-local sample
    index per row (-1 on pad rows), so drivers can restore corpus output
    order after packing reordered the stream — and ``_tag`` — the bucket's
    geometry tag for per-bucket compile-guard labels.

    ``stamp``: optional post-assembly hook run WORKER-side, like
    feeder.assembly_tasks' — the decode drivers pass
    decode.prefix_cache.stamp_digests under ``cfg.prefix_cache`` so
    content digests never hash on the scheduler thread."""
    from fira_tpu.data.batching import make_batch

    bs = batch_size or cfg.batch_size

    def task(chunk: np.ndarray, geom: BucketGeom):
        def build():
            batch = make_batch(split, chunk, cfg, batch_size=bs, geom=geom)
            positions = np.full(bs, -1, dtype=np.int64)
            positions[: len(chunk)] = chunk
            batch["_positions"] = positions
            batch["_tag"] = geom_tag(geom)
            return stamp(batch) if stamp is not None else batch
        # a failing worker's FeederTaskError names the poisoned chunk:
        # split positions + bucket geometry (data/feeder.task_note)
        from fira_tpu.data.feeder import task_note

        build.note = task_note(chunk, geom_tag=geom_tag(geom),
                               site="bucketed_assembly_tasks")
        return build

    for chunk, geom in plan:
        yield task(chunk, geom)


# --------------------------------------------------------------------------
# program-family warmup
# --------------------------------------------------------------------------

def decode_table(cfg: FiraConfig) -> Tuple[BucketGeom, ...]:
    """The decode-side bucket family, deduplicated, cost-sorted, full
    fallback last.

    Default (``cfg.decode_tar_buckets = False``): tar_len pinned to the
    FULL value on every bucket — beam output length is model-decided and
    must not be clipped.

    ``decode_tar_buckets = True`` (the longer-target-geometry mode,
    docs/DECODE_ENGINE.md "Paged KV arena"): each declared bucket KEEPS
    its own tar_len, assignment goes by reference-message extent
    (``use_msg=True`` — the caller's packing must match), and the slot
    engine caps each sample's generation at its bucket's tar budget,
    which is exactly the paged-KV block reservation the slot is seated
    with. This turns a raised ``cfg.tar_len`` (say 64) plus a
    common-case bucket (say tar 30) into two RESERVATION sizes against
    one block pool and ONE step program — not a per-length program or
    arena explosion. The batched-beam path ignores the cap (its scan is
    always the full budget), so tar-bucketed decode is equivalence-
    claimed only within the engine family (file-byte determinism across
    schedules is pinned by tests/test_buckets.py)."""
    full = full_geom(cfg)
    geoms: List[BucketGeom] = []
    for g in bucket_table(cfg)[:-1]:
        d = (g if cfg.decode_tar_buckets
             else BucketGeom(g.ast_len, g.max_edges, cfg.tar_len))
        if d != full and d not in geoms:
            geoms.append(d)
    geoms.sort(key=lambda g: geom_cost(cfg, g))
    return tuple(geoms) + (full,)


def warmup_batch(split: ProcessedSplit, cfg: FiraConfig, geom: BucketGeom,
                 batch_size: int):
    """An all-pad batch at one bucket geometry — the compile key for that
    bucket's program, with zero training effect (every row is invalid; the
    loss divides by max(count, 1))."""
    from fira_tpu.data.batching import make_batch

    return make_batch(split, np.arange(0), cfg, batch_size=batch_size,
                      geom=geom)


# --------------------------------------------------------------------------
# padding / wasted-FLOP metric
# --------------------------------------------------------------------------

def padding_report(split: ProcessedSplit, cfg: FiraConfig,
                   table: Optional[Sequence[BucketGeom]] = None, *,
                   use_msg: bool = True) -> Dict:
    """Corpus-level padded-FLOP accounting, single-geometry vs bucketed.

    ``padding_frac`` = 1 - (sum of per-sample ideal cost at the sample's
    own extents) / (sum of cost at the geometry actually dispatched) —
    the share of device FLOPs spent multiplying pad. Per-bucket rows ride
    along so the table's coverage is auditable."""
    table = tuple(table) if table is not None else bucket_table(cfg)
    ext = sample_extents(split, cfg)
    assignment = assign_buckets(ext, table, use_msg=use_msg)
    # scalar per-sample arithmetic: edges at the sample's own ast extent is
    # just its count minus its truncated self-loop tail (calling edges_at
    # per sample would rebuild a full length-n array each iteration)
    ideal = np.asarray([
        geom_cost(cfg, BucketGeom(
            int(ext.ast[i]),
            int(ext.edges[i]) - (ext.ast_change_len - int(ext.ast[i])),
            max(2, int(ext.msg[i]))))
        for i in range(len(split))
    ])
    full_cost = geom_cost(cfg, full_geom(cfg))
    bucket_costs = np.asarray([geom_cost(cfg, g) for g in table])
    assigned = bucket_costs[assignment]
    per_bucket = []
    for b, geom in enumerate(table):
        members = assignment == b
        n = int(members.sum())
        row = {"geom": geom_tag(geom), "n": n}
        if n:
            row["padding_frac"] = round(
                1.0 - float(ideal[members].sum())
                / float(assigned[members].sum()), 4)
        per_bucket.append(row)
    return {
        "n_samples": len(split),
        "padding_frac_single": round(
            1.0 - float(ideal.sum()) / (full_cost * len(split)), 4),
        "padding_frac_bucketed": round(
            1.0 - float(ideal.sum()) / float(assigned.sum()), 4),
        "flops_ratio_bucketed_vs_single": round(
            float(assigned.sum()) / (full_cost * len(split)), 4),
        "buckets": per_bucket,
    }
