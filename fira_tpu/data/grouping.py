"""Bucket-homogeneous grouped dispatch: the one epoch scheduler.

The two biggest shipped throughput wins used to be mutually exclusive: the
production knob set runs a ``fused_steps=8`` device loop (docs/PERF.md) and
the reference-dynamics config accumulates ``accum_steps=4`` micro-batches
(config.py), but both stack K batches on a leading axis — and the bucket
packer (data/buckets.py) emits batches of MIXED geometry, which cannot
stack. This module closes the gap: after bucket assignment over the same
``epoch_order`` permutation, runs of K (fused) or A (accum) SAME-geometry
batches pack into one dispatch group, so the padding win (padding_frac
0.264 -> 0.086, docs/BUCKET_BENCH.jsonl) and the dispatch-amortization win
(68.75 ms/step stacked row, docs/PERF.md) compose instead of competing —
the standard NMT/Transformer recipe (length-bucketed batching + multi-step
device loops; PAPERS.md).

One plan shape subsumes every train epoch:

- ``group_size == 1``: per-step dispatch. With a bucket table this is
  EXACTLY ``buckets.packed_plan(shuffle=True)`` (same greedy walk, same
  tail flush); with ``cfg.buckets = ()`` it degenerates to the sequential
  ``epoch_index_chunks`` slicing — both byte-identical to the pre-grouping
  paths (pinned by tests/test_grouping.py).
- ``group_size > 1``, fused: each bucket's chunks collect until K are
  ready, then emit as ONE :class:`GroupEntry` the moment the K-th fills
  (deterministic in the walk); leftovers smaller than K fall back to
  per-step dispatch — the fused-tail rule, now per bucket.
- ``group_size > 1``, accum: tails pad to A with all-invalid micro-batches
  (zero rows contribute nothing to the global (sum, count) — the same
  machinery as the pre-bucket accum tail), so accumulation is always ONE
  A-stacked dispatch and the per-step program is never needed.

Determinism contract (extends the buckets/feeder contracts)
-----------------------------------------------------------
The plan is a pure function of ``(seed, epoch, bucket table, group size,
accum)``. Chunk FORMATION depends only on the permutation walk — the
sample->chunk assignment is identical for every group size; grouping only
packages chunks into dispatches. The feeder preserves task order for any
worker count, so the delivered sample stream is identical across worker
counts too (all pinned by tests/test_grouping.py).

Correctness bar: a grouped dispatch is the same ``train_step`` body run K
times by ``lax.scan`` (train/step.py), and each member batch is assembled
by the same ``make_batch(geom=...)`` the per-step bucketed path uses — so
grouped-bucketed training reproduces per-step bucketed dispatch of the
same chunk stream (params + per-step losses), which is already bit-exact
against full pad (tests/test_buckets.py).

Sanitizer interplay: each grouped program is one member of the (geometry x
entrypoint x group-size) family — labels via
``analysis.sanitizer.program_label`` (``grouped_step[a16.e256.t8.g8]``),
pre-warmed and declared by train/loop.py, so an undeclared (geom, K)
program still raises at the dispatch that produced it.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.buckets import (BucketGeom, assign_buckets, bucket_table,
                                   geom_cost, geom_tag, sample_extents)
from fira_tpu.data.dataset import ProcessedSplit


class GroupEntry(NamedTuple):
    """One dispatch of an epoch plan.

    ``pad_to == 1``: a per-step dispatch of ``chunks[0]`` (exactly one
    chunk). ``pad_to > 1``: a stacked dispatch — ``chunks`` (all the same
    geometry, each a full or tail index chunk) stack on a leading axis;
    when ``len(chunks) < pad_to`` (accum tails) the assembly pads with
    all-invalid micro-batches up to ``pad_to``.
    """

    chunks: tuple          # of np.ndarray index chunks, len >= 1
    geom: BucketGeom
    pad_to: int


Plan = List[GroupEntry]


def grouped_plan(split: ProcessedSplit, cfg: FiraConfig, *,
                 batch_size: Optional[int] = None,
                 group_size: int = 1,
                 accum: bool = False,
                 shuffle: bool = False,
                 seed: int = 0,
                 epoch: int = 0,
                 table: Optional[Sequence[BucketGeom]] = None,
                 extents=None,
                 assignment: Optional[np.ndarray] = None,
                 use_msg: bool = True) -> Plan:
    """The deterministic grouped batch order of one train epoch.

    Walks the exact ``epoch_order(seed, epoch)`` permutation (the single
    order source every packing strategy chunks from), appending each sample
    to its bucket's open chunk; a chunk joins its bucket's pending group
    when it fills, and a group dispatches the moment its ``group_size``-th
    chunk lands. Tails flush in table order: fused leftovers (< group_size
    chunks, plus each bucket's partial chunk) emit per-step; with
    ``accum=True`` they emit as one short group the assembly pads to
    ``group_size`` with all-invalid micro-batches.
    """
    from fira_tpu.data.batching import epoch_order

    if group_size < 1:
        raise ValueError(f"group_size must be >= 1, got {group_size}")
    bs = batch_size or cfg.batch_size
    table = tuple(table) if table is not None else bucket_table(cfg)
    if assignment is None:
        if len(table) == 1:  # single geometry: everything is the fallback
            assignment = np.zeros(len(split), dtype=np.int64)
        else:
            extents = extents or sample_extents(split, cfg)
            assignment = assign_buckets(extents, table, use_msg=use_msg)
    order = epoch_order(len(split), shuffle=shuffle, seed=seed, epoch=epoch)

    plan: Plan = []
    open_rows: List[List[int]] = [[] for _ in table]
    pending: List[List[np.ndarray]] = [[] for _ in table]
    for i in order:
        b = int(assignment[i])  # firacheck: allow[HOST-SYNC] host numpy assignment array — the scheduler runs on host index data only, never device values
        open_rows[b].append(int(i))  # firacheck: allow[HOST-SYNC] host numpy permutation entry, same scheduler-side data
        if len(open_rows[b]) < bs:
            continue
        pending[b].append(np.asarray(open_rows[b]))  # firacheck: allow[HOST-SYNC] list-of-host-ints to numpy chunk; no device round-trip
        open_rows[b] = []
        if group_size == 1:
            plan.append(GroupEntry((pending[b].pop(),), table[b], 1))
        elif len(pending[b]) == group_size:
            plan.append(GroupEntry(tuple(pending[b]), table[b], group_size))
            pending[b] = []
    for b, geom in enumerate(table):
        if open_rows[b]:
            pending[b].append(np.asarray(open_rows[b]))  # firacheck: allow[HOST-SYNC] same host-side tail flush as above
        if not pending[b]:
            continue
        if group_size > 1 and accum:
            # accum tail: ONE short group, padded to the stacked shape with
            # all-invalid micro-batches at assembly time
            plan.append(GroupEntry(tuple(pending[b]), geom, group_size))
        else:
            # fused tail (or per-step mode): leftover chunks run per-step
            plan.extend(GroupEntry((c,), geom, 1) for c in pending[b])
        pending[b] = []
    return plan


def stack_group(batches: Sequence[Dict[str, np.ndarray]], *,
                pad_to: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Stack same-geometry host batches on a new leading axis; with
    ``pad_to`` larger than the group, pad with all-zero micro-batches
    (every row invalid, label 0 everywhere — they contribute nothing to the
    accumulated (sum, count), the same mechanism that makes make_batch's
    pad rows free). Identical layout to ``train.step.stack_batches``."""
    group = list(batches)
    if pad_to is not None and len(group) < pad_to:
        pad = {k: np.zeros_like(v) for k, v in group[0].items()}
        group.extend([pad] * (pad_to - len(group)))
    return {k: np.stack([b[k] for b in group]) for k in group[0]}


def grouped_assembly_tasks(split: ProcessedSplit, plan: Plan,
                           cfg: FiraConfig, *,
                           batch_size: Optional[int] = None,
                           bucketed: bool = False) -> Iterator:
    """One zero-arg assembly task per plan entry for the async Feeder
    (data/feeder.py): a per-step entry builds one ``make_batch`` batch; a
    stacked entry builds its member batches AND stacks them, so the worker
    ``device_put``s the whole K-group as ONE transfer.

    ``bucketed=False`` (``cfg.buckets = ()``): batches build at the full
    geometry with no host-only fields — byte-identical to the pre-grouping
    stream. ``bucketed=True``: each batch builds at its entry's geometry
    and carries the host-only ``_tag`` (geometry tag, for per-bucket guard
    labels; per-step entries also carry ``_positions`` like
    ``buckets.bucketed_assembly_tasks``)."""
    from fira_tpu.data.batching import make_batch

    bs = batch_size or cfg.batch_size

    def task(entry: GroupEntry):
        geom = entry.geom if bucketed else None

        def build():
            group = [make_batch(split, c, cfg, batch_size=bs, geom=geom)
                     for c in entry.chunks]
            if entry.pad_to == 1:
                batch = group[0]
                if bucketed:
                    chunk = entry.chunks[0]
                    positions = np.full(bs, -1, dtype=np.int64)
                    positions[: len(chunk)] = chunk
                    batch["_positions"] = positions
                    batch["_tag"] = geom_tag(entry.geom)
                return batch
            batch = stack_group(group, pad_to=entry.pad_to)
            if bucketed:
                batch["_tag"] = geom_tag(entry.geom)
            return batch
        return build

    for entry in plan:
        yield task(entry)


def plan_report(split: ProcessedSplit, cfg: FiraConfig, plan: Plan, *,
                batch_size: Optional[int] = None,
                extents=None) -> Dict:
    """Dispatch-count + padded-FLOP accounting for one epoch plan — the
    numbers bench.py's composed leg reports on every record.

    ``padding_frac_dispatched`` extends ``buckets.padding_report`` to the
    ACTUAL dispatched stream: the denominator prices every dispatched row —
    bucket pad inside chunks, invalid pad rows of partial chunks, and the
    all-invalid accum pad micro-batches — at its dispatch geometry."""
    bs = batch_size or cfg.batch_size
    ext = extents or sample_extents(split, cfg)
    ideal = 0.0
    dispatched = 0.0
    n_commits = 0
    n_grouped = n_per_step = steps = real_batches = 0
    for entry in plan:
        cost = geom_cost(cfg, entry.geom)
        k = max(1, entry.pad_to)
        dispatched += k * bs * cost
        steps += k
        real_batches += len(entry.chunks)
        if entry.pad_to > 1:
            n_grouped += 1
        else:
            n_per_step += 1
        for chunk in entry.chunks:
            n_commits += len(chunk)
            for i in chunk:
                i = int(i)  # firacheck: allow[HOST-SYNC] host numpy index chunk; the accounting never holds device values
                ideal += geom_cost(cfg, BucketGeom(
                    int(ext.ast[i]),  # firacheck: allow[HOST-SYNC] SampleExtents are host numpy arrays (data/buckets.sample_extents); no device value exists in the accounting
                    int(ext.edges[i]) - (ext.ast_change_len - int(ext.ast[i])),  # firacheck: allow[HOST-SYNC] same host-side extents arithmetic
                    max(2, int(ext.msg[i]))))  # firacheck: allow[HOST-SYNC] same host-side extents arithmetic
    return {
        "dispatches": len(plan),
        "grouped_dispatches": n_grouped,
        "per_step_dispatches": n_per_step,
        "steps_dispatched": steps,
        "real_batches": real_batches,
        "commits": n_commits,
        "padding_frac_dispatched": round(
            1.0 - ideal / dispatched, 4) if dispatched else 0.0,
    }
