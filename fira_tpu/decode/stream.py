"""Ordered, crash-resilient streaming output for decode drivers.

The bucketed packer (data/buckets.py) and the slot-refill engine
(decode/engine.py) both emit predictions OUT of split order — the packer
reorders the batch stream, the engine harvests whichever slot settles
first. Output files, however, are one plain line per sample in split
order (the reference's OUTPUT/output_fira contract).

:class:`OrderedStreamWriter` restores order ON THE WAY to disk instead of
buffering the whole run in memory and writing the ordered file only at
completion (the pre-engine bucketed path): lines arrive keyed by split
position, and the contiguous prefix from position 0 streams to
``<path>.partial`` the moment it completes — a byte-exact, parseable
PREFIX of the final file, every flushed line a finished prediction in
its final place. Lines above a gap wait in memory for the ordered file
AND spill position-tagged (``pos\\tline``) to ``<path>.partial.tail`` the
moment they are added, so a crash costs NOTHING that was decoded: the
plain prefix plus the tagged tail together hold every finished line
(the tagged-tail recovery contract of the old bucketed stream, now
layered on top of the plain prefix instead of replacing it).
``close()`` renames ``.partial`` to the final path atomically and
removes the tail spill, exactly like the historical plain streaming
path. ``pending`` exposes the above-gap count for observability.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


class OrderedStreamWriter:
    """Position-keyed streaming writer with atomic completion.

    Use as a context manager: on a clean exit the partial file is renamed
    to ``path``; on an exception both the plain prefix (``.partial``) and
    the tagged above-gap spill (``.partial.tail``) are LEFT on disk as
    the crash-recovery pair (never renamed, never deleted).
    """

    def __init__(self, path: str, *, start: int = 0,
                 expected: Optional[int] = None):
        """``expected``: total line count the completed file must have —
        close() refuses to rename a silently truncated file (a tail-of-
        split sample that was never decoded leaves no interior gap, so
        the gap check alone cannot see it)."""
        self.path = path
        self.partial_path = path + ".partial"
        self.tail_path = path + ".partial.tail"
        self.expected = expected
        self._pending: Dict[int, str] = {}
        self._next = start
        self._written = 0
        self._closed = False
        self._aborted = False
        # line-buffered: the crash contract promises every ADDED line is
        # on disk, not parked in a userspace stdio buffer until the next
        # periodic flush — a hard kill (OOM, SIGKILL) must not eat
        # decoded predictions. Output files are a few thousand lines; a
        # write syscall per line is noise next to a beam step.
        self._f = open(self.partial_path, "w", buffering=1)
        self._tail_f: Optional = None  # opened lazily on the first gap

    def add(self, pos: int, line: str) -> None:
        """Stage ``line`` at split position ``pos``; flush the contiguous
        prefix, spill anything above a gap to the tagged tail. Each
        position must be added exactly once."""
        if self._closed:
            raise ValueError("writer is closed")
        if pos < self._next or pos in self._pending:
            raise ValueError(f"duplicate output position {pos}")
        if pos == self._next:
            self._f.write(line)
            self._next += 1
            self._written += 1
        else:
            # above a gap: held for the ordered file, AND on disk tagged —
            # a crash must not cost a finished prediction
            self._pending[pos] = line
            if self._tail_f is None:
                self._tail_f = open(self.tail_path, "w", buffering=1)
            self._tail_f.write(f"{pos}\t{line}")
        while self._next in self._pending:
            self._f.write(self._pending.pop(self._next))
            self._next += 1
            self._written += 1

    @property
    def written(self) -> int:
        """Lines flushed to the plain prefix (its parseable length)."""
        return self._written

    @property
    def pending(self) -> int:
        """Lines held above a gap (all of them also in the tagged tail)."""
        return len(self._pending)

    def flush(self) -> None:
        self._f.flush()
        if self._tail_f is not None:
            self._tail_f.flush()

    def close(self) -> str:
        """Complete the file: requires no gaps (every position below the
        high-water mark added), then atomically renames partial -> final
        and removes the tail spill. Raises if the writer was aborted —
        the final file was never produced, only the recovery pair."""
        if self._aborted:
            raise RuntimeError(
                f"writer was aborted — {self.path} was never produced; "
                f"the flushed prefix is at {self.partial_path}")
        if self._closed:
            return self.path
        if self._pending:
            self.abort()  # leave the prefix + tagged tail for post-mortem
            raise RuntimeError(
                f"{len(self._pending)} line(s) stranded above a gap at "
                f"position {self._next} — a sample was never decoded; the "
                f"flushed prefix is preserved at {self.partial_path} and "
                f"the stranded lines, position-tagged, at {self.tail_path}")
        if self.expected is not None and self._written != self.expected:
            self.abort()  # suffix truncation: no gap, but samples missing
            raise RuntimeError(
                f"only {self._written} of {self.expected} expected lines "
                f"were written — trailing sample(s) were never decoded; "
                f"the flushed prefix is preserved at {self.partial_path}")
        self._f.close()
        if self._tail_f is not None:
            self._tail_f.close()
            os.remove(self.tail_path)
        self._closed = True
        os.replace(self.partial_path, self.path)
        return self.path

    def abort(self) -> None:
        """Stop writing, LEAVING the plain prefix and the tagged tail on
        disk (the crash contract: everything decoded stays recoverable)."""
        if not self._closed:
            self._f.close()
            if self._tail_f is not None:
                self._tail_f.close()
            self._closed = True
            self._aborted = True

    def __enter__(self) -> "OrderedStreamWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            self.abort()
