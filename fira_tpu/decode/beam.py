"""Jitted batched beam search.

The reference's decoder loop (/root/reference/run_model.py:187-380) is pure
Python: per step x per beam it re-runs the full decoder on the padded
prefix, fuses gen+copy probabilities, multiplies by the running beam
probability (PROBABILITIES, not log-probs, :271), appends finished-beam
sentinel probabilities (:281-298), takes one global top-k (:305-310), and
resolves copy ids to source token ids at beam-extension time (:334-337).

This rebuild runs the whole thing as ONE compiled program: beams fold into
the batch dim, `lax.scan` drives the tar_len-1 steps, and top-k replaces the
sort. Two accumulation modes:

- compat (default, cfg.beam_compat_prob_space=True): probability-space
  accumulation with the reference's exact candidate construction —
  finished beams contribute a -1-masked distribution PLUS a sentinel entry
  carrying their probability, so selection order is bit-for-bit the
  reference's (needed for +-0.3 BLEU parity, SURVEY.md hard-part 2);
- log-space: the numerically sound default for long targets; identical
  argmax behavior until probabilities underflow.

Semantic note vs the reference: the reference skips a beam only when it is
finished for EVERY batch item (cal_beam, :229-247) and compacts the sentinel
list (:286-296); per item that yields exactly the candidate set built here
(active beams: dist x prob; finished beams: -1-mask + sentinel), so the
fixed-shape formulation selects the same beams without data-dependent
control flow. The reference's early loop exit (:276-279) defaults to
running all steps here — finished beams are fixed points of the update —
and comes back as cfg.beam_early_exit: a `lax.while_loop` that stops one
settling step after every beam finishes, bit-exact vs the full scan (see
:func:`_run_steps`).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.vocab import EOS_ID, START_ID
from fira_tpu.model.model import FiraModel


def _resolve_copy(tok, diff, sub_token, cfg: FiraConfig):
    """Copy-id -> source token id (run_model.py:334-337), vectorized.

    tok: (B, K) candidate ids over the fused output space;
    diff: (B, sou_len); sub_token: (B, sub_token_len).
    """
    V = cfg.vocab_size
    sub_pos = jnp.clip(tok - V - cfg.sou_len, 0, cfg.sub_token_len - 1)
    diff_pos = jnp.clip(tok - V, 0, cfg.sou_len - 1)
    from_sub = jnp.take_along_axis(sub_token, sub_pos, axis=1)
    from_diff = jnp.take_along_axis(diff, diff_pos, axis=1)
    return jnp.where(
        tok >= V + cfg.sou_len, from_sub,
        jnp.where(tok >= V, from_diff, tok),
    )


def step_valid_mask(flat, s, T: int):
    """Cached-decode per-position validity, shared by the batched beam and
    the slot engine's step program (decode/engine.py): real (nonzero)
    prefix tokens, position 0 (<start>) always attended, causally
    restricted to positions <= ``s``. ``s`` is a traced scalar (batch
    beam: every row at the same depth) or a (B,) vector (engine: each row
    at its slot's own depth) — identical per-row math either way, which is
    one leg of the engine's bit-exactness argument. The same mask guards
    the PAGED cache reads: positions a slot never wrote (stale pool
    blocks included) are exactly -1e9-masked, and exp(-1e9 - m)
    underflows to 0.0 in every stable softmax dtype, so unwritten block
    contents multiply a hard zero — the reason freed blocks are unmapped,
    never zeroed (tests/test_paged_kv.py pins it)."""
    base = (flat != 0).at[:, 0].set(True)
    s = jnp.asarray(s)
    lim = s[:, None] if s.ndim else s
    return base & (jnp.arange(T)[None, :] <= lim)


def top_beam_token(tokens, pos):
    """Top-beam token at per-row position ``pos`` — the emitted token of
    the step that just advanced row b to ``pos[b]`` (selection's top_k
    returns candidates prob-descending, so beam 0 IS the running best
    beam after every step). Shared by the slot engine's verify program
    (decode/spec.py): a drafted token is accepted exactly when it equals
    this value. tokens: (B, K, T); pos: (B,) int32 clamped by the caller
    to a legal column."""
    top = tokens[:, 0, :]
    return jnp.take_along_axis(top, pos[:, None], axis=1)[:, 0]


def scatter_token(flat, pos, tok):
    """Write ``tok[b]`` at row b's own column ``pos[b]`` — the per-row
    vector twin of :func:`_selection_tail`'s top-beam append, shared by
    the spec drafters (decode/spec.py) rolling a single-beam prefix
    forward. flat: (B, T) int32; pos/tok: (B,) int32."""
    return flat.at[jnp.arange(flat.shape[0]), pos].set(tok)


def _init_beam(B: int, cfg: FiraConfig):
    """Initial (tokens, probs, finished) carry + the masked/pad value."""
    K, T = cfg.beam_size, cfg.tar_len
    tokens0 = jnp.zeros((B, K, T), jnp.int32).at[:, :, 0].set(START_ID)
    if cfg.beam_compat_prob_space:
        # beam 0 prob 1, others 0 (run_model.py:216-221)
        probs0 = jnp.tile(jnp.asarray([1.0] + [0.0] * (K - 1), jnp.float32),
                          (B, 1))
        neg = jnp.float32(-1.0)  # reference's masked/-pad value (:273,294)
    else:
        probs0 = jnp.tile(
            jnp.asarray([0.0] + [-np.inf] * (K - 1), jnp.float32), (B, 1)
        )
        neg = jnp.float32(-np.inf)
    finished0 = jnp.zeros((B, K), bool)
    return tokens0, probs0, finished0, neg


def _selection_tail(cand, ids, tokens, probs, finished, s, batch,
                    cfg: FiraConfig, neg):
    """Shared selection tail for :func:`_select` and
    :func:`_select_factored`: mask finished beams, append their sentinel
    entries, one global top-k over K*W + K candidates, decode sentinels vs
    real candidates, write the chosen token at position s+1
    (run_model.py:267-310).

    cand: (B, K, W) candidate scores already in the selection space.
    ids: None when W is the fused output space itself (token id = index
    within the beam's W); else a (B, K, W) table of fused-space ids to
    gather the chosen token from (the factored path's per-side top-k
    candidates).

    ``s`` may be a scalar (every row at the same position — the batch beam
    scan) or a (B,) vector (each row at its OWN position — the slot-refill
    engine, decode/engine.py, whose slots hold samples mid-flight at mixed
    depths). The two forms run the identical per-row math: the vector path
    only swaps the shared s+1 column write for a per-row gather/scatter."""
    B, K, W = cand.shape
    cand = jnp.where(finished[:, :, None], neg, cand)
    sentinel = jnp.where(finished, probs, neg)          # (B, K)
    allc = jnp.concatenate([cand.reshape(B, K * W), sentinel], axis=1)
    top_vals, top_idx = jax.lax.top_k(allc, K)          # (B, K)

    is_sent = top_idx >= K * W
    src_beam = jnp.where(is_sent, top_idx - K * W, top_idx // W)
    if ids is None:
        tok = jnp.where(is_sent, 0, top_idx % W)
    else:
        tok = jnp.take_along_axis(
            ids.reshape(B, K * W), jnp.where(is_sent, 0, top_idx), axis=1)
        tok = jnp.where(is_sent, 0, tok)
    tok = _resolve_copy(tok, batch["diff"], batch["sub_token"], cfg)

    new_tokens = jnp.take_along_axis(tokens, src_beam[:, :, None], axis=1)
    if jnp.ndim(s) == 0:
        keep = new_tokens[:, :, s + 1]  # finished beams keep their padding
        new_tokens = new_tokens.at[:, :, s + 1].set(
            jnp.where(is_sent, keep, tok)
        )
    else:
        # per-row position: row b writes its own column s[b]+1 (clamped
        # rows — engine slots already done/idle — are blended away by the
        # caller, so their garbage write never lands in live state)
        b_idx = jnp.arange(B)[:, None]
        k_idx = jnp.arange(K)[None, :]
        sp1 = (s + 1)[:, None]
        keep = new_tokens[b_idx, k_idx, sp1]
        new_tokens = new_tokens.at[b_idx, k_idx, sp1].set(
            jnp.where(is_sent, keep, tok)
        )
    new_finished = jnp.where(is_sent, True, tok == EOS_ID)
    return new_tokens, top_vals, new_finished, src_beam


def _select_factored(gen, copy, gate, tokens, probs, finished, s, batch,
                     cfg: FiraConfig, neg):
    """Beam-selection round from the distribution FACTORS.

    gen: (B, K, vocab) generation softmax; copy: (B, K, sou+sub) copy
    softmax; gate: (B, K, 2). The fused distribution is
    [gate0*gen || gate1*copy], so each beam's global top-K lies in the
    union of its per-side top-Ks — selection runs over 2K candidates per
    beam (6 for beam 3) instead of the 25,020-way assembled tensor. Same
    candidate math as :func:`_select` (prob- or log-space, finished-beam
    sentinels); only tie-breaking among exactly-equal probabilities can
    differ from the fused scan order."""
    B, K, V = gen.shape
    gv, gi = jax.lax.top_k(gen, K)                      # (B, K, K)
    cv, ci = jax.lax.top_k(copy, K)
    side_vals = jnp.concatenate(
        [gv * gate[:, :, 0:1], cv * gate[:, :, 1:2]], axis=-1)  # (B, K, 2K)
    side_ids = jnp.concatenate([gi, ci + V], axis=-1)   # fused-space ids

    if cfg.beam_compat_prob_space:
        cand = side_vals * probs[:, :, None]
    else:
        cand = jnp.log(jnp.clip(side_vals, 1e-10, 1.0)) + probs[:, :, None]
    return _selection_tail(cand, side_ids, tokens, probs, finished, s,
                           batch, cfg, neg)


def _select(dist, tokens, probs, finished, s, batch, cfg: FiraConfig, neg):
    """One beam-selection round given this step's fused distribution.

    dist: (B, K, V_out) probability-space distribution at position ``s``.
    Implements the reference's candidate construction exactly: active beams
    contribute dist x prob (prob- or log-space), finished beams are masked
    to ``neg`` and contribute a sentinel entry carrying their own
    probability; one global top-k over K*V_out + K candidates
    (run_model.py:267-310). Returns (new_tokens, new_probs, new_finished,
    src_beam)."""
    if cfg.beam_compat_prob_space:
        cand = dist * probs[:, :, None]
    else:
        cand = jnp.log(jnp.clip(dist, 1e-10, 1.0)) + probs[:, :, None]
    return _selection_tail(cand, None, tokens, probs, finished, s,
                           batch, cfg, neg)


def _run_steps(step, carry0, T: int, early_exit: bool):
    """Drive the per-position beam step over positions 0..T-2.

    early_exit=False: plain `lax.scan` (always T-1 steps — the parity
    default). early_exit=True: `lax.while_loop` that stops once every beam
    of every item is finished AND one settling step has run after
    saturation. The settling step matters for bit-exactness: the first
    all-finished step re-sorts beams prob-descending via the sentinel
    top-k; after it the state is an element-wise fixed point (stable top_k
    on a sorted vector), so skipping the remaining steps changes nothing.
    `finished` is carry[2] in both beam variants.

    Returns (final_carry, steps_run) — steps_run is a traced scalar under
    early exit (T-1 exactly otherwise)."""
    if not early_exit:
        carry, _ = jax.lax.scan(step, carry0, jnp.arange(T - 1))
        return carry, jnp.int32(T - 1)

    def cond(state):
        s, settled, carry = state
        return (s < T - 1) & ~(settled & jnp.all(carry[2]))

    def body(state):
        s, settled, carry = state
        new_carry, _ = step(carry, s)
        return s + 1, jnp.all(carry[2]), new_carry

    s, _, carry = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.asarray(False), carry0))
    return carry, s


def beam_search(model: FiraModel, params, batch: Dict[str, jnp.ndarray],
                cfg: FiraConfig, with_steps: bool = False,
                ) -> Tuple[jnp.ndarray, ...]:
    """Returns (tokens (B, beam, tar_len) with copy ids already resolved,
    scores (B, beam)). The best beam is argmax(scores) (run_model.py:351).
    with_steps=True appends the number of decode positions actually run
    (a scalar; < tar_len-1 only under cfg.beam_early_exit).

    Jit this via `make_beam_step` below or wrap in jax.jit at the call site;
    everything inside is fixed-shape.
    """
    K, T, V_out = cfg.beam_size, cfg.tar_len, cfg.output_vocab_size
    B = batch["diff"].shape[0]

    states, mask = model.apply({"params": params}, batch,
                               method=FiraModel.encode)
    # fold beams into batch for the decoder: (B*K, ...)
    states_k = jnp.repeat(states, K, axis=0)
    mask_k = jnp.repeat(mask, K, axis=0)

    tokens0, probs0, finished0, neg = _init_beam(B, cfg)

    def step(carry, s):
        tokens, probs, finished = carry
        flat = tokens.reshape(B * K, T)
        # active prefixes all have length s+1; pad mask = positions <= s for
        # active beams, < own length for finished (their tail is 0-padded, and
        # they are masked out of selection anyway)
        tar_mask = flat != 0
        tar_mask = tar_mask.at[:, 0].set(True)  # position 0 is <start>: always attended
        if cfg.beam_factored_topk:
            gen, copy, gate = model.apply(
                {"params": params}, states_k, mask_k, flat, tar_mask,
                method=FiraModel.dist_parts,
            )
            new_tokens, new_probs, new_finished, _ = _select_factored(
                gen[:, s, :].reshape(B, K, -1),
                copy[:, s, :].reshape(B, K, -1),
                gate[:, s, :].reshape(B, K, 2),
                tokens, probs, finished, s, batch, cfg, neg)
            return (new_tokens, new_probs, new_finished), None
        fused = model.apply(
            {"params": params}, states_k, mask_k, flat, tar_mask,
            method=FiraModel.fused_probs,
        )  # (B*K, T, V_out)
        dist = fused[:, s, :].reshape(B, K, V_out)
        new_tokens, new_probs, new_finished, _ = _select(
            dist, tokens, probs, finished, s, batch, cfg, neg)
        return (new_tokens, new_probs, new_finished), None

    (tokens, probs, _), steps = _run_steps(
        step, (tokens0, probs0, finished0), T, cfg.beam_early_exit)
    return (tokens, probs, steps) if with_steps else (tokens, probs)


def beam_search_cached(model: FiraModel, params, batch: Dict[str, jnp.ndarray],
                       cfg: FiraConfig, with_steps: bool = False,
                       ) -> Tuple[jnp.ndarray, ...]:
    """KV-cached beam search: identical selection semantics to
    :func:`beam_search` (the equivalence is pinned by
    tests/test_train_decode.py), but each scan step decodes ONE position via
    per-layer self-attention caches, with cross-attention K/V and the copy
    head's source projection computed once per batch — O(T) decoder work
    overall instead of the reference's O(T^2) full re-decode per step
    (run_model.py:256; SURVEY.md §7 build-plan 6).

    The cache is beam-gathered with the same src_beam permutation as the
    token prefixes each step, so reshuffled beams keep consistent histories.
    """
    K, T, V_out = cfg.beam_size, cfg.tar_len, cfg.output_vocab_size
    B = batch["diff"].shape[0]
    L, H = cfg.num_layers, cfg.num_head
    d_head = cfg.embedding_dim // H

    states, mask = model.apply({"params": params}, batch,
                               method=FiraModel.encode)
    mask_k = jnp.repeat(mask, K, axis=0)
    # project once per ITEM, then replicate per beam — beams share encoder
    # states, so projecting after the beam fold would do K-fold duplicate
    # matmuls (the raw states themselves are not needed per step at all)
    cross_k, cross_v, src_proj = model.apply(
        {"params": params}, states, method=FiraModel.decode_init)
    cross_k = jnp.repeat(cross_k, K, axis=1)   # (L, B*K, H, S, d_head)
    cross_v = jnp.repeat(cross_v, K, axis=1)
    src_proj = jnp.repeat(src_proj, K, axis=0)

    tokens0, probs0, finished0, neg = _init_beam(B, cfg)
    cache0 = jnp.zeros((L, B * K, H, T, d_head), states.dtype)

    def step(carry, s):
        tokens, probs, finished, k_cache, v_cache = carry
        flat = tokens.reshape(B * K, T)
        # same per-position validity rule as the full-prefix path's pad
        # mask, restricted causally to positions <= s
        valid = step_valid_mask(flat, s, T)
        tok_in = jax.lax.dynamic_slice_in_dim(flat, s, 1, axis=1)  # (B*K, 1)
        if cfg.beam_factored_topk:
            gen, copy, gate, k_cache, v_cache = model.apply(
                {"params": params}, mask_k, tok_in, s,
                k_cache, v_cache, cross_k, cross_v, src_proj,
                valid[:, None, None, :],
                method=FiraModel.dist_parts_step,
            )
            new_tokens, new_probs, new_finished, src_beam = _select_factored(
                gen[:, 0, :].reshape(B, K, -1),
                copy[:, 0, :].reshape(B, K, -1),
                gate[:, 0, :].reshape(B, K, 2),
                tokens, probs, finished, s, batch, cfg, neg)
        else:
            fused, k_cache, v_cache = model.apply(
                {"params": params}, mask_k, tok_in, s,
                k_cache, v_cache, cross_k, cross_v, src_proj,
                valid[:, None, None, :],
                method=FiraModel.fused_probs_step,
            )  # (B*K, 1, V_out)
            dist = fused[:, 0, :].reshape(B, K, V_out)
            new_tokens, new_probs, new_finished, src_beam = _select(
                dist, tokens, probs, finished, s, batch, cfg, neg)
        # permute cached histories to follow their beams: (L, B, K, ...)
        idx = src_beam[None, :, :, None, None, None]

        def gather_cache(c):
            c = c.reshape(L, B, K, H, T, d_head)
            c = jnp.take_along_axis(c, idx, axis=2)
            return c.reshape(L, B * K, H, T, d_head)

        return (new_tokens, new_probs, new_finished,
                gather_cache(k_cache), gather_cache(v_cache)), None

    (tokens, probs, *_), steps = _run_steps(
        step, (tokens0, probs0, finished0, cache0, cache0), T,
        cfg.beam_early_exit)
    return (tokens, probs, steps) if with_steps else (tokens, probs)


def make_beam_search(model: FiraModel, cfg: FiraConfig,
                     with_steps: bool = False):
    """jit-compiled beam search closure over (params, batch); KV-cached by
    default (cfg.beam_kv_cache), full-prefix re-decode otherwise.
    with_steps=True makes the closure return (tokens, probs, steps_run)."""
    impl = beam_search_cached if cfg.beam_kv_cache else beam_search
    return jax.jit(lambda params, batch: impl(model, params, batch, cfg,
                                              with_steps=with_steps))


def eos_biased_params(params, delta: float = 8.0):
    """A paramset whose generation head is biased hard toward EOS, so every
    beam finishes within a few positions. Test/bench utility: saturates the
    beam_early_exit path deterministically (tests/test_beam_early_exit.py
    pins exactness with it; tpu_decode_bench.py uses it for the best-case
    `_saturated` rows). Shared here so the out_fc param path and the bias
    magnitude cannot drift between the two."""
    from fira_tpu.data.vocab import EOS_ID

    bias = np.asarray(params["out_fc"]["bias"]).copy()
    bias[EOS_ID] += delta
    return {**params,
            "out_fc": {**params["out_fc"], "bias": jnp.asarray(bias)}}
