"""Jitted batched beam search.

The reference's decoder loop (/root/reference/run_model.py:187-380) is pure
Python: per step x per beam it re-runs the full decoder on the padded
prefix, fuses gen+copy probabilities, multiplies by the running beam
probability (PROBABILITIES, not log-probs, :271), appends finished-beam
sentinel probabilities (:281-298), takes one global top-k (:305-310), and
resolves copy ids to source token ids at beam-extension time (:334-337).

This rebuild runs the whole thing as ONE compiled program: beams fold into
the batch dim, `lax.scan` drives the tar_len-1 steps, and top-k replaces the
sort. Two accumulation modes:

- compat (default, cfg.beam_compat_prob_space=True): probability-space
  accumulation with the reference's exact candidate construction —
  finished beams contribute a -1-masked distribution PLUS a sentinel entry
  carrying their probability, so selection order is bit-for-bit the
  reference's (needed for +-0.3 BLEU parity, SURVEY.md hard-part 2);
- log-space: the numerically sound default for long targets; identical
  argmax behavior until probabilities underflow.

Semantic note vs the reference: the reference skips a beam only when it is
finished for EVERY batch item (cal_beam, :229-247) and compacts the sentinel
list (:286-296); per item that yields exactly the candidate set built here
(active beams: dist x prob; finished beams: -1-mask + sentinel), so the
fixed-shape formulation selects the same beams without data-dependent
control flow. Early loop exit (:276-279) is replaced by running all steps —
finished beams are fixed points of the update.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.vocab import EOS_ID, START_ID
from fira_tpu.model.model import FiraModel


def _resolve_copy(tok, diff, sub_token, cfg: FiraConfig):
    """Copy-id -> source token id (run_model.py:334-337), vectorized.

    tok: (B, K) candidate ids over the fused output space;
    diff: (B, sou_len); sub_token: (B, sub_token_len).
    """
    V = cfg.vocab_size
    sub_pos = jnp.clip(tok - V - cfg.sou_len, 0, cfg.sub_token_len - 1)
    diff_pos = jnp.clip(tok - V, 0, cfg.sou_len - 1)
    from_sub = jnp.take_along_axis(sub_token, sub_pos, axis=1)
    from_diff = jnp.take_along_axis(diff, diff_pos, axis=1)
    return jnp.where(
        tok >= V + cfg.sou_len, from_sub,
        jnp.where(tok >= V, from_diff, tok),
    )


def beam_search(model: FiraModel, params, batch: Dict[str, jnp.ndarray],
                cfg: FiraConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (tokens (B, beam, tar_len) with copy ids already resolved,
    scores (B, beam)). The best beam is argmax(scores) (run_model.py:351).

    Jit this via `make_beam_step` below or wrap in jax.jit at the call site;
    everything inside is fixed-shape.
    """
    K, T, V_out = cfg.beam_size, cfg.tar_len, cfg.output_vocab_size
    B = batch["diff"].shape[0]
    prob_space = cfg.beam_compat_prob_space

    states, mask = model.apply({"params": params}, batch,
                               method=FiraModel.encode)
    # fold beams into batch for the decoder: (B*K, ...)
    states_k = jnp.repeat(states, K, axis=0)
    mask_k = jnp.repeat(mask, K, axis=0)

    tokens0 = jnp.zeros((B, K, T), jnp.int32).at[:, :, 0].set(START_ID)
    if prob_space:
        # beam 0 prob 1, others 0 (run_model.py:216-221)
        probs0 = jnp.tile(jnp.asarray([1.0] + [0.0] * (K - 1), jnp.float32),
                          (B, 1))
        neg = jnp.float32(-1.0)  # reference's masked/-pad value (:273,294)
    else:
        probs0 = jnp.tile(
            jnp.asarray([0.0] + [-np.inf] * (K - 1), jnp.float32), (B, 1)
        )
        neg = jnp.float32(-np.inf)
    finished0 = jnp.zeros((B, K), bool)

    def step(carry, s):
        tokens, probs, finished = carry
        flat = tokens.reshape(B * K, T)
        # active prefixes all have length s+1; pad mask = positions <= s for
        # active beams, < own length for finished (their tail is 0-padded, and
        # they are masked out of selection anyway)
        tar_mask = flat != 0
        tar_mask = tar_mask.at[:, 0].set(True)  # <start> may be id 0? no: 2
        fused = model.apply(
            {"params": params}, states_k, mask_k, flat, tar_mask,
            method=FiraModel.fused_probs,
        )  # (B*K, T, V_out)
        dist = fused[:, s, :].reshape(B, K, V_out)
        if prob_space:
            cand = dist * probs[:, :, None]
        else:
            cand = jnp.log(jnp.clip(dist, 1e-10, 1.0)) + probs[:, :, None]
        cand = jnp.where(finished[:, :, None], neg, cand)
        sentinel = jnp.where(finished, probs, neg)          # (B, K)
        allc = jnp.concatenate([cand.reshape(B, K * V_out), sentinel], axis=1)
        top_vals, top_idx = jax.lax.top_k(allc, K)          # (B, K)

        is_sent = top_idx >= K * V_out
        src_beam = jnp.where(is_sent, top_idx - K * V_out, top_idx // V_out)
        tok = jnp.where(is_sent, 0, top_idx % V_out)
        tok = _resolve_copy(tok, batch["diff"], batch["sub_token"], cfg)

        gather = lambda arr: jnp.take_along_axis(
            arr, src_beam.reshape(B, K, *([1] * (arr.ndim - 2))), axis=1
        )
        new_tokens = gather(tokens)
        keep = new_tokens[:, :, s + 1]  # finished beams keep their padding
        new_tokens = new_tokens.at[:, :, s + 1].set(
            jnp.where(is_sent, keep, tok)
        )
        new_finished = jnp.where(is_sent, True, tok == EOS_ID)
        return (new_tokens, top_vals, new_finished), None

    (tokens, probs, _), _ = jax.lax.scan(
        step, (tokens0, probs0, finished0), jnp.arange(T - 1)
    )
    return tokens, probs


def make_beam_search(model: FiraModel, cfg: FiraConfig):
    """jit-compiled beam search closure over (params, batch)."""
    return jax.jit(lambda params, batch: beam_search(model, params, batch, cfg))
