"""Test-split decoding driver (the reference's `test()`,
/root/reference/run_model.py:187-380): decode every sample, pick the
argmax-probability beam, cook text, score in-loop sentence BLEU, and write
one prediction per line to OUTPUT/output_fira (ablations write their own
suffixed files, matching OUTPUT/output_fira_{no_edit,no_subtoken,nothing}).

Two decode paths, selected by ``cfg.decode_engine`` (CLI ``--engine``;
bit-exact per sample — docs/DECODE_ENGINE.md):

- **batched beam** (default): one beam program dispatch per packed batch;
  with ``beam_early_exit`` the dispatch still runs until the batch's
  LONGEST message settles.
- **slot-refill engine** (decode/engine.py): S static slots advanced one
  token per step, settled slots harvested and refilled mid-flight from
  the same packer stream — wall clock scales with total tokens emitted.
  With ``cfg.engine_replicas > 1`` the engine becomes a replicated FLEET
  (parallel/fleet.py): N engines on N devices pull from one shared
  admission queue; decoded file bytes are invariant to the replica count.

Both paths stream through the ordered writer (decode/stream.py): the
contiguous split-order prefix is on disk the moment it completes, a crash
leaves a parseable prefix, and completion atomically renames
``.partial`` to the final file.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import numpy as np

from fira_tpu.analysis.sanitizer import program_label
from fira_tpu.config import FiraConfig
from fira_tpu.data import buckets as buckets_lib
from fira_tpu.data.batching import epoch_index_chunks
from fira_tpu.data.dataset import FiraDataset
from fira_tpu.data.feeder import Feeder, assembly_tasks
from fira_tpu.decode import engine as engine_lib
from fira_tpu.decode.beam import make_beam_search
from fira_tpu.decode.stream import OrderedStreamWriter
from fira_tpu.decode.text import cook_prediction, deanonymize, reference_words
from fira_tpu.eval.dev_bleu import nltk_sentence_bleu
from fira_tpu.model.model import FiraModel


def output_name(ablation: Optional[str]) -> str:
    """OUTPUT file naming per paper ablation (BASELINE.md rows)."""
    if ablation in (None, "", "none", "full"):
        return "output_fira"
    return f"output_fira_{ablation}"


def sample_emitter(writer, *, vocab, cfg: FiraConfig, bleu_by_pos: Dict,
                   n_total: int, var_maps=None, indices=None):
    """The per-sample tail every decode driver shares (batched beam, slot
    engine, fleet, and the serving loop — serve/server.py): pick the
    argmax beam, cook text, score BLEU, de-anonymize, write at the
    sample's split position."""

    def emit(pos, host, row, tokens, probs):
        best = int(np.argmax(probs))             # run_model.py:351
        ids = tokens[best].tolist()
        # beam output ids are already copy-resolved at extension time
        hyp = cook_prediction(ids[1:], host["diff"][row],
                              host["sub_token"][row], vocab, cfg,
                              resolve=False)
        ref = reference_words(host["msg"][row], vocab)
        # keyed by position, summed in split order at the end: samples
        # settle in scheduler order (engine/fleet/serve), and float
        # addition in settle order would make the aggregate depend on
        # replica count / refill interleaving in the last ulp
        bleu_by_pos[pos] = nltk_sentence_bleu([ref], hyp)
        n = len(bleu_by_pos)
        var_map = (var_maps[indices[pos]]
                   if var_maps is not None else None)
        writer.add(pos, " ".join(deanonymize(hyp, var_map)) + "\n")
        if n % 1000 == 0:
            writer.flush()
            print(f"decode: {n}/{n_total}", flush=True)

    return emit


def _decode_tasks(data, cfg: FiraConfig):
    """The packed decode stream: (tasks, decode bucket table or None).
    Shared by both decode paths — the engine prefills EXACTLY the batches
    the batched beam would dispatch."""
    stamp = None
    if cfg.prefix_cache:
        # content digests computed worker-side with the rest of assembly
        # (bucketed and unbucketed streams alike — the engine's on-demand
        # fallback exists only for streams that bypass these task
        # builders); the digest carries the serving tier's namespace so a
        # cached f32 artifact never seats a bf16 slot (decode/quant.py)
        import functools

        from fira_tpu.decode import quant
        from fira_tpu.decode.prefix_cache import stamp_digests
        stamp = functools.partial(stamp_digests,
                                  namespace=quant.tier_namespace(cfg))
    if cfg.buckets:
        table = buckets_lib.decode_table(cfg)
        # tar-bucketed decode assigns by reference-message extent (the
        # bucket's tar is a generation budget, so a sample must FIT its
        # bucket); the tar-pinned default ignores msg, as before
        plan = buckets_lib.packed_plan(data, cfg,
                                       batch_size=cfg.test_batch_size,
                                       table=table,
                                       use_msg=cfg.decode_tar_buckets)
        tasks = buckets_lib.bucketed_assembly_tasks(
            data, plan, cfg, batch_size=cfg.test_batch_size, stamp=stamp)
        return tasks, table
    chunks = epoch_index_chunks(len(data), cfg,
                                batch_size=cfg.test_batch_size)
    return assembly_tasks(data, chunks, cfg,
                          batch_size=cfg.test_batch_size,
                          stamp=stamp), None


def run_test(model: FiraModel, params, dataset: FiraDataset,
             cfg: Optional[FiraConfig] = None, *,
             out_dir: str = "OUTPUT",
             ablation: Optional[str] = None,
             var_maps: Optional[List[Dict[str, str]]] = None,
             split: str = "test",
             guard=None,
             engine_slots: Optional[int] = None,
             refill_order: str = "fifo",
             faults=None) -> Dict[str, float]:
    """``guard``: an armed analysis.sanitizer.CompileGuard — each decode
    program must compile exactly once (warmup), then never again. The CLI
    arms it via ``--sanitize``; library callers use the
    sanitizer.sanitize() context manager so global config is restored.
    ``engine_slots``/``refill_order`` apply to the engine path only (the
    latter exists so the determinism tests can pin refill-order
    independence).

    ``faults``: an armed robust.faults.FaultInjector (None resolves from
    ``cfg.inject_faults``; "" keeps it off at zero overhead). Drain mode
    degrades like a batch job should: transient assembly faults are
    absorbed by the feeder's ``cfg.robust_retries`` retry budget, a fleet
    replica whose dispatch raises or blows ``cfg.dispatch_watchdog_s``
    retires with its requests requeued onto survivors (parallel/fleet.py)
    — and a fault nothing can absorb fails LOUDLY with the sample named
    in the traceback, never silently truncating the output file."""
    cfg = cfg or dataset.cfg
    if faults is None:
        from fira_tpu.robust import faults as faults_lib

        faults = faults_lib.injector_from(cfg)
    data = dataset.splits[split]
    vocab = dataset.word_vocab
    indices = dataset.split_indices[split]
    tasks, table = _decode_tasks(data, cfg)

    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, output_name(ablation))
    bleu_by_pos: Dict[int, float] = {}
    n_total = len(data)
    engine_stats = None

    def make_emit(writer):
        return sample_emitter(writer, vocab=vocab, cfg=cfg,
                              bleu_by_pos=bleu_by_pos, n_total=n_total,
                              var_maps=var_maps, indices=indices)

    if cfg.decode_engine:
        n_rep = max(1, int(cfg.engine_replicas))
        if n_rep > 1:
            from fira_tpu.parallel import fleet as fleet_lib

            eng = fleet_lib.EngineFleet(model, params, cfg, replicas=n_rep,
                                        slots=engine_slots, guard=guard,
                                        faults=faults)
        else:
            eng = engine_lib.SlotEngine(model, params, cfg,
                                        slots=engine_slots, guard=guard,
                                        faults=faults)
        if table is not None:
            if guard is not None:
                # single engine: the classic (geometry x {prefill, step,
                # insert}) family; fleet: the union over replicas, each
                # label suffixed r<i> (per-device executables are real
                # per-replica compiles)
                guard.declare(eng.labels(table))
            eng.prewarm(
                (buckets_lib.warmup_batch(data, cfg, g, cfg.test_batch_size),
                 buckets_lib.geom_tag(g)) for g in table)
            print(f"decode buckets: {len(table)} engine prefill programs "
                  f"pre-warmed"
                  f"{f' x {n_rep} replicas' if n_rep > 1 else ''} "
                  f"({', '.join(buckets_lib.geom_tag(g) for g in table)})",
                  flush=True)
        else:
            # unbucketed: pre-warm the single-geometry engine family
            # (prefill + no-op insert/step + harvest gather) so the
            # dispatch watchdog never reads a first-use XLA compile as a
            # hung replica (docs/FAULTS.md)
            from fira_tpu.data.batching import make_batch

            warm = make_batch(data, np.arange(0), cfg,
                              batch_size=cfg.test_batch_size)
            eng.prewarm([(warm, None)])
        # the Feeder is constructed INSIDE the with (after the writer's
        # open succeeds): a failing open must not leak worker threads.
        # The fleet's feeder skips the device_put (put=False): which
        # replica a chunk lands on is a scheduling decision, so the
        # transfer happens at admission, onto the claiming replica's chip.
        with OrderedStreamWriter(out_path, expected=n_total) as writer, \
                Feeder(tasks, num_workers=cfg.feeder_workers,
                       depth=cfg.feeder_depth, put=n_rep == 1,
                       retries=max(0, cfg.robust_retries),
                       faults=faults) as feed:
            emit = make_emit(writer)
            for item in eng.run(feed, refill_order=refill_order):
                emit(item.position, item.host, item.row, item.tokens,
                     item.probs)
        engine_stats = eng.stats.summary()
    else:
        beam = make_beam_search(model, cfg)
        # Bucketed decode (data/buckets.py): each bucket's beam program is
        # pre-warmed with an all-pad batch, then the guard learns the
        # closed family.
        if table is not None:
            if guard is not None:
                guard.declare(program_label("beam_search",
                                            buckets_lib.geom_tag(g))
                              for g in table)
            for g in table:
                beam(params, buckets_lib.warmup_batch(data, cfg, g,
                                                      cfg.test_batch_size))
                if guard is not None:
                    guard.step(program_label("beam_search",
                                             buckets_lib.geom_tag(g)))
            print(f"decode buckets: {len(table)} beam programs pre-warmed "
                  f"({', '.join(buckets_lib.geom_tag(g) for g in table)})",
                  flush=True)
        cursor = 0
        with OrderedStreamWriter(out_path, expected=n_total) as writer, \
                Feeder(tasks, num_workers=cfg.feeder_workers,
                       depth=cfg.feeder_depth) as feed:
            emit = make_emit(writer)
            for item in feed:
                batch = item.host  # numpy fields for host-side text cooking
                tokens, probs = beam(params, item.device)
                # firacheck: allow[HOST-SYNC] per-batch output collection IS the decode boundary: beams must reach the host to be cooked into text
                tokens = np.asarray(jax.device_get(tokens))
                probs = np.asarray(jax.device_get(probs))  # firacheck: allow[HOST-SYNC] same decode output boundary as the line above
                positions = batch.get("_positions")  # bucketed stream only
                if guard is not None:
                    guard.step(program_label("beam_search",
                                             batch.get("_tag")))
                valid = batch["valid"]  # host-side numpy field, no sync
                for i in range(tokens.shape[0]):
                    if not valid[i]:
                        continue
                    pos = cursor if positions is None else int(positions[i])  # firacheck: allow[HOST-SYNC] _positions is a host-only numpy field (feeder strips it from the wire); no device value exists here
                    emit(pos, batch, i, tokens[i], probs[i])
                    cursor += 1
    n = len(bleu_by_pos)
    total_bleu = sum(bleu_by_pos[p] for p in sorted(bleu_by_pos))
    out: Dict[str, float] = {
        "sentence_bleu": total_bleu / max(n, 1), "n": float(n),
        "output_path": out_path}  # type: ignore[assignment]
    if engine_stats is not None:
        out["engine"] = engine_stats  # type: ignore[assignment]
    return out  # type: ignore[return-value]
