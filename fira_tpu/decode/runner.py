"""Test-split decoding driver (the reference's `test()`,
/root/reference/run_model.py:187-380): beam-search every batch, pick the
argmax-probability beam, cook text, score in-loop sentence BLEU, and write
one prediction per line to OUTPUT/output_fira (ablations write their own
suffixed files, matching OUTPUT/output_fira_{no_edit,no_subtoken,nothing}).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import numpy as np

from fira_tpu.analysis.sanitizer import program_label
from fira_tpu.config import FiraConfig
from fira_tpu.data import buckets as buckets_lib
from fira_tpu.data.batching import epoch_index_chunks
from fira_tpu.data.dataset import FiraDataset
from fira_tpu.data.feeder import Feeder, assembly_tasks
from fira_tpu.decode.beam import make_beam_search
from fira_tpu.decode.text import cook_prediction, deanonymize, reference_words
from fira_tpu.eval.dev_bleu import nltk_sentence_bleu
from fira_tpu.model.model import FiraModel


def output_name(ablation: Optional[str]) -> str:
    """OUTPUT file naming per paper ablation (BASELINE.md rows)."""
    if ablation in (None, "", "none", "full"):
        return "output_fira"
    return f"output_fira_{ablation}"


def run_test(model: FiraModel, params, dataset: FiraDataset,
             cfg: Optional[FiraConfig] = None, *,
             out_dir: str = "OUTPUT",
             ablation: Optional[str] = None,
             var_maps: Optional[List[Dict[str, str]]] = None,
             split: str = "test",
             guard=None) -> Dict[str, float]:
    """``guard``: an armed analysis.sanitizer.CompileGuard — the beam
    program must compile exactly once (warmup), then never again. The CLI
    arms it via ``--sanitize``; library callers use the
    sanitizer.sanitize() context manager so global config is restored."""
    cfg = cfg or dataset.cfg
    data = dataset.splits[split]
    vocab = dataset.word_vocab
    indices = dataset.split_indices[split]
    beam = make_beam_search(model, cfg)

    # Bucketed decode (data/buckets.py): sort-by-length packing over the
    # (ast nodes, edges) axes — tar_len stays FULL on every decode bucket,
    # the model decides the output length and it must not be clipped. Each
    # bucket's beam program is pre-warmed here with an all-pad batch, then
    # the guard learns the closed family. The packer reorders the sample
    # stream, so output lines buffer and write in split order at the end
    # (the buckets-off path keeps its crash-resilient streaming writes).
    table = None
    if cfg.buckets:
        table = buckets_lib.decode_table(cfg)
        if guard is not None:
            guard.declare(program_label("beam_search",
                                        buckets_lib.geom_tag(g))
                          for g in table)
        for g in table:
            beam(params, buckets_lib.warmup_batch(data, cfg, g,
                                                  cfg.test_batch_size))
            if guard is not None:
                guard.step(program_label("beam_search",
                                         buckets_lib.geom_tag(g)))
        plan = buckets_lib.packed_plan(data, cfg,
                                       batch_size=cfg.test_batch_size,
                                       table=table, use_msg=False)
        tasks = buckets_lib.bucketed_assembly_tasks(
            data, plan, cfg, batch_size=cfg.test_batch_size)
        print(f"decode buckets: {len(table)} beam programs pre-warmed "
              f"({', '.join(buckets_lib.geom_tag(g) for g in table)})",
              flush=True)
    else:
        chunks = epoch_index_chunks(len(data), cfg,
                                    batch_size=cfg.test_batch_size)
        tasks = assembly_tasks(data, chunks, cfg,
                               batch_size=cfg.test_batch_size)

    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, output_name(ablation))
    # stream to a .partial file, atomically renamed on completion: full-size
    # decodes run for tens of minutes and a crash must not cost every line.
    # Bucketed packing emits samples out of split order, so its .partial
    # lines stream POSITION-TAGGED ("pos\tline" — still crash-recoverable,
    # every decoded line is on disk the moment its batch lands) and the
    # plain split-ordered final file is written from the sorted buffer at
    # completion; the buckets-off path keeps the historical plain stream.
    partial_path = out_path + ".partial"
    total_bleu, n = 0.0, 0
    cursor = 0
    n_total = len(data)
    buffered: List[tuple] = []  # bucketed mode: (split position, line)
    # the Feeder is constructed INSIDE the with (after open succeeds): a
    # failing open must not leak already-started worker threads
    with open(partial_path, "w") as out_f, \
            Feeder(tasks, num_workers=cfg.feeder_workers,
                   depth=cfg.feeder_depth) as feed:
        for item in feed:
            batch = item.host  # numpy fields for host-side text cooking
            tokens, probs = beam(params, item.device)
            # firacheck: allow[HOST-SYNC] per-batch output collection IS the decode boundary: beams must reach the host to be cooked into text
            tokens = np.asarray(jax.device_get(tokens))
            probs = np.asarray(jax.device_get(probs))  # firacheck: allow[HOST-SYNC] same decode output boundary as the line above
            positions = batch.get("_positions")  # bucketed stream only
            if guard is not None:
                guard.step(program_label("beam_search", batch.get("_tag")))
            valid = batch["valid"]  # host-side numpy batch field, no sync
            for i in range(tokens.shape[0]):
                if not valid[i]:
                    continue
                best = int(np.argmax(probs[i]))      # run_model.py:351
                ids = tokens[i, best].tolist()
                # beam output ids are already copy-resolved at extension time
                hyp = cook_prediction(ids[1:], batch["diff"][i],
                                      batch["sub_token"][i], vocab, cfg,
                                      resolve=False)
                ref = reference_words(batch["msg"][i], vocab)
                total_bleu += nltk_sentence_bleu([ref], hyp)
                n += 1
                pos = cursor if positions is None else int(positions[i])  # firacheck: allow[HOST-SYNC] _positions is a host-only numpy field (feeder strips it from the wire); no device value exists here
                var_map = (var_maps[indices[pos]]
                           if var_maps is not None else None)
                line = " ".join(deanonymize(hyp, var_map)) + "\n"
                if positions is None:
                    out_f.write(line)
                else:
                    out_f.write(f"{pos}\t{line}")  # tagged, crash-recoverable
                    buffered.append((pos, line))
                cursor += 1
            if n and n % 1000 < cfg.test_batch_size:
                out_f.flush()
                print(f"decode: {n}/{n_total}", flush=True)
    if buffered:
        # completion: the split-ordered plain file replaces the tagged
        # stream atomically (write-then-rename, like the plain path)
        buffered.sort(key=lambda r: r[0])
        ordered_path = out_path + ".ordered"
        with open(ordered_path, "w") as f:
            for _, line in buffered:
                f.write(line)
        os.replace(ordered_path, out_path)
        os.remove(partial_path)
    else:
        os.replace(partial_path, out_path)
    return {"sentence_bleu": total_bleu / max(n, 1), "n": float(n),
            "output_path": out_path}  # type: ignore[return-value]
