"""Host-side id -> text post-processing shared by dev and test decoding.

Replicates the reference's output cooking exactly
(/root/reference/run_model.py:141-179 dev, :342-372 test):
copy-id resolution against the sample's own diff / sub-token id arrays,
<eos> truncation, special-token stripping with <unkm> rendered as the
emoji sentinel, and reverse-variable-map de-anonymization applied AFTER
BLEU is scored on the anonymized tokens.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.vocab import (
    EOS_ID,
    EOS_TOKEN,
    PAD_TOKEN,
    START_TOKEN,
    UNK_TOKEN,
    Vocab,
)

UNK_RENDER = "\U0001f605"  # the reference prints <unkm> as 😅 (run_model.py:162,355)


def resolve_copy_ids(ids: Sequence[int], diff_ids: Sequence[int],
                     sub_token_ids: Sequence[int], cfg: FiraConfig) -> List[int]:
    """run_model.py:154-158: ids >= vocab+sou_len index the sub-token array,
    ids >= vocab index the padded diff array."""
    out = []
    for t in ids:
        if t >= cfg.vocab_size + cfg.sou_len:
            t = int(sub_token_ids[t - cfg.vocab_size - cfg.sou_len])
        elif t >= cfg.vocab_size:
            t = int(diff_ids[t - cfg.vocab_size])
        out.append(int(t))
    return out


def truncate_at_eos(ids: Sequence[int]) -> List[int]:
    ids = list(ids)
    if EOS_ID in ids:
        ids = ids[: ids.index(EOS_ID)]
    return ids


def ids_to_words(ids: Sequence[int], vocab: Vocab) -> List[str]:
    """Tokens with specials stripped and <unkm> rendered (run_model.py:161-163:
    join, replace, strip, re-split — equivalent to dropping strippable tokens)."""
    words = []
    for tok in vocab.convert_ids_to_tokens(ids):
        if tok in (PAD_TOKEN, START_TOKEN, EOS_TOKEN):
            continue
        words.append(UNK_RENDER if tok == UNK_TOKEN else tok)
    return words


def deanonymize(words: Sequence[str], var_map: Optional[Dict[str, str]]) -> List[str]:
    """Reverse the per-commit variable anonymization (run_model.py:143-146,
    175-177): placeholder -> original identifier."""
    if not var_map:
        return list(words)
    reverse = {v: k for k, v in var_map.items()}
    return [reverse.get(w, w) for w in words]


def cook_prediction(ids: Sequence[int], diff_ids, sub_token_ids, vocab: Vocab,
                    cfg: FiraConfig, *, resolve: bool = True) -> List[str]:
    """Greedy/beam output ids -> anonymized word list (pre-BLEU form)."""
    ids = truncate_at_eos(ids)
    if resolve:
        ids = resolve_copy_ids(ids, diff_ids, sub_token_ids, cfg)
    return ids_to_words(ids, vocab)


def reference_words(msg_ids: Sequence[int], vocab: Vocab) -> List[str]:
    """run_model.py:165-167: the <start>-stripped, <eos>-truncated reference."""
    msg_ids = list(np.asarray(msg_ids).tolist())
    return ids_to_words(truncate_at_eos(msg_ids[1:]), vocab)
