"""Cross-request prefix cache: content-addressed prefill reuse.

At serving scale traffic REPEATS — CI re-runs, monorepo bots, and client
retries send byte-identical diffs — yet every request pays a full
prefill: the encoder pass, the per-beam cross K/V, and the copy-head
source projections (the static, read-only-during-decode half of a seat's
state). vLLM's block-sharing design (PAPERS.md "Continuous batching /
inference serving", SOSP '23) showed content-addressed read-only reuse is
the biggest serve-throughput lever short of new hardware; this module is
that lever under this stack's architecture (docs/DECODE_ENGINE.md
"Prefix cache & dedup"):

- **Content address**: a request's identity is a KEYED blake2b digest of
  its packed wire payload — every non-host-only field's bytes, dtype, and
  shape (the keyed-digest idiom of robust/faults.py: no process-global
  hashing, deterministic across processes and thread schedules). The
  digest is computed HOST-side, worker-side where a feeder assembles the
  payload (data/feeder.py ``stamp=``, serve/server._request_tasks), and
  on demand in the engine otherwise.
- **Prefill-result cache** (:class:`PrefixCache`): digest -> the per-row
  prefill artifacts, held as HOST numpy copies (one D2H per cache-filling
  prefill — prefill is already a dispatch boundary). On a hit the engine
  assembles a staged chunk from cached rows with plain numpy + ONE
  ``device_put`` and seats it WITHOUT dispatching prefill: no compiled
  program runs, so the program family — and the zero-post-warmup-retrace
  contract — is untouched by construction. Capacity-bounded LRU
  (``cfg.prefix_cache_entries``); while a fault injector arms the
  ``cache.lookup`` site, every entry carries a content checksum verified
  at lookup, so a corrupt-injected read is DETECTED and the entry
  dropped (a miss, never a wrong answer — the chaos legs pin exactly
  this; unarmed, entries are trusted process memory like every other
  host buffer, and hashing megabytes of artifacts per hit would tax the
  scheduler thread the cache exists to relieve).
- **In-flight dedup** rides the same digests: byte-identical requests
  already admitted coalesce onto the existing seat with fan-out delivery
  at harvest (one decode, N output positions). The maps live in the
  engine (per replica) and the serve loop (fleet-global);
  this module only provides the addressing.

Equivalence contract: a cache-hit seat decodes from BIT-identical
artifact values (``device_put(device_get(x))`` round-trips exactly), so
its (tokens, probs) — hence its output bytes — equal the cold run's
(tests/test_prefix_cache.py, all four kv-cache x factored-topk modes,
paged and unpaged).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

# the keyed-digest discipline of robust/faults.py: never Python hash()
# (salted per process), always a keyed blake2b over explicit bytes
_DIGEST_KEY = b"fira-prefix-cache-v1"

# the per-row prefill artifact fields, by engine mode (the chunk keys of
# decode/engine.SlotEngine._prefill_fn minus the scalar dtype marker)
ARTIFACT_FIELDS_KV = ("src_mask", "diff", "sub_token",
                      "cross_k", "cross_v", "src_proj")
ARTIFACT_FIELDS_NOKV = ("src_mask", "diff", "sub_token", "states")


def _digest_arrays(items: Iterable[Tuple[str, np.ndarray]],
                   namespace: bytes = b"") -> str:
    """Keyed blake2b over (name, dtype, shape, bytes) of each array —
    shape/dtype are hashed so a bucket geometry change can never alias a
    content match across geometries. ``namespace`` (the serving tier's
    digest namespace, decode/quant.tier_namespace) prefixes the hash so
    artifacts produced under different low-precision tiers can never
    alias: a tier change is a cache MISS, never a wrong answer. Empty —
    digests byte-identical to before — on the f32/f32 contract path."""
    h = hashlib.blake2b(key=_DIGEST_KEY, digest_size=16)
    if namespace:
        h.update(namespace)
    for name, arr in items:
        a = np.ascontiguousarray(arr)
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def payload_digests(host: Dict, namespace: bytes = b""
                    ) -> List[Optional[str]]:
    """One content digest per VALID row of a packed host batch (None for
    pad rows): every wire field (host-only "_" keys and the positional
    ``valid`` mask excluded) contributes its row's bytes. Two rows digest
    equal iff their packed payloads are byte-identical at the same
    geometry AND the same ``namespace`` (the serving tier's —
    decode/quant.tier_namespace; empty on the f32/f32 contract path) —
    the dedup/cache identity."""
    valid = np.asarray(host["valid"], dtype=bool)
    fields = sorted(k for k in host if not k.startswith("_") and k != "valid")
    out: List[Optional[str]] = []
    for r in range(valid.shape[0]):
        out.append(_digest_arrays(((f, np.asarray(host[f])[r])  # firacheck: allow[HOST-SYNC] packed host batches are numpy already (the feeder assembles on host); digesting their bytes is pure host work, no device value exists here
                                   for f in fields), namespace)
                   if valid[r] else None)
    return out


def stamp_digests(host: Dict, namespace: bytes = b"") -> Dict:
    """Attach ``_digests`` (host-only metadata, stripped from the wire by
    the feeder like every "_" key) to a packed batch — the worker-side
    stamping hook (data/feeder.assembly_tasks ``stamp=``,
    serve/server._request_tasks), so the scheduler thread never pays the
    hashing. ``namespace``: same tier namespacing as
    :func:`payload_digests` — the stamping side and the engine's on-demand
    side both derive it from the SAME cfg, so they always agree."""
    host["_digests"] = payload_digests(host, namespace)
    return host


def payload_nbytes(payload: Dict[str, np.ndarray]) -> int:
    return sum(int(np.asarray(v).nbytes) for v in payload.values())


def payload_checksum(payload: Dict[str, np.ndarray]) -> str:
    """Content checksum of one per-row artifact payload — the SAME keyed
    digest the cache's integrity check uses, exported as the transport
    verification seam: a prefill worker stamps it at produce time
    (serve/disagg.py), the decode side recomputes it at seat, and any
    in-flight scramble (the ``disagg.transport`` corrupt site) is caught
    as a mismatch and re-prefilled — never a wrong answer."""
    return _digest_arrays(sorted(payload.items()))


def extract_payloads(chunk_host: Dict[str, np.ndarray], rows: List[int],
                     beam: int) -> Dict[int, Dict[str, np.ndarray]]:
    """Slice one prefilled chunk's HOST copy into per-row cache payloads.
    Row r owns beam lanes ``r*K..(r+1)*K`` of the K-repeated arrays
    (cross_k/cross_v on axis 1, src_proj/states on axis 0) — and those K
    lanes are byte-identical by construction (the prefill's
    ``jnp.repeat``), so the payload stores ONE lane and :func:`build_chunk`
    re-repeats it: 1/K the host RAM, hashing, and byte-budget charge for
    a bit-identical rebuild. ``seed`` records the cache-seed dtype so a
    rebuilt chunk reproduces the prefill pytree exactly."""
    K = int(beam)
    kv = "cross_k" in chunk_host
    out: Dict[int, Dict[str, np.ndarray]] = {}
    for r in rows:
        p: Dict[str, np.ndarray] = {
            "src_mask": np.ascontiguousarray(chunk_host["src_mask"][r]),
            "diff": np.ascontiguousarray(chunk_host["diff"][r]),
            "sub_token": np.ascontiguousarray(chunk_host["sub_token"][r]),
        }
        if kv:
            p["cross_k"] = np.ascontiguousarray(
                chunk_host["cross_k"][:, r * K:r * K + 1])
            p["cross_v"] = np.ascontiguousarray(
                chunk_host["cross_v"][:, r * K:r * K + 1])
            p["src_proj"] = np.ascontiguousarray(
                chunk_host["src_proj"][r * K:r * K + 1])
            p["seed"] = np.zeros((), chunk_host["cache_seed"].dtype)
        else:
            p["states"] = np.ascontiguousarray(
                chunk_host["states"][r * K:r * K + 1])
        out[r] = p
    return out


def build_chunk(payloads: Dict[int, Dict[str, np.ndarray]], batch_rows: int,
                beam: int) -> Dict[str, np.ndarray]:
    """Assemble a staged-chunk pytree from cached per-row payloads: the
    EXACT key set, shapes, and dtypes of the prefill program's output for
    this geometry (so the insert program sees the same pytree structure
    it was traced with — a cache hit can never retrace). Rows without a
    payload (pad rows, coalesced rows) stay zero; the insert scatter
    drops them via the sentinel slot id, so their values are never read."""
    C, K = int(batch_rows), int(beam)
    any_p = next(iter(payloads.values()))
    kv = "cross_k" in any_p
    out: Dict[str, np.ndarray] = {}
    for f in ("src_mask", "diff", "sub_token"):
        a = any_p[f]
        out[f] = np.zeros((C,) + a.shape, a.dtype)
    if kv:
        ck = any_p["cross_k"]          # (L, 1, ...) — one stored lane
        L = ck.shape[0]
        for f in ("cross_k", "cross_v"):
            out[f] = np.zeros((L, C * K) + ck.shape[2:], ck.dtype)
        sp = any_p["src_proj"]         # (1, ...)
        out["src_proj"] = np.zeros((C * K,) + sp.shape[1:], sp.dtype)
        out["cache_seed"] = np.zeros((), any_p["seed"].dtype)
    else:
        st = any_p["states"]           # (1, ...)
        out["states"] = np.zeros((C * K,) + st.shape[1:], st.dtype)
    for r, p in payloads.items():
        for f in ("src_mask", "diff", "sub_token"):
            out[f][r] = p[f]
        # re-repeat the single stored lane across the K beam slots —
        # bitwise what the prefill's jnp.repeat produced
        if kv:
            out["cross_k"][:, r * K:(r + 1) * K] = np.repeat(
                p["cross_k"], K, axis=1)
            out["cross_v"][:, r * K:(r + 1) * K] = np.repeat(
                p["cross_v"], K, axis=1)
            out["src_proj"][r * K:(r + 1) * K] = np.repeat(
                p["src_proj"], K, axis=0)
        else:
            out["states"][r * K:(r + 1) * K] = np.repeat(
                p["states"], K, axis=0)
    return out


@dataclasses.dataclass
class _Entry:
    payload: Dict[str, np.ndarray]
    checksum: Optional[str]  # keyed digest of the payload content —
    #                          computed/verified only while a fault
    #                          injector arms cache.lookup (the only
    #                          writer between put and take IS that
    #                          injector's corrupt; hashing megabytes of
    #                          artifacts per hit on the scheduler thread
    #                          would tax exactly the path the cache
    #                          exists to make cheap)
    nbytes: int


class PrefixCache:
    """Capacity-bounded LRU of per-row prefill artifacts, content-
    addressed by payload digest. Host-side only: no device memory, no
    compiled programs, no locks (the scheduler thread owns it — one
    instance per engine replica, per-chip like the arena it feeds).

    ``take`` is the metered lookup: LRU-touches on a hit, and — while an
    injector arms the ``cache.lookup`` site — runs the fault check (a
    raise demotes the lookup to a miss) and verifies the entry's content
    checksum (a corrupt-injected read is dropped, never served).
    ``contains`` is the non-mutating probe the serve loop partitions
    batches with.
    """

    def __init__(self, entries: int, *, max_bytes: int = 0, faults=None):
        if int(entries) < 1:
            raise ValueError(
                f"prefix cache needs >= 1 entry of capacity, got {entries}")
        if int(max_bytes) < 0:
            raise ValueError(
                f"prefix cache byte budget must be >= 0, got {max_bytes}")
        self.capacity = int(entries)
        # optional host-RAM bound: artifact payloads are MBs per entry at
        # production geometry, so the entry cap alone can pin gigabytes
        self.max_bytes = int(max_bytes)
        self._nbytes = 0
        self._lru: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        self._faults = faults
        self._lookups = 0   # deterministic event key for the fault site

    def _integrity(self) -> bool:
        """Content checksums are maintained exactly while the
        ``cache.lookup`` fault site is armed — corrupt-injection is the
        one writer between put and take, and the chaos contract is that
        its scramble is DETECTED and dropped, never served."""
        return self._faults is not None and self._faults.armed(
            "cache.lookup")

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def contains(self, digest: Optional[str]) -> bool:
        return digest is not None and digest in self._lru

    def take(self, digest: str
             ) -> Tuple[Optional[Dict[str, np.ndarray]], str]:
        """(payload, outcome) — outcome one of ``hit`` / ``miss`` /
        ``fault_miss`` (injected lookup raise, absorbed here: a cache
        fault must never become a wrong answer or a shed request) /
        ``integrity_drop`` (content checksum mismatch: the entry is
        evicted and the caller re-prefills)."""
        entry = self._lru.get(digest)
        if entry is None:
            return None, "miss"
        payload = entry.payload
        if self._integrity():
            self._lookups += 1
            try:
                self._faults.check("cache.lookup", key=self._lookups)
            except Exception:
                return None, "fault_miss"
            payload = self._faults.corrupt("cache.lookup", self._lookups,
                                           payload)
            if (entry.checksum is not None
                    and payload_checksum(payload) != entry.checksum):
                del self._lru[digest]
                self._nbytes -= entry.nbytes
                return None, "integrity_drop"
        self._lru.move_to_end(digest)
        return payload, "hit"

    def put(self, digest: str, payload: Dict[str, np.ndarray]) -> int:
        """Insert/refresh one entry; returns how many LRU entries were
        evicted to make room (the eviction meter). Eviction honors both
        bounds: the entry cap AND, when ``max_bytes`` is set, the host
        byte budget (an over-budget entry alone still lives — the cache
        degrades to capacity one, never refuses to serve)."""
        old = self._lru.get(digest)
        if old is not None:
            self._nbytes -= old.nbytes
        entry = _Entry(
            payload=payload,
            checksum=(payload_checksum(payload)
                      if self._integrity() else None),
            nbytes=payload_nbytes(payload))
        self._lru[digest] = entry
        self._lru.move_to_end(digest)
        self._nbytes += entry.nbytes
        evicted = 0
        while len(self._lru) > self.capacity or (
                self.max_bytes and self._nbytes > self.max_bytes
                and len(self._lru) > 1):
            _d, e = self._lru.popitem(last=False)
            self._nbytes -= e.nbytes
            evicted += 1
        return evicted

    def clear(self) -> None:
        self._lru.clear()
        self._nbytes = 0
