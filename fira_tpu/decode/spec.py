"""Speculative copy-head draft-and-verify decode for the slot engine.

The slot engine (decode/engine.py) dispatches one step program per emitted
beam position per round: with ingest unthrottled and the fleet self-healing,
that dispatch cadence IS the serving ceiling. FIRA's dual copy mechanism
makes commit-message tokens unusually draftable — a large fraction is copied
verbatim from the diff — so a near-free DRAFTER proposes ``k`` tokens per
live slot and ONE fixed-shape VERIFY program advances up to k beam positions
per dispatch, accepting the longest drafted prefix that the real beam math
agrees with (Leviathan et al., ICML 2023; Chen et al. 2023 — PAPERS.md
"Speculative decoding").

Exactness is BY CONSTRUCTION, not by comparison tolerance. The verify
program is a ``lax.while_loop`` whose body is the engine's own
``_one_step`` — the identical per-position HLO the plain step runs — gated
per row: frame 0 advances every live slot unconditionally (progress >= 1,
exactly the plain step), frame j+1 advances only rows whose frame-j emitted
top-beam token (beam.top_beam_token; selection's top_k is prob-descending,
so beam 0 is the running best) equalled ``drafts[:, j]``. The loop exits
early once no gated row remains (the engine twin of beam._run_steps's
early-exit predicate). Every position the verify advances therefore ran the
exact step math the plain engine would have run, and every position it did
NOT advance is simply run later by a subsequent dispatch — so tokens, probs,
and file bytes are invariant to ``k``, the acceptance pattern, the harvest
cadence, and the replica count (tests/test_spec.py pins all of it, in all
four kv x factored modes, paged and unpaged). "Rollback" of rejected tails
is free: a frozen row's state is blended to its old values (the plain
step's own inactive-row discipline), its paged block table is
sentinel-masked (no append, no permute), and its unpaged cache rows are
identity-permuted (see the gated branch in engine._one_step) — the one
place the plain step's scribble-on-inactive-rows shortcut would corrupt a
row that RESUMES.

Drafter tiers (cfg.spec_decode):

- ``copy``: the copy-head distribution ALONE — pointer scores from the
  cached source projections (state["src_proj"], computed once at prefill)
  against the raw target embedding proxy (model.copy_draft_scores: embed +
  position row, NO decoder layer). Near-free: k tiny matvec/tanh passes per
  dispatch. Rides FIRA's measured verbatim-copy fraction.
- ``draft``: a greedy argmax roll of the existing cached step program on
  each slot's TOP BEAM only — 1/beam of the step's decoder rows, against
  scratch copies of the beam-0 caches (paged mode gathers the beam-0 lane
  dense via layers.gather_block_kv_beam; the real pool/arena is never
  written by a drafter). Costlier, higher acceptance on generated spans.

Both tiers emit RESOLVED vocab ids (beam._resolve_copy — the same id space
the beam stores at extension time), so drafted-vs-emitted comparison is a
plain int equality. Draft quality moves only the acceptance rate, never
output bytes.

Program family: ``engine_draft[k<k>...]`` + ``engine_verify[k<k>...]``, one
fixed-(S, k) member each, declared in the compile-guard family next to the
step/insert/harvest programs (replica tags compose: ``engine_verify[k4.r1]``)
— zero post-warmup retraces with spec armed.

Low-precision serving tiers (decode/quant.py) compose with NO code here:
the drafter's scratch caches inherit the arena's storage dtype (the unpaged
beam-0 slice stays bf16 and decode_step_multi's read-upcast rule handles
it; the paged gather_block_kv_beam upcasts at the gather), and the engine
wraps the drafter so the int8w weight tier dequantizes at the draft trace
top exactly like the step/verify programs. Draft math under a tier is
acceptance-only — the verify body is still the engine's own step program on
the engine's own params, so the within-tier exactness argument above is
unchanged: accepted prefixes are bit-identical to that tier's plain decode
(labels carry the tier suffix, e.g. ``engine_verify[k4.bf16kv.int8w.r1]``).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from fira_tpu.config import FiraConfig
from fira_tpu.decode import paging
from fira_tpu.decode.beam import (_resolve_copy, scatter_token,
                                  step_valid_mask, top_beam_token)
from fira_tpu.model.layers import gather_block_kv_beam
from fira_tpu.model.model import FiraModel

DRAFT_LABEL = "engine_draft"
VERIFY_LABEL = "engine_verify"

SPEC_TIERS = ("off", "copy", "draft")

# plain step dispatches run after a verify whose drafts ALL missed, before
# re-arming: a stalled drafter (e.g. mid rare-token span) should not pay a
# draft+verify dispatch per emitted token. Scheduling only — output bytes
# are invariant to the cooldown by the exactness argument above.
STALL_COOLDOWN = 4


def spec_errors(cfg: FiraConfig) -> List[str]:
    """Parse-time validation for the speculative-decode knobs (the
    paging.paging_errors convention: named-knob messages, CLI exit 2).

    - ``spec_decode`` must be one of {off, copy, draft};
    - spec requires ``decode_engine`` (the drafter/verify programs are
      members of the slot engine's program family — there is nothing to
      arm on the batched-beam path);
    - ``engine_spec_k`` must fit the smallest declared decode tar budget:
      1 <= k <= min(tar) - 1 (a verify window past the budget could never
      accept its tail — the <start> column is not generated).
    """
    errs: List[str] = []
    if cfg.spec_decode not in SPEC_TIERS:
        errs.append(
            f"spec_decode {cfg.spec_decode!r} not in {set(SPEC_TIERS)}")
        return errs
    if cfg.spec_decode == "off":
        return errs
    if not cfg.decode_engine:
        errs.append(
            f"spec_decode={cfg.spec_decode!r} requires decode_engine: the "
            f"drafter/verify programs extend the slot engine's program "
            f"family (enable decode_engine or set spec_decode='off')")
    k = int(cfg.engine_spec_k)
    budget = min(paging.declared_decode_tars(cfg)) - 1
    if not 1 <= k <= budget:
        errs.append(
            f"engine_spec_k {k} outside [1, {budget}]: the verify window "
            f"must fit the smallest declared decode tar budget "
            f"({budget + 1} positions, decode_tar_buckets/tar_len) minus "
            f"the <start> column")
    return errs


def copy_biased_params(params, delta: float = 6.0,
                       target_blind: bool = False):
    """A paramset whose gen/copy gate leans hard toward the COPY side, so
    decode emits mostly copied source tokens — the regime the ``copy``
    drafter exists for. Test/bench utility (the beam.eos_biased_params
    convention; shared here so the copy_net param paths cannot drift
    between the spec tests and the bench legs).

    ``target_blind=True`` additionally zeroes the copy head's target
    projection, making pointer scores a pure function of the cached source
    projection: the drafter's raw-embedding proxy then scores EXACTLY what
    the real step scores, so copy-tier acceptance saturates — the
    deterministic best case the acceptance-sweep tests pin. (Exactness of
    the OUTPUT never depends on any of this — only the acceptance rate
    moves.)"""
    import numpy as np

    cn = params["copy_net"]
    bias = np.asarray(cn["gate"]["bias"]).copy()
    bias[0] -= delta
    bias[1] += delta
    new_cn = {**cn, "gate": {**cn["gate"], "bias": jnp.asarray(bias)}}
    if target_blind:
        new_cn["tgt_proj"] = {
            **cn["tgt_proj"],
            "kernel": jnp.zeros_like(cn["tgt_proj"]["kernel"])}
    return {**params, "copy_net": new_cn}


def make_drafter(model: FiraModel, cfg: FiraConfig, slots: int, paged: bool):
    """Build the (params, state) -> (S, k) int32 drafter for this engine's
    tier/geometry. Pure function of the engine state — drafters never write
    real state (the scratch caches of the ``draft`` tier live and die in
    the scan carry), so the engine jits the result WITHOUT donation and the
    verify that follows donates the untouched arena as usual."""
    K, T = cfg.beam_size, cfg.tar_len
    L, H = cfg.num_layers, cfg.num_head
    d_head = cfg.embedding_dim // H
    V = cfg.vocab_size
    k = int(cfg.engine_spec_k)
    tier = cfg.spec_decode

    def resolve(choice, state):
        """Fused-space choice -> resolved vocab id, the id space the beam
        stores (beam._resolve_copy over this slot arena's sources)."""
        return _resolve_copy(choice[:, None], state["diff"],
                             state["sub_token"], cfg)[:, 0]

    def roll(state, body):
        """Drive one drafter micro-step k times from each slot's top-beam
        token at its current depth; stack proposals to (S, k)."""
        pos0 = jnp.minimum(state["pos"], T - 2)
        flat0 = state["tokens"][:, 0, :]            # (S, T) resolved ids
        tok0 = jnp.take_along_axis(flat0, pos0[:, None], axis=1)[:, 0]
        return body(flat0, tok0, pos0)

    if tier == "copy":

        def drafter(params, state):
            if cfg.beam_kv_cache:
                src_proj0 = state["src_proj"][0::K]  # beam-0 cached rows
            else:
                # the no-KV arena holds raw encoder states, not decode_init
                # artifacts: project the beam-0 rows here (one matmul —
                # still no decoder stack)
                src_proj0 = model.apply(
                    {"params": params}, state["states"][0::K],
                    method=lambda m, s: m.copy_net.project_src(s))
            mask = state["src_mask"]

            def body(flat0, tok0, pos0):
                def step(carry, _):
                    tok, p = carry
                    scores = model.apply(
                        {"params": params}, mask, src_proj0, tok[:, None],
                        p, method=FiraModel.copy_draft_scores)
                    choice = V + jnp.argmax(
                        scores[:, 0, :], axis=-1).astype(jnp.int32)
                    nxt = resolve(choice, state)
                    return (nxt, jnp.minimum(p + 1, T - 2)), nxt

                _, drafts = jax.lax.scan(step, (tok0, pos0), None, length=k)
                return drafts.T                     # (k, S) -> (S, k)

            return roll(state, body)

        return drafter

    assert tier == "draft", tier

    def drafter(params, state):
        mask = state["src_mask"]
        if not cfg.beam_kv_cache:
            states0 = state["states"][0::K]

            def body(flat0, tok0, pos0):
                def step(carry, _):
                    flat, p = carry
                    tar_mask = (flat != 0).at[:, 0].set(True)
                    fused = model.apply(
                        {"params": params}, states0, mask, flat, tar_mask,
                        method=FiraModel.fused_probs)
                    at_p = jnp.take_along_axis(
                        fused, p[:, None, None], axis=1)[:, 0, :]
                    nxt = resolve(
                        jnp.argmax(at_p, axis=-1).astype(jnp.int32), state)
                    p2 = jnp.minimum(p + 1, T - 2)
                    return (scatter_token(flat, p2, nxt), p2), nxt

                _, drafts = jax.lax.scan(
                    step, (flat0, pos0), None, length=k)
                return drafts.T

            return roll(state, body)

        cross_k0 = state["cross_k"][:, 0::K]
        cross_v0 = state["cross_v"][:, 0::K]
        src_proj0 = state["src_proj"][0::K]
        if paged:
            # dense SCRATCH view of each slot's beam-0 lane: the pool is
            # read once per draft and never written (sentinel table rows of
            # idle/done slots clamp to garbage the validity mask zeroes)
            tab = state["block_tab"]
            k_sc = jnp.stack([gather_block_kv_beam(state["k_pool"][l], tab, 0)
                              for l in range(L)])
            v_sc = jnp.stack([gather_block_kv_beam(state["v_pool"][l], tab, 0)
                              for l in range(L)])
        else:
            k_sc = state["k_cache"].reshape(L, -1, K, H, T, d_head)[:, :, 0]
            v_sc = state["v_cache"].reshape(L, -1, K, H, T, d_head)[:, :, 0]

        def body(flat0, tok0, pos0):
            def step(carry, _):
                flat, p, kc, vc = carry
                valid = step_valid_mask(flat, p, T)
                tok_in = jnp.take_along_axis(flat, p[:, None], axis=1)
                fused, kc, vc = model.apply(
                    {"params": params}, mask, tok_in, p, kc, vc,
                    cross_k0, cross_v0, src_proj0,
                    valid[:, None, None, :],
                    method=FiraModel.fused_probs_step_multi)
                nxt = resolve(
                    jnp.argmax(fused[:, 0, :], axis=-1).astype(jnp.int32),
                    state)
                p2 = jnp.minimum(p + 1, T - 2)
                return (scatter_token(flat, p2, nxt), p2, kc, vc), nxt

            _, drafts = jax.lax.scan(
                step, (flat0, pos0, k_sc, v_sc), None, length=k)
            return drafts.T

        return roll(state, body)

    return drafter


def run_verify(step_gated, state, drafts, k: int, tar_len: int):
    """The draft-and-verify acceptance loop: up to ``k`` gated exact step
    frames in one dispatch.

    ``step_gated(st, gate)`` is the engine's ``_one_step`` partially
    applied over params — (state', active-row count). Frame 0 runs every
    live row (gate starts all-True: exactly the plain step, so one verify
    dispatch NEVER does less than one plain dispatch); frame j+1 keeps a
    row gated in only while frame j's emitted top-beam token equalled
    ``drafts[:, j]`` and the row did not settle. The loop exits as soon as
    no gated row remains — a fully-missed draft costs exactly one plain
    step's frames.

    Returns (state', occ_entry, counters) with counters =
    [tested, matched, iters]: row-frames advanced (the plain dispatches
    this verify replaced, occ_entry of them owed anyway), drafted-token
    agreements, and while-loop iterations (device-compute honesty: each
    frame costs one plain step's FLOPs). All three ride back as ONE stacked
    device vector the engine drains at harvest — its designated sync
    boundary — so spec metering adds no host sync."""
    S = drafts.shape[0]
    active0 = state["live"] & ~state["done"]
    occ_entry = jnp.sum(active0.astype(jnp.int32))
    z = jnp.int32(0)

    def cond(carry):
        st, gate, j, _tested, _matched = carry
        return (j < k) & jnp.any(st["live"] & ~st["done"] & gate)

    def body(carry):
        st, gate, j, tested, matched = carry
        act = st["live"] & ~st["done"] & gate
        pos_c = jnp.minimum(st["pos"], tar_len - 2)
        st2, occ = step_gated(st, gate)
        emitted = top_beam_token(st2["tokens"], pos_c + 1)
        draft_j = jax.lax.dynamic_slice_in_dim(drafts, j, 1, axis=1)[:, 0]
        match = act & (emitted == draft_j)
        # rows that were not stepped this frame keep their gate: their
        # fate was already decided (or they are idle/done and act-masked)
        gate = jnp.where(act, match, gate)
        return (st2, gate, j + 1, tested + occ,
                matched + jnp.sum(match.astype(jnp.int32)))

    st, _gate, iters, tested, matched = jax.lax.while_loop(
        cond, body, (state, jnp.ones((S,), bool), z, z, z))
    return st, occ_entry, jnp.stack([tested, matched, iters])
