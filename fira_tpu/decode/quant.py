"""Low-precision serving tiers for the slot engine (docs/DECODE_ENGINE.md
"Low-precision tiers").

Two independent knobs, f32 staying the default CONTRACT path (labels,
digests, and output bytes unchanged when both are "f32"):

- ``cfg.kv_dtype`` ("f32" | "bf16") — storage dtype of the decode
  self-attention K/V arena: the paged pool's blocks AND the unpaged
  comparator stripes. The prefill program emits a ``cache_seed`` of this
  dtype (:func:`kv_seed_dtype`), so the engine's arena allocation and its
  ``kv_bytes_per_slot`` accounting follow automatically; writes cast on
  append (model/layers.append_block_kv, the dense ``.at[].set`` sites) and
  reads upcast on gather, so the attention math itself stays in the
  compute dtype. Cross-attention K/V and the copy-head source projection
  are request-lifetime activations, not the per-step arena — they stay
  f32.

- ``cfg.serve_precision`` ("f32" | "bf16" | "int8w") — weight tier of the
  DECODE-ONLY program family (step / spec draft / verify; prefill and the
  encoder keep the original params). The engine builds a quantized COPY of
  the dominant matmul weights once at construction
  (:func:`quantize_decode_params` over :data:`DECODE_WEIGHT_SCOPES` —
  decoder stack, vocab projection, copy head); a fleet respawn or spare
  prewarm re-runs it by constructing a fresh engine from the original
  params. "bf16" stores the weights half-width and the existing
  ``kernel.astype(dtype)`` upcast in the matmul layers consumes them;
  "int8w" stores per-channel symmetric int8 (:func:`quantize_int8`) and
  the step programs dequantize on the fly with f32 accumulate
  (:func:`dequant_tree` at the top of the traced step — the scales embed
  as trace-time constants, so static shapes and the declared program
  family are unchanged, labels merely suffixed via :func:`tier_tag`).

The quality contract is MEASURED, never assumed: bench records carry
``bleu_delta_vs_f32`` and ``logprob_divergence_{mean,p99}`` vs the f32
reference (docs/QUANT_BENCH_r01.jsonl), and within a tier output bytes
remain a pure function of the input stream (the engine's existing
determinism contract, re-pinned per tier in tests/test_quant_tiers.py).

Precedent: LLM.int8() (Dettmers et al.) for post-training per-channel
int8 weights with higher-precision accumulate; GShard/T5 for static-shape
mixed precision on TPU; vLLM for KV bytes — not FLOPs — capping slot
concurrency (PAPERS.md).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

KV_DTYPES = ("f32", "bf16")
SERVE_PRECISIONS = ("f32", "bf16", "int8w")

# param subtrees the weight tier rewrites: the decode-side matmul owners.
# The encoder (prefill-only) and everything 1-D (biases, LayerNorm
# scales) keep the original f32 params.
DECODE_WEIGHT_SCOPES = ("decoder", "out_fc", "copy_net")


def quant_errors(cfg, *, train: bool = False) -> List[str]:
    """Parse-time validation for the serving-tier knobs. ``train=True``
    is the training path, where any non-f32 tier is rejected outright:
    quantized serving reads frozen weights, it never trains them."""
    errs: List[str] = []
    if cfg.kv_dtype not in KV_DTYPES:
        errs.append(f"kv_dtype {cfg.kv_dtype!r} not in "
                    f"{{{', '.join(map(repr, KV_DTYPES))}}}")
    if cfg.serve_precision not in SERVE_PRECISIONS:
        errs.append(f"serve_precision {cfg.serve_precision!r} not in "
                    f"{{{', '.join(map(repr, SERVE_PRECISIONS))}}}")
    armed = cfg.kv_dtype != "f32" or cfg.serve_precision != "f32"
    if train and armed:
        errs.append(
            "kv_dtype/serve_precision are serving-tier knobs; the training "
            "path runs full precision — leave both 'f32'")
        return errs
    if cfg.kv_dtype in KV_DTYPES and cfg.kv_dtype != "f32" \
            and not cfg.decode_engine:
        errs.append(
            f"kv_dtype {cfg.kv_dtype!r} requires the slot engine "
            f"(--engine / decode_engine=True): the low-precision KV "
            f"arena is the engine's slot arena")
    if cfg.serve_precision in SERVE_PRECISIONS \
            and cfg.serve_precision != "f32" and not cfg.decode_engine:
        errs.append(
            f"serve_precision {cfg.serve_precision!r} requires the slot "
            f"engine (--engine / decode_engine=True): the weight tier "
            f"quantizes the decode-only program family")
    return errs


def kv_seed_dtype(cfg, compute_dtype):
    """Dtype of the prefill program's ``cache_seed`` marker — what the
    engine allocates its K/V arena at. "f32" keeps the historical rule
    (the encoder-state dtype, which may be wider under stable_residual);
    "bf16" pins the arena half-width regardless of compute dtype."""
    return jnp.bfloat16 if cfg.kv_dtype == "bf16" else compute_dtype


def tier_tag(cfg) -> str:
    """Program-label tier mod ("" on the f32/f32 contract path, so the
    default label set is byte-for-byte unchanged). Composes into the
    engine's mods chain: ``engine_step[bf16kv.int8w.r1]``."""
    parts = []
    if cfg.kv_dtype != "f32":
        parts.append(f"{cfg.kv_dtype}kv")
    if cfg.serve_precision != "f32":
        sp = cfg.serve_precision
        parts.append(sp if sp.endswith("w") else sp + "w")
    return ".".join(parts)


def tier_namespace(cfg) -> bytes:
    """Digest namespace for prefix-cache / dedup content addressing:
    prefill artifacts carry their tier, so a cached f32 artifact can
    never seat a bf16 slot (and vice versa). Empty — digests unchanged —
    on the f32/f32 contract path."""
    tag = tier_tag(cfg)
    return tag.encode("ascii") if tag else b""


# --- per-channel symmetric int8 --------------------------------------------

def quantize_int8(w) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel symmetric int8: channel = LAST axis (the output
    features of every kernel in this stack). scale[c] = amax(|w[..., c]|)
    / 127 (zero columns get scale 1.0 so the divide is exact), values
    round-to-nearest then clip. Max absolute error per element is
    scale/2: |w| <= 127*scale means the clip never binds, so the only
    error is the rounding's half-step (pinned in tests)."""
    a = np.asarray(jax.device_get(w), np.float32)  # firacheck: allow[HOST-SYNC] engine-BUILD-time quantization (once per engine/respawn/spare prewarm, before any serving dispatch); never runs inside the step loop
    reduce_axes = tuple(range(a.ndim - 1))
    scale = np.max(np.abs(a), axis=reduce_axes) / 127.0
    scale = np.where(scale == 0.0, np.float32(1.0), scale).astype(np.float32)
    q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_int8(q, scale):
    """f32 reconstruction (host or traced): int8 codes x per-channel
    scale, broadcast over the last axis."""
    return q.astype(jnp.float32) * scale


def _eligible(leaf) -> bool:
    """Weight-tier eligibility: float leaves of rank >= 2 — the matmul
    kernels and embedding tables. 1-D params (biases, LayerNorm
    scale/bias) stay f32: they are O(d) bytes and numerics-sensitive."""
    return (hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and getattr(leaf, "ndim", 0) >= 2)


def quantize_decode_params(params, cfg):
    """Build the decode-side param tree for ``cfg.serve_precision``.

    Returns ``(decode_params, scales)``:

    - "f32": ``(params, None)`` — the ORIGINAL tree, no copy (identity is
      what the f32 byte-identity contract rides on).
    - "bf16": eligible leaves under :data:`DECODE_WEIGHT_SCOPES` stored
      bf16, everything else shared; ``scales`` is None (the layers' own
      ``astype`` upcast consumes bf16 directly).
    - "int8w": eligible scoped leaves stored int8; ``scales`` mirrors the
      FULL tree (unquantized leaves carry a scalar 1.0 sentinel) so
      :func:`dequant_tree` is one structure-aligned tree.map inside the
      step trace.

    Quantization happens ONCE per engine build — a respawned replica or
    prewarmed spare re-runs it from the original f32 params by
    construction (parallel/fleet.py builds a fresh SlotEngine).
    """
    sp = cfg.serve_precision
    if sp == "f32":
        return params, None
    out = {}
    scales = {} if sp == "int8w" else None
    for k, v in params.items():
        if k not in DECODE_WEIGHT_SCOPES:
            out[k] = v
            if scales is not None:
                scales[k] = jax.tree.map(
                    lambda _l: np.ones((), np.float32), v)
            continue
        leaves, treedef = jax.tree_util.tree_flatten(v)
        if sp == "bf16":
            out[k] = treedef.unflatten([
                np.asarray(jax.device_get(l)).astype(jnp.bfloat16)  # firacheck: allow[HOST-SYNC] engine-BUILD-time weight cast (once per engine/respawn/spare prewarm, before any serving dispatch); never runs inside the step loop
                if _eligible(l) else l for l in leaves])
        else:
            qs, ss = [], []
            for l in leaves:
                if _eligible(l):
                    q, s = quantize_int8(l)
                else:
                    q, s = l, np.ones((), np.float32)
                qs.append(q)
                ss.append(s)
            out[k] = treedef.unflatten(qs)
            scales[k] = treedef.unflatten(ss)
    return out, scales


def dequant_tree(params, scales):
    """On-the-fly dequant at the top of the decode-only traced programs:
    int8 leaves reconstruct to f32 against their per-channel scales
    (embedded as trace-time constants), every other leaf passes through.
    ``scales is None`` (f32/bf16 tiers) is the identity — the call sites
    stay branch-free in the trace."""
    if scales is None:
        return params

    def dq(p, s):
        if p.dtype == jnp.int8:
            return dequantize_int8(p, s)
        return p

    return jax.tree.map(dq, params, scales)
