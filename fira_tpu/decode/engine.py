"""Slot-refill continuous-batching decode engine.

The batched beam (decode/beam.py) dispatches whole batches: even with
``beam_early_exit`` the while_loop runs until the batch's LONGEST message
settles, so on real corpora (mean message ~8-10 tokens against the
tar_len-1 = 29 step budget) most rows of a dispatch are finished beams
burning device cycles. This module applies iteration-level continuous
batching (Orca, OSDI '22) under this stack's static-shape regime (slots as
a fixed-geometry KV arena, vLLM SOSP '23 — PAPERS.md "Continuous batching
/ inference serving"): a fixed arena of S slots, each holding one
sample's beam mid-flight at its OWN decode depth, advanced one token per
step program; settled slots are harvested and refilled with freshly
prefilled requests, so wall clock scales with TOTAL TOKENS EMITTED, not
with per-batch max length.

Program family (all fixed-shape, labelled for the compile guard —
``engine_prefill[<geom>]`` x the decode bucket table, ``engine_step``,
``engine_insert``; zero post-warmup retraces):

- **prefill** (one per decode bucket geometry): encoder forward + per-beam
  cross-attention K/V + copy-head source projection for ONE packed batch
  of new requests — exactly the per-batch preamble of the batched beam, on
  exactly the batches the existing bucketed/sorted packer emits (the
  feeder assembles and ships them asynchronously, as for every driver).
- **step** (single geometry — the bucketable axes never reach the decoder:
  ``sou_len``/``sub_token_len`` are pinned by the copy-label id space and
  decode pins ``tar_len`` full): advance every live slot's beam
  ``cfg.engine_harvest_every`` positions at the slot's own depth
  (model.dist_parts_step_multi / fused_probs_step_multi; the per-row
  ``s`` vector path of beam._selection_tail), with a per-slot
  finished/done mask instead of the batch path's global early-exit
  predicate. Idle/done slots compute garbage that is blended away — they
  are the occupancy loss the refill loop exists to keep near zero.
- **insert**: scatter up to one prefilled chunk's rows into freed slots
  (slot ids are data, not shapes: a (C,) vector with the out-of-range
  sentinel S marking rows not consumed this call, ``mode="drop"``).

Equivalence contract (pinned by tests/test_engine.py in all four
kv-cache x factored-topk modes): per sample, the engine's (tokens, probs)
are BIT-EXACT equal to the batched beam's. The argument has three legs:

1. beam search is per-sample independent — every batched-beam op acts
   row-wise (embeds, per-row matmuls, attention over the row's own
   sequence, per-row top-k), so a sample's trajectory does not depend on
   its batch neighbours (the test_batch_size knob already rides on this);
2. the step program runs the SAME selection math at a per-row position
   vector (beam._selection_tail treats scalar and vector ``s``
   identically per row), against the same prefill values the batched
   beam computes (same packed batches, same encode/decode_init program
   prefix);
3. per-slot termination replicates the early-exit predicate exactly —
   done = all-finished-before-step AND all-finished-after (the settling
   step that re-sorts beams), or position exhausted — and
   tests/test_beam_early_exit.py already pins that stopping there equals
   running the full scan.

Host scheduler (:meth:`SlotEngine.run`): drains the packer stream via the
async feeder, prefills ahead (``cfg.engine_prefill_depth`` chunks),
refills every freed slot, steps, harvests settled slots, and yields one
:class:`EngineItem` per sample AS IT SETTLES (out of split order — the
ordered streaming writer, decode/stream.py, restores order on disk). The
per-dispatch ``done`` readback is the engine's designated sync boundary:
the refill decision is host-side by construction.

The scheduler is exposed as STEPPABLE pieces — ``begin_stream`` /
``wants_input`` / ``admit`` / ``refill`` / ``step_dispatch`` / ``harvest``
— and ``run()`` is just the single-engine loop over them. The replicated
decode fleet (parallel/fleet.py) round-robins the SAME pieces over N
engine instances pulling from one shared admission queue, so the fleet
inherits the single engine's scheduling semantics (and its per-sample
bit-exactness) by construction instead of re-implementing them.
``device``/``tag`` pin a replica to its own chip and suffix its guard
labels (``engine_step[r0]``), keeping the one-compile-per-label contract
honest when N replicas each compile their own program set.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fira_tpu.analysis.sanitizer import program_label
from fira_tpu.config import FiraConfig
from fira_tpu.decode.beam import _init_beam, _select, _select_factored
from fira_tpu.model.model import FiraModel

PREFILL_KIND = "engine_prefill"
STEP_LABEL = "engine_step"
INSERT_LABEL = "engine_insert"


@dataclasses.dataclass
class EngineStats:
    """Dispatch/occupancy accounting for one engine run."""

    slots: int
    prefills: int = 0            # prefill program dispatches (chunks)
    refills: int = 0             # insert program dispatches
    slots_refilled: int = 0      # slot fills across all inserts
    steps: int = 0               # beam MICRO-steps run (cadence x dispatches)
    step_dispatches: int = 0     # step program dispatches
    occupied_slot_steps: int = 0  # exact count of (slot, micro-step) pairs
                                  # that did real beam work (device-counted)
    commits: int = 0             # samples harvested

    @property
    def slot_occupancy(self) -> float:
        """Mean fraction of slots doing real beam work per micro-step."""
        total = self.steps * self.slots
        return self.occupied_slot_steps / total if total else 0.0

    @property
    def steps_per_commit(self) -> float:
        return self.steps / self.commits if self.commits else 0.0

    @property
    def dispatches(self) -> int:
        return self.prefills + self.refills + self.step_dispatches

    def summary(self) -> Dict[str, float]:
        return {
            "slots": self.slots,
            "prefills": self.prefills,
            "refills": self.refills,
            "slots_refilled": self.slots_refilled,
            "steps_run": self.steps,
            "step_dispatches": self.step_dispatches,
            "commits": self.commits,
            "dispatches": self.dispatches,
            "slot_occupancy": round(self.slot_occupancy, 4),
            "steps_per_commit": round(self.steps_per_commit, 3),
        }


@dataclasses.dataclass
class EngineItem:
    """One settled sample: the per-sample view of the batched beam's
    output — ``tokens[argmax(probs)]`` is the prediction, copy ids already
    resolved at extension time (identical contract to decode/beam.py)."""

    position: int        # split-local sample position (output order key)
    host: Dict           # the host batch this sample rode in on
    row: int             # its row within that batch (indexes host fields)
    tokens: np.ndarray   # (beam, tar_len) int32
    probs: np.ndarray    # (beam,) float32


@dataclasses.dataclass
class _Staged:
    """A prefilled chunk whose rows are not all inserted yet."""

    chunk: Dict                  # device pytree from the prefill program
    host: Dict                   # host batch (text-cooking fields + meta)
    rows: "collections.deque[Tuple[int, int]]"  # (row, split position)


class SlotEngine:
    """S-slot continuous-batching beam decoder over one model/params.

    ``slots``: arena size (default ``cfg.engine_slots`` or, when that is 0,
    ``cfg.test_batch_size`` — equal geometry with the batched beam, which
    is also what the bit-exactness golden tests pin). ``guard``: an armed
    analysis.sanitizer.CompileGuard; every dispatch is labelled, so the
    one-compile-per-label contract covers the whole engine family.
    ``device``: pin the arena, params inputs, and every admitted chunk to
    ONE device (a fleet replica's chip); None keeps the default placement.
    ``tag``: label suffix (the fleet's ``r<i>``) so each replica's own
    compiles stay one-per-label under the guard.
    """

    def __init__(self, model: FiraModel, params, cfg: FiraConfig, *,
                 slots: Optional[int] = None, guard=None,
                 device=None, tag: Optional[str] = None):
        self.model = model
        self.params = params
        self.cfg = cfg
        self.slots = int(slots or cfg.engine_slots or cfg.test_batch_size)
        if self.slots < 1:
            raise ValueError(f"engine needs >= 1 slot, got {self.slots}")
        self.guard = guard
        self.device = device
        self.tag = tag
        self.stats = EngineStats(slots=self.slots)
        self._state = None
        self._prefill = jax.jit(self._prefill_fn)
        # the big slot arena is donated through step/insert: the engine
        # holds exactly one live state, rebound on every dispatch
        self._step = jax.jit(self._step_fn, donate_argnums=(1,))
        self._insert = jax.jit(self._insert_fn, donate_argnums=(0,))
        self._pending_occ = None
        self.begin_stream()

    def label(self, kind: str, geom_tag: Optional[str] = None) -> str:
        """Guard label for one of THIS engine's programs: the geometry tag
        (prefill family) and the replica tag compose into the standard
        ``program_label`` format — ``engine_prefill[a16.e256.t12.r1]``,
        ``engine_step[r1]``; with no tag the single-engine labels are
        unchanged."""
        mods = ".".join(t for t in (geom_tag, self.tag) if t)
        return program_label(kind, mods or None)

    def labels(self, table=None) -> List[str]:
        """This engine's full declared program family: one prefill label
        per decode bucket geometry (or the untagged prefill when no table)
        plus step + insert."""
        from fira_tpu.data.buckets import geom_tag

        prefills = ([self.label(PREFILL_KIND, geom_tag(g)) for g in table]
                    if table is not None else [self.label(PREFILL_KIND)])
        return prefills + [self.label(STEP_LABEL), self.label(INSERT_LABEL)]

    # --- jitted programs -------------------------------------------------

    def _prefill_fn(self, params, batch):
        """Per-batch preamble of the batched beam, verbatim: encode once,
        then (kv mode) per-layer cross K/V + copy-head source projection
        replicated per beam, or (full-redecode mode) the per-beam encoder
        states themselves. Identical program prefix => identical values."""
        cfg, model = self.cfg, self.model
        K = cfg.beam_size
        states, mask = model.apply({"params": params}, batch,
                                   method=FiraModel.encode)
        out = {"src_mask": mask, "diff": batch["diff"],
               "sub_token": batch["sub_token"]}
        if cfg.beam_kv_cache:
            cross_k, cross_v, src_proj = model.apply(
                {"params": params}, states, method=FiraModel.decode_init)
            out["cross_k"] = jnp.repeat(cross_k, K, axis=1)
            out["cross_v"] = jnp.repeat(cross_v, K, axis=1)
            out["src_proj"] = jnp.repeat(src_proj, K, axis=0)
            # dtype marker only: fresh slots seed their self-attention
            # cache at zeros of the ENCODER STATE dtype, exactly like the
            # batched beam's cache0 (which may be wider than the compute
            # dtype under stable_residual)
            out["cache_seed"] = jnp.zeros((), states.dtype)
        else:
            out["states"] = jnp.repeat(states, K, axis=0)
        return out

    def _step_fn(self, params, state):
        """Advance every live, not-yet-done slot ``cfg.engine_harvest_every``
        beam positions at its own depth (a lax.scan of identical one-step
        bodies — slots that settle mid-scan self-mask out, so the cadence
        changes WHICH dispatch a harvest lands in, never the math);
        everything else passes through unchanged. Returns (state,
        occupied-slot-step count) — the occupancy numerator, counted
        exactly, micro-step by micro-step."""
        R = max(1, int(self.cfg.engine_harvest_every))
        if R == 1:
            return self._one_step(params, state)

        def body(carry, _):
            st, acc = carry
            st, occ = self._one_step(params, st)
            return (st, acc + occ), None

        (state, occ), _ = jax.lax.scan(
            body, (state, jnp.int32(0)), None, length=R)
        return state, occ

    def _one_step(self, params, state):
        """One beam position for every live, not-yet-done slot."""
        cfg, model = self.cfg, self.model
        S, K, T = self.slots, cfg.beam_size, cfg.tar_len
        L, H = cfg.num_layers, cfg.num_head
        d_head = cfg.embedding_dim // H
        neg = (jnp.float32(-1.0) if cfg.beam_compat_prob_space
               else jnp.float32(-np.inf))

        tokens, probs, finished = (state["tokens"], state["probs"],
                                   state["finished"])
        pos = state["pos"]
        active = state["live"] & ~state["done"]
        # idle/done rows clamp to a legal position; their computation is
        # garbage by construction and blended away below
        pos_c = jnp.minimum(pos, T - 2)
        flat = tokens.reshape(S * K, T)
        pos_bk = jnp.repeat(pos_c, K)
        mask_k = jnp.repeat(state["src_mask"], K, axis=0)
        slot_src = {"diff": state["diff"], "sub_token": state["sub_token"]}
        all_fin_before = jnp.all(finished, axis=1)   # (S,)

        out_caches = {}
        if cfg.beam_kv_cache:
            # same per-row validity rule as beam_search_cached, at the
            # per-slot position vector
            valid = (flat != 0).at[:, 0].set(True) & (
                jnp.arange(T)[None, :] <= pos_bk[:, None])
            tok_in = jnp.take_along_axis(flat, pos_bk[:, None], axis=1)
            if cfg.beam_factored_topk:
                gen, copy, gate, k_cache, v_cache = model.apply(
                    {"params": params}, mask_k, tok_in, pos_bk,
                    state["k_cache"], state["v_cache"],
                    state["cross_k"], state["cross_v"], state["src_proj"],
                    valid[:, None, None, :],
                    method=FiraModel.dist_parts_step_multi,
                )
                new_tokens, new_probs, new_finished, src_beam = \
                    _select_factored(
                        gen[:, 0, :].reshape(S, K, -1),
                        copy[:, 0, :].reshape(S, K, -1),
                        gate[:, 0, :].reshape(S, K, 2),
                        tokens, probs, finished, pos_c, slot_src, cfg, neg)
            else:
                fused, k_cache, v_cache = model.apply(
                    {"params": params}, mask_k, tok_in, pos_bk,
                    state["k_cache"], state["v_cache"],
                    state["cross_k"], state["cross_v"], state["src_proj"],
                    valid[:, None, None, :],
                    method=FiraModel.fused_probs_step_multi,
                )
                dist = fused[:, 0, :].reshape(S, K, -1)
                new_tokens, new_probs, new_finished, src_beam = _select(
                    dist, tokens, probs, finished, pos_c, slot_src, cfg, neg)
            # permute cached histories to follow their beams (exactly the
            # batched beam's gather). Inactive rows are NOT blended back:
            # a done/idle slot's cache is never read again — it is not
            # stepped, and a refill overwrites its cache rows wholesale
            # (insert zeroes k/v, rewrites cross/src) — so letting the
            # step scribble on it saves two full-cache select passes per
            # micro-step. tokens/probs/finished/pos DO blend below: they
            # must survive until harvest.
            idx = src_beam[None, :, :, None, None, None]

            def gather_cache(c):
                c = c.reshape(L, S, K, H, T, d_head)
                c = jnp.take_along_axis(c, idx, axis=2)
                return c.reshape(L, S * K, H, T, d_head)

            out_caches["k_cache"] = gather_cache(k_cache)
            out_caches["v_cache"] = gather_cache(v_cache)
        else:
            tar_mask = (flat != 0).at[:, 0].set(True)

            def at_pos(a):  # row b's own position out of the full-prefix decode
                return jnp.take_along_axis(
                    a, pos_bk[:, None, None], axis=1)[:, 0, :]

            if cfg.beam_factored_topk:
                gen, copy, gate = model.apply(
                    {"params": params}, state["states"], mask_k, flat,
                    tar_mask, method=FiraModel.dist_parts)
                new_tokens, new_probs, new_finished, _ = _select_factored(
                    at_pos(gen).reshape(S, K, -1),
                    at_pos(copy).reshape(S, K, -1),
                    at_pos(gate).reshape(S, K, 2),
                    tokens, probs, finished, pos_c, slot_src, cfg, neg)
            else:
                fused = model.apply(
                    {"params": params}, state["states"], mask_k, flat,
                    tar_mask, method=FiraModel.fused_probs)
                dist = at_pos(fused).reshape(S, K, -1)
                new_tokens, new_probs, new_finished, _ = _select(
                    dist, tokens, probs, finished, pos_c, slot_src, cfg, neg)

        tokens = jnp.where(active[:, None, None], new_tokens, tokens)
        probs = jnp.where(active[:, None], new_probs, probs)
        finished = jnp.where(active[:, None], new_finished, finished)
        new_pos = jnp.where(active, pos + 1, pos)
        all_fin_after = jnp.all(finished, axis=1)
        # the early-exit predicate, per slot: stopping is exact once the
        # settling step has re-sorted an all-finished beam set
        # (decode/beam._run_steps; tests/test_beam_early_exit.py), or when
        # the position budget is exhausted
        done = state["done"] | (active & ((new_pos >= T - 1)
                                          | (all_fin_before & all_fin_after)))
        return (dict(state, tokens=tokens, probs=probs, finished=finished,
                     pos=new_pos, done=done, **out_caches),
                jnp.sum(active.astype(jnp.int32)))

    def _insert_fn(self, state, chunk, slot_ids):
        """Scatter chunk rows into slots. ``slot_ids``: (C,) int32, row j
        goes to slot ``slot_ids[j]``; the out-of-range sentinel S marks
        rows NOT consumed by this call (their scatter drops)."""
        cfg = self.cfg
        K = cfg.beam_size
        C = slot_ids.shape[0]
        tokens0, probs0, finished0, _neg = _init_beam(C, cfg)
        sid = slot_ids.astype(jnp.int32)
        sid_bk = jnp.repeat(sid, K) * K + jnp.tile(jnp.arange(K), C)

        new = dict(state)

        def put(field, value):
            new[field] = state[field].at[sid].set(value, mode="drop")

        put("tokens", tokens0)
        put("probs", probs0)
        put("finished", finished0)
        put("diff", chunk["diff"])
        put("sub_token", chunk["sub_token"])
        put("src_mask", chunk["src_mask"])
        new["pos"] = state["pos"].at[sid].set(0, mode="drop")
        new["live"] = state["live"].at[sid].set(True, mode="drop")
        new["done"] = state["done"].at[sid].set(False, mode="drop")
        if cfg.beam_kv_cache:
            for f in ("cross_k", "cross_v"):
                new[f] = state[f].at[:, sid_bk].set(chunk[f], mode="drop")
            new["src_proj"] = state["src_proj"].at[sid_bk].set(
                chunk["src_proj"], mode="drop")
            # fresh slots start from the batched beam's zero cache
            new["k_cache"] = state["k_cache"].at[:, sid_bk].set(0, mode="drop")
            new["v_cache"] = state["v_cache"].at[:, sid_bk].set(0, mode="drop")
        else:
            new["states"] = state["states"].at[sid_bk].set(
                chunk["states"], mode="drop")
        return new

    # --- state ----------------------------------------------------------

    def _ensure_state(self, chunk) -> None:
        """Allocate the slot arena (all slots dead) from the first chunk's
        shapes/dtypes. Plain host zeros + one device_put: no compiled
        program, so nothing for the compile guard to mis-attribute."""
        if self._state is not None:
            return
        cfg = self.cfg
        S, K, T = self.slots, cfg.beam_size, cfg.tar_len
        L, H = cfg.num_layers, cfg.num_head
        d_head = cfg.embedding_dim // H
        z = {
            "tokens": np.zeros((S, K, T), np.int32),
            "probs": np.zeros((S, K), np.float32),
            "finished": np.zeros((S, K), bool),
            "pos": np.zeros((S,), np.int32),
            "live": np.zeros((S,), bool),
            "done": np.zeros((S,), bool),
            "diff": np.zeros((S,) + chunk["diff"].shape[1:],
                             chunk["diff"].dtype),
            "sub_token": np.zeros((S,) + chunk["sub_token"].shape[1:],
                                  chunk["sub_token"].dtype),
            "src_mask": np.zeros((S,) + chunk["src_mask"].shape[1:], bool),
        }
        if cfg.beam_kv_cache:
            ck = chunk["cross_k"]
            z["cross_k"] = np.zeros((L, S * K) + ck.shape[2:], ck.dtype)
            z["cross_v"] = np.zeros((L, S * K) + ck.shape[2:], ck.dtype)
            sp = chunk["src_proj"]
            z["src_proj"] = np.zeros((S * K,) + sp.shape[1:], sp.dtype)
            cd = chunk["cache_seed"].dtype
            z["k_cache"] = np.zeros((L, S * K, H, T, d_head), cd)
            z["v_cache"] = np.zeros((L, S * K, H, T, d_head), cd)
        else:
            st = chunk["states"]
            z["states"] = np.zeros((S * K,) + st.shape[1:], st.dtype)
        self._state = jax.device_put(z, self.device)

    # --- host scheduler --------------------------------------------------

    def _guard_step(self, label: str) -> None:
        if self.guard is not None:
            self.guard.step(label)

    def prewarm(self, warm_batches: Iterable[Tuple[Dict, Optional[str]]]
                ) -> None:
        """Compile the prefill program family up front: one all-pad batch
        per decode bucket geometry (the compile keys), tagged with the
        geometry's guard label. The step/insert programs take their single
        warmup compile at their natural first dispatch."""
        for host, tag in warm_batches:
            wire = {k: v for k, v in host.items() if not k.startswith("_")}
            chunk = self._prefill(self.params,
                                  jax.device_put(wire, self.device))
            self._guard_step(self.label(PREFILL_KIND, tag))
            self._ensure_state(chunk)

    # --- steppable scheduler pieces (the fleet round-robins these) -------

    def begin_stream(self) -> None:
        """Reset the host-side scheduling state for a fresh input stream
        (the slot arena and stats persist — stats accumulate across runs,
        exactly as before the scheduler was made steppable)."""
        self._staged: "collections.deque[_Staged]" = collections.deque()
        self._staged_rows = 0
        self._free: List[int] = list(range(self.slots))
        self._busy: Dict[int, Tuple[int, Dict, int]] = {}

    def wants_input(self) -> bool:
        """Prefill-ahead policy: keep ``engine_prefill_depth`` chunks
        staged, and at least enough rows to refill every free slot."""
        depth = max(1, int(self.cfg.engine_prefill_depth))
        return (len(self._staged) < depth
                or self._staged_rows < len(self._free))

    def in_flight(self) -> int:
        return len(self._busy)

    def admit(self, host: Dict, index: int, device_batch=None) -> None:
        """Prefill one packed batch and stage its real rows for refill.
        ``device_batch``: the feeder's already-transferred wire batch;
        None (or an engine pinned to its own device — a fleet replica
        cannot use a chunk committed elsewhere) re-ships the host batch,
        stripping the "_"-prefixed host-only fields exactly like the
        feeder does."""
        if device_batch is None or self.device is not None:
            wire = {k: v for k, v in host.items() if not k.startswith("_")}
            device_batch = jax.device_put(wire, self.device)
        chunk = self._prefill(self.params, device_batch)
        self._guard_step(self.label(PREFILL_KIND, host.get("_tag")))
        self._ensure_state(chunk)
        self.stats.prefills += 1
        positions = host.get("_positions")  # bucketed stream only
        valid = host["valid"]
        rows: "collections.deque[Tuple[int, int]]" = collections.deque()
        C = valid.shape[0]
        for r in range(C):
            if not valid[r]:
                continue
            pos_id = (int(positions[r]) if positions is not None  # firacheck: allow[HOST-SYNC] _positions is a host-only numpy field (feeder strips it from the wire); no device value exists here
                      else index * C + r)
            rows.append((r, pos_id))
        if rows:
            self._staged.append(_Staged(chunk=chunk, host=host, rows=rows))
            self._staged_rows += len(rows)

    def refill(self, refill_order: str = "fifo") -> None:
        """Insert staged rows into every free slot (one insert dispatch
        per staged chunk touched)."""
        while self._free and self._staged:
            entry = self._staged[0]
            C = entry.host["valid"].shape[0]
            slot_ids = np.full((C,), self.slots, dtype=np.int32)  # S = drop
            n_ins = 0
            while self._free and entry.rows:
                r, pos_id = entry.rows.popleft()
                slot = (self._free.pop(0) if refill_order == "fifo"
                        else self._free.pop())
                slot_ids[r] = slot
                self._busy[slot] = (pos_id, entry.host, r)
                n_ins += 1
            self._state = self._insert(self._state, entry.chunk, slot_ids)
            self._guard_step(self.label(INSERT_LABEL))
            self.stats.refills += 1
            self.stats.slots_refilled += n_ins
            self._staged_rows -= n_ins
            if not entry.rows:
                self._staged.popleft()

    def step_dispatch(self) -> None:
        """Dispatch one step program (async — the fleet dispatches every
        replica's step before any harvest readback, so replica compute
        overlaps across chips)."""
        self._state, self._pending_occ = self._step(self.params, self._state)
        self._guard_step(self.label(STEP_LABEL))
        self.stats.step_dispatches += 1
        self.stats.steps += max(1, int(self.cfg.engine_harvest_every))

    def harvest(self) -> Iterator[EngineItem]:
        """Read back the dispatched step's done mask and yield every newly
        settled slot's sample. COPIES, not views: the next dispatch DONATES
        these buffers, and on the CPU backend a zero-copy device_get view
        into a donated buffer dangles."""
        stats = self.stats
        stats.occupied_slot_steps += int(np.array(
            jax.device_get(self._pending_occ)))
        done = np.array(jax.device_get(self._state["done"]))
        newly = [s for s in self._busy if done[s]]
        if newly:
            toks = np.array(jax.device_get(self._state["tokens"]))
            probs = np.array(jax.device_get(self._state["probs"]))
            for s in newly:
                pos_id, host, r = self._busy.pop(s)
                self._free.append(s)
                stats.commits += 1
                yield EngineItem(position=pos_id, host=host, row=r,
                                 tokens=toks[s], probs=probs[s])

    def run(self, feed, *, refill_order: str = "fifo"
            ) -> Iterator[EngineItem]:
        """Drive the engine over ``feed`` — an iterable of
        data.feeder.FedBatch items carrying the SAME packed batches the
        batched-beam path decodes (item.device is the prefill input;
        item.host keeps the text-cooking fields and the packer's
        ``_positions``/``_tag`` metadata).

        ``refill_order``: which freed slot a waiting request lands in —
        "fifo" (queue) or "lifo" (stack). Output is identical either way
        (results are keyed by split position and samples are slot-
        independent); the knob exists so the determinism tests can pin
        exactly that.

        Yields one :class:`EngineItem` per real sample as it settles.
        """
        if refill_order not in ("fifo", "lifo"):
            raise ValueError(f"refill_order {refill_order!r} not in "
                             f"{{'fifo', 'lifo'}}")
        self.begin_stream()
        feed_iter = iter(feed)
        exhausted = False

        while True:
            # prefill ahead: keep `depth` chunks staged, and at least
            # enough rows to refill every currently free slot
            while not exhausted and self.wants_input():
                try:
                    item = next(feed_iter)
                except StopIteration:
                    exhausted = True
                    break
                # a put=False feed (the fleet's shared queue) leaves
                # item.device == item.host; admit re-ships it then
                self.admit(item.host, item.index,
                           None if item.device is item.host else item.device)

            # refill every free slot from the staged queue
            self.refill(refill_order)

            if not self._busy:
                if exhausted:
                    break
                continue  # nothing in flight yet: pull more input

            self.step_dispatch()
            yield from self.harvest()
