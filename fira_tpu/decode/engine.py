"""Slot-refill continuous-batching decode engine.

The batched beam (decode/beam.py) dispatches whole batches: even with
``beam_early_exit`` the while_loop runs until the batch's LONGEST message
settles, so on real corpora (mean message ~8-10 tokens against the
tar_len-1 = 29 step budget) most rows of a dispatch are finished beams
burning device cycles. This module applies iteration-level continuous
batching (Orca, OSDI '22) under this stack's static-shape regime (slots as
a fixed-geometry KV arena, vLLM SOSP '23 — PAPERS.md "Continuous batching
/ inference serving"): a fixed arena of S slots, each holding one
sample's beam mid-flight at its OWN decode depth, advanced one token per
step program; settled slots are harvested and refilled with freshly
prefilled requests, so wall clock scales with TOTAL TOKENS EMITTED, not
with per-batch max length.

Program family (all fixed-shape, labelled for the compile guard —
``engine_prefill[<geom>]`` x the decode bucket table, ``engine_step``,
``engine_insert``, ``engine_harvest`` (the sliced-readback row gather);
zero post-warmup retraces):

- **prefill** (one per decode bucket geometry): encoder forward + per-beam
  cross-attention K/V + copy-head source projection for ONE packed batch
  of new requests — exactly the per-batch preamble of the batched beam, on
  exactly the batches the existing bucketed/sorted packer emits (the
  feeder assembles and ships them asynchronously, as for every driver).
- **step** (single geometry — the bucketable axes never reach the decoder:
  ``sou_len``/``sub_token_len`` are pinned by the copy-label id space and
  decode pins ``tar_len`` full): advance every live slot's beam
  ``cfg.engine_harvest_every`` positions at the slot's own depth
  (model.dist_parts_step_multi / fused_probs_step_multi; the per-row
  ``s`` vector path of beam._selection_tail), with a per-slot
  finished/done mask instead of the batch path's global early-exit
  predicate. Idle/done slots compute garbage that is blended away — they
  are the occupancy loss the refill loop exists to keep near zero.
- **insert**: scatter up to one prefilled chunk's rows into freed slots
  (slot ids are data, not shapes: a (C,) vector with the out-of-range
  sentinel S marking rows not consumed this call, ``mode="drop"``).

Equivalence contract (pinned by tests/test_engine.py in all four
kv-cache x factored-topk modes): per sample, the engine's (tokens, probs)
are BIT-EXACT equal to the batched beam's. The argument has three legs:

1. beam search is per-sample independent — every batched-beam op acts
   row-wise (embeds, per-row matmuls, attention over the row's own
   sequence, per-row top-k), so a sample's trajectory does not depend on
   its batch neighbours (the test_batch_size knob already rides on this);
2. the step program runs the SAME selection math at a per-row position
   vector (beam._selection_tail treats scalar and vector ``s``
   identically per row), against the same prefill values the batched
   beam computes (same packed batches, same encode/decode_init program
   prefix);
3. per-slot termination replicates the early-exit predicate exactly —
   done = all-finished-before-step AND all-finished-after (the settling
   step that re-sorts beams), or position exhausted — and
   tests/test_beam_early_exit.py already pins that stopping there equals
   running the full scan.

Paged KV arena (``cfg.engine_paged_kv``, default on — decode/paging.py,
docs/DECODE_ENGINE.md "Paged KV arena"): the per-slot self-attention
caches live in a FIXED POOL of KV blocks — ``k_pool``/``v_pool``
(L, P, beam, H, block, d_head) — addressed through a per-slot block
table (S, W) instead of whole-sequence slot stripes. The step program
appends into each live slot's current tail block and gathers its cache
view by block id (model.Decoder.decode_step_paged); ``insert`` hands a
fresh slot exactly the blocks its decode bucket's tar budget reserves;
``harvest`` returns a settled slot's blocks to the host free list WHOLE
— freed blocks are unmapped, never zeroed (beam.step_valid_mask already
multiplies unwritten positions by an exact 0.0). Everything stays
static-shape (fixed P, fixed W), so the program family above is
unchanged and per-sample output is BIT-exact (tokens AND probs) vs the
unpaged arena (tests/test_paged_kv.py). The point: slot residency
decouples from sequence length — ``engine_slots`` grows past what
whole-sequence arenas allow at equal HBM, and longer-tar decode buckets
(``cfg.decode_tar_buckets``) become smaller/larger block RESERVATIONS
against one pool instead of a per-length arena blow-up. The scheduler's
admission becomes reservation-based when the pool is undersized: the
head staged row waits until harvests return enough blocks (head-of-line,
deterministic), and parse-time floors (decode/paging.paging_errors)
guarantee it can always eventually be seated.

Host scheduler (:meth:`SlotEngine.run`): drains the packer stream via the
async feeder, prefills ahead (``cfg.engine_prefill_depth`` chunks),
refills every freed slot, steps, harvests settled slots, and yields one
:class:`EngineItem` per sample AS IT SETTLES (out of split order — the
ordered streaming writer, decode/stream.py, restores order on disk). The
per-dispatch ``done`` readback is the engine's designated sync boundary:
the refill decision is host-side by construction.

The scheduler is exposed as STEPPABLE pieces — ``begin_stream`` /
``wants_input`` / ``admit`` / ``refill`` / ``step_dispatch`` / ``harvest``
— and ``run()`` is just the single-engine loop over them. The replicated
decode fleet (parallel/fleet.py) round-robins the SAME pieces over N
engine instances pulling from one shared admission queue, so the fleet
inherits the single engine's scheduling semantics (and its per-sample
bit-exactness) by construction instead of re-implementing them.
``device``/``tag`` pin a replica to its own chip and suffix its guard
labels (``engine_step[r0]``), keeping the one-compile-per-label contract
honest when N replicas each compile their own program set.

Cross-request reuse (``cfg.prefix_cache``, default off — decode/
prefix_cache.py, docs/DECODE_ENGINE.md "Prefix cache & dedup"): ``admit``
content-addresses each valid row by a keyed blake2b digest of its packed
payload and applies two composable mechanisms before dispatching
prefill. (a) IN-FLIGHT DEDUP: a row byte-identical to one already
admitted on THIS engine coalesces onto the existing seat as a FOLLOWER —
no seat, no blocks, no prefill; ``harvest`` fans the leader's settled
(tokens, probs) out to every follower's own output position (one decode,
N commits). (b) PREFILL-RESULT CACHE: when every remaining row's
artifacts are cached, the staged chunk is assembled host-side from the
cached rows and seated WITHOUT a prefill dispatch (``prefills_saved``);
a chunk that does dispatch fills the cache with host copies of its rows.
Both are host-side lookups — no new program geometry exists, so the
zero-post-warmup-retrace contract holds with the cache armed — and both
are bit-exact: a cache-hit or coalesced response is byte-identical to
its cold run (tests/test_prefix_cache.py).

The paged block allocator is REFCOUNTED (the free list is a deque —
O(1) grants, the old ``list.pop(0)`` walk was O(n) per block): a grant
acquires each block at refcount 1, harvest/retire RELEASE grants (a
block returns to ``_free_blocks`` only at refcount zero) rather than
scribbling the free list wholesale, and double-grant/double-release are
asserted impossible (:meth:`SlotEngine.allocator_invariants`, pinned in
tier-1). Blocks whose seat serves a coalesced fan-out group are the
SHARED blocks of the reuse story — one grant serving N requests — and
their high-water mark is metered (``shared_block_peak``).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fira_tpu.analysis.sanitizer import leak_guard, program_label
from fira_tpu.config import FiraConfig
from fira_tpu.decode import paging
from fira_tpu.decode import prefix_cache as prefix_cache_lib
from fira_tpu.decode import quant
from fira_tpu.decode import spec as spec_lib
from fira_tpu.decode.beam import (_init_beam, _select, _select_factored,
                                  step_valid_mask)
from fira_tpu.model.model import FiraModel

PREFILL_KIND = "engine_prefill"
STEP_LABEL = "engine_step"
INSERT_LABEL = "engine_insert"
HARVEST_LABEL = "engine_harvest"


@dataclasses.dataclass
class EngineStats:
    """Dispatch/occupancy accounting for one engine run."""

    slots: int
    prefills: int = 0            # prefill program dispatches (chunks)
    refills: int = 0             # insert program dispatches
    slots_refilled: int = 0      # slot fills across all inserts
    steps: int = 0               # beam MICRO-steps run (cadence x dispatches)
    step_dispatches: int = 0     # step program dispatches
    occupied_slot_steps: int = 0  # exact count of (slot, micro-step) pairs
                                  # that did real beam work (device-counted)
    commits: int = 0             # samples harvested
    # paged-KV HBM accounting (decode/paging.py; 0/defaults when the
    # engine runs the unpaged arena or no KV cache at all) — stamped by
    # every step dispatch so a stats reset between timed windows
    # (bench.py / tpu_decode_bench.py do exactly that) re-learns them
    pool_blocks: int = 0         # fixed pool size P (paged only)
    kv_block_size: int = 0       # positions per block (paged only)
    kv_bytes_per_slot: int = 0   # committed K+V cache HBM per slot
    block_steps: int = 0         # blocks in use, summed per step dispatch
    peak_blocks: int = 0         # high-water mark of blocks in use
    # sliced-harvest readback accounting: harvest copies ONLY the settled
    # slots' token/prob rows D2H (one jitted dynamic-index gather per
    # row) instead of the full (S, K, T) / (S, K) arenas per harvest
    harvest_row_reads: int = 0   # settled-slot rows read back individually
    harvest_bytes_read: int = 0  # token/prob bytes actually copied D2H
    harvest_bytes_saved: int = 0  # vs the historical full-arena readback
    # cross-request reuse accounting (decode/prefix_cache.py; all zero
    # when cfg.prefix_cache is off — the byte-identical comparator)
    cache_hits: int = 0          # seated rows served from the prefill cache
    cache_misses: int = 0        # seated rows that paid a prefill dispatch
    #                              with the cache armed
    cache_evictions: int = 0     # LRU entries evicted for capacity
    cache_integrity_drops: int = 0  # entries dropped on checksum mismatch
    prefills_saved: int = 0      # admitted chunks that dispatched NO
    #                              prefill (all rows cache-hit or coalesced)
    cache_hbm_bytes_saved: int = 0  # prefill-artifact bytes served from
    #                              cache instead of materialized by dispatch
    dedup_fanout: int = 0        # requests coalesced onto an existing seat
    #                              (delivered by fan-out at harvest)
    shared_block_peak: int = 0   # high-water mark of paged blocks whose
    #                              seat serves a coalesced fan-out group
    # speculative draft-and-verify accounting (decode/spec.py; all zero
    # with cfg.spec_decode off — the byte-identical comparator). ``steps``
    # counts a verify dispatch as ONE step — the forwards-per-token framing
    # of the spec literature — so steps_per_commit falling under spec is
    # exactly "fewer dispatches bought the same commits"; the device-side
    # frames a verify actually ran are metered separately (spec_frames):
    # on CPU each frame costs one plain step's FLOPs, on a parallel-verify
    # backend it does not.
    drafted: int = 0             # draft tokens proposed (k x live slots
    #                              at verify entry)
    accepted: int = 0            # drafted tokens the verify frames matched
    verify_dispatches: int = 0   # draft->verify dispatches (vs plain steps)
    steps_saved: int = 0         # beam frames a verify advanced BEYOND its
    #                              frame-0 obligation — plain step
    #                              dispatches' worth of work avoided
    spec_frames: int = 0         # verify while_loop frames actually run
    # low-precision serving tiers (decode/quant.py; both "f32" on the
    # byte-identical contract path) — stamped by every step dispatch like
    # the pool fields, so stats resets between timed windows re-learn them
    kv_dtype: str = "f32"        # K/V arena storage dtype (f32|bf16)
    serve_precision: str = "f32"  # decode weight tier (f32|bf16|int8w)

    @property
    def cache_hit_rate(self) -> float:
        """Fraction of seated rows served from the prefill cache."""
        total = self.cache_hits + self.cache_misses
        return self.cache_hits / total if total else 0.0

    @property
    def acceptance_rate(self) -> float:
        """Fraction of drafted tokens the verify frames accepted."""
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def slot_occupancy(self) -> float:
        """Mean fraction of slots doing real beam work per micro-step."""
        total = self.steps * self.slots
        return self.occupied_slot_steps / total if total else 0.0

    @property
    def steps_per_commit(self) -> float:
        return self.steps / self.commits if self.commits else 0.0

    @property
    def pool_utilization(self) -> float:
        """Mean fraction of the KV pool mapped to live slots per step
        dispatch. 1.0 for the unpaged arena (the whole-sequence stripes
        are committed whether or not a slot is live — exactly the HBM
        the paged pool stops paying); 0.0 with no KV cache at all."""
        if self.pool_blocks and self.step_dispatches:
            return self.block_steps / (self.step_dispatches
                                       * self.pool_blocks)
        return 1.0 if self.kv_bytes_per_slot else 0.0

    @property
    def dispatches(self) -> int:
        return self.prefills + self.refills + self.step_dispatches

    def summary(self) -> Dict[str, float]:
        return {
            "slots": self.slots,
            "prefills": self.prefills,
            "refills": self.refills,
            "slots_refilled": self.slots_refilled,
            "steps_run": self.steps,
            "step_dispatches": self.step_dispatches,
            "commits": self.commits,
            "dispatches": self.dispatches,
            "slot_occupancy": round(self.slot_occupancy, 4),
            "steps_per_commit": round(self.steps_per_commit, 3),
            "pool_blocks": self.pool_blocks,
            "kv_block_size": self.kv_block_size,
            "kv_bytes_per_slot": self.kv_bytes_per_slot,
            "peak_blocks": self.peak_blocks,
            "pool_utilization": round(self.pool_utilization, 4),
            "harvest_row_reads": self.harvest_row_reads,
            "harvest_bytes_read": self.harvest_bytes_read,
            "harvest_bytes_saved": self.harvest_bytes_saved,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "cache_hit_rate": round(self.cache_hit_rate, 4),
            "cache_evictions": self.cache_evictions,
            "cache_integrity_drops": self.cache_integrity_drops,
            "prefills_saved": self.prefills_saved,
            "cache_hbm_bytes_saved": self.cache_hbm_bytes_saved,
            "dedup_fanout": self.dedup_fanout,
            "shared_block_peak": self.shared_block_peak,
            "drafted": self.drafted,
            "accepted": self.accepted,
            "acceptance_rate": round(self.acceptance_rate, 4),
            "verify_dispatches": self.verify_dispatches,
            "steps_saved": self.steps_saved,
            "spec_frames": self.spec_frames,
            "kv_dtype": self.kv_dtype,
            "serve_precision": self.serve_precision,
        }


@dataclasses.dataclass
class EngineItem:
    """One settled sample: the per-sample view of the batched beam's
    output — ``tokens[argmax(probs)]`` is the prediction, copy ids already
    resolved at extension time (identical contract to decode/beam.py)."""

    position: int        # split-local sample position (output order key)
    host: Dict           # the host batch this sample rode in on
    row: int             # its row within that batch (indexes host fields)
    tokens: np.ndarray   # (beam, tar_len) int32
    probs: np.ndarray    # (beam,) float32


@dataclasses.dataclass
class _Staged:
    """A prefilled chunk whose rows are not all inserted yet."""

    chunk: Dict                  # device pytree from the prefill program
    host: Dict                   # host batch (text-cooking fields + meta)
    rows: "collections.deque[Tuple[int, int]]"  # (row, split position)
    limit: int                   # per-slot tar budget for this chunk's rows
                                 # (the bucket's tar under decode_tar_buckets,
                                 # else cfg.tar_len) — sets the paged block
                                 # reservation AND the generation cap


class SlotEngine:
    """S-slot continuous-batching beam decoder over one model/params.

    ``slots``: arena size (default ``cfg.engine_slots`` or, when that is 0,
    ``cfg.test_batch_size`` — equal geometry with the batched beam, which
    is also what the bit-exactness golden tests pin). ``guard``: an armed
    analysis.sanitizer.CompileGuard; every dispatch is labelled, so the
    one-compile-per-label contract covers the whole engine family.
    ``device``: pin the arena, params inputs, and every admitted chunk to
    ONE device (a fleet replica's chip); None keeps the default placement.
    ``tag``: label suffix (the fleet's ``r<i>``) so each replica's own
    compiles stay one-per-label under the guard.
    """

    def __init__(self, model: FiraModel, params, cfg: FiraConfig, *,
                 slots: Optional[int] = None, guard=None,
                 device=None, tag: Optional[str] = None,
                 pool_blocks: Optional[int] = None, faults=None):
        self.model = model
        self.params = params
        self.cfg = cfg
        # robust.faults.FaultInjector (or None — the zero-overhead
        # default): checks the engine.{prefill,step,harvest} sites at
        # each dispatch. ``retired`` is set by retire(): a replica whose
        # dispatch raised or blew the watchdog is dead — every steppable
        # piece bails early on it, including an abandoned watchdog thread
        # that wakes up after the retirement (docs/FAULTS.md).
        self._faults = faults
        # resource-lifecycle sanitizer (--sanitize / chaos harness):
        # armed, every paged-block grant is ledgered with its acquire
        # site and assert_clean() at teardown names what leaked; unarmed
        # (None — the default) each allocator path pays one is-None
        # branch and records nothing (analysis.sanitizer.LeakGuard)
        self._leaks = leak_guard()
        self.retired = False
        self.slots = int(slots or cfg.engine_slots or cfg.test_batch_size)
        if self.slots < 1:
            raise ValueError(f"engine needs >= 1 slot, got {self.slots}")
        self.guard = guard
        self.device = device
        self.tag = tag
        # low-precision serving tiers (decode/quant.py). The tier tag
        # suffixes EVERY program label of this engine ("" on the f32/f32
        # contract path — the default label set is unchanged), and the
        # weight tier builds a quantized copy of the decode-side params
        # ONCE, here: a fleet respawn or spare prewarm constructs a fresh
        # SlotEngine from the original f32 params, so re-quantization is
        # automatic by construction.
        qerrs = quant.quant_errors(cfg)
        if qerrs:
            raise ValueError("; ".join(qerrs))
        self._tier_tag = quant.tier_tag(cfg)
        self._tier_ns = quant.tier_namespace(cfg)
        self._decode_params, self._wq_scales = quant.quantize_decode_params(
            params, cfg)
        if self._decode_params is not params:
            self._decode_params = jax.device_put(self._decode_params, device)
        # paged KV arena geometry (decode/paging.py). ``pool_blocks`` is
        # THIS engine's pool (a fleet replica's per-chip share); None
        # falls back to cfg.kv_pool_blocks, 0 to the full-residency auto
        # size (slots x table width — scheduling identical to unpaged).
        self._paged = bool(cfg.beam_kv_cache and cfg.engine_paged_kv)
        self._block_size = self._table_width = self._pool_blocks = 0
        self._kv_bytes_per_slot = 0
        if self._paged:
            self._block_size = paging.resolve_block_size(cfg)
            if cfg.tar_len % self._block_size:
                raise ValueError(
                    f"kv_block_size {self._block_size} does not divide "
                    f"tar_len {cfg.tar_len}; the block table must tile "
                    f"the arena budget exactly (decode/paging.py)")
            self._table_width = cfg.tar_len // self._block_size
            self._pool_blocks = int(
                pool_blocks if pool_blocks is not None
                else cfg.kv_pool_blocks) or self.slots * self._table_width
            if self._pool_blocks < self._table_width:
                raise ValueError(
                    f"kv_pool_blocks {self._pool_blocks} < table width "
                    f"{self._table_width}: one full-tar sample must fit "
                    f"an empty pool or admission livelocks")
        # cross-request prefill cache (decode/prefix_cache.py): one LRU
        # PER ENGINE — a fleet replica's cache is per-chip like its KV
        # arena (cached artifacts re-enter via device_put onto this
        # engine's own device). None = off, zero hot-path overhead.
        self._cache = None
        if cfg.prefix_cache:
            self._cache = prefix_cache_lib.PrefixCache(
                cfg.prefix_cache_entries,
                max_bytes=cfg.prefix_cache_bytes, faults=faults)
        self.stats = EngineStats(slots=self.slots)
        self._state = None
        self._prefill = jax.jit(self._prefill_fn)
        # the big slot arena is donated through step/insert: the engine
        # holds exactly one live state, rebound on every dispatch
        self._step = jax.jit(self._step_fn, donate_argnums=(1,))
        self._insert = jax.jit(self._insert_fn, donate_argnums=(0,))
        # sliced harvest readback: one tiny program gathers a SINGLE
        # settled slot's (tokens, probs) rows so the D2H copy is the
        # slot's own bytes, not the whole (S, K, T) arena. dynamic_index
        # keeps the slot id a runtime value — one compile for any slot,
        # not one per slot constant.
        self._take_rows = jax.jit(lambda tokens, probs, slot: (
            jax.lax.dynamic_index_in_dim(tokens, slot, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(probs, slot, 0, keepdims=False)))
        self._pending_occ = None
        # speculative draft-and-verify (decode/spec.py; cfg.spec_decode):
        # the drafter reads the arena (never donated — the verify right
        # behind it consumes the same state), the verify donates it like
        # the plain step. _pending_spec carries the verify's device-side
        # [tested, matched, iters] counters to the harvest sync boundary
        # (the _pending_occ pattern: no new host syncs). _spec_cd is the
        # stall cooldown — plain dispatches to run before re-arming after
        # a verify whose drafts all missed (scheduling only; output bytes
        # are invariant by the spec.py exactness argument).
        self._spec_tier = (cfg.spec_decode
                           if cfg.spec_decode not in (None, "off") else None)
        self._spec_k = int(cfg.engine_spec_k)
        self._spec_cd = 0
        self._pending_spec = None
        if self._spec_tier is not None:
            errs = spec_lib.spec_errors(cfg)
            if errs:
                raise ValueError("; ".join(errs))
            # the drafter runs on the same decode-side weight tier as the
            # step it feeds: int8w leaves dequant at the trace top (a
            # no-op identity for f32/bf16 — scales is None)
            base_draft = spec_lib.make_drafter(model, cfg, self.slots,
                                               self._paged)
            self._draft = jax.jit(lambda p, st: base_draft(
                quant.dequant_tree(p, self._wq_scales), st))
            self._verify = jax.jit(self._verify_fn, donate_argnums=(1,))
        self.begin_stream()

    def label(self, kind: str, geom_tag: Optional[str] = None) -> str:
        """Guard label for one of THIS engine's programs: the geometry tag
        (prefill family), the low-precision tier tag (decode/quant.py —
        empty on the f32/f32 contract path) and the replica tag compose
        into the standard ``program_label`` format —
        ``engine_prefill[a16.e256.t12.r1]``, ``engine_step[bf16kv.int8w.r1]``;
        with no tags the single-engine labels are unchanged."""
        mods = ".".join(t for t in (geom_tag, self._tier_tag, self.tag) if t)
        return program_label(kind, mods or None)

    def labels(self, table=None) -> List[str]:
        """This engine's full declared program family: one prefill label
        per decode bucket geometry (or the untagged prefill when no table)
        plus step + insert + the sliced-harvest row gather."""
        from fira_tpu.data.buckets import geom_tag

        prefills = ([self.label(PREFILL_KIND, geom_tag(g)) for g in table]
                    if table is not None else [self.label(PREFILL_KIND)])
        return prefills + [self.label(STEP_LABEL), self.label(INSERT_LABEL),
                           self.label(HARVEST_LABEL)] + self._spec_labels()

    def labels_for_tags(self, geom_tags) -> List[str]:
        """The declared family from already-computed geometry tags (the
        respawn path holds the stored warm-batch tags, not the bucket
        table — parallel/fleet.py replace_slot): one prefill label per
        tag (None = the untagged single-geometry prefill) plus the
        step/insert/harvest trio."""
        prefills = [self.label(PREFILL_KIND, t) for t in geom_tags] \
            or [self.label(PREFILL_KIND)]
        return prefills + [self.label(STEP_LABEL), self.label(INSERT_LABEL),
                           self.label(HARVEST_LABEL)] + self._spec_labels()

    def _spec_labels(self) -> List[str]:
        """The (S, k) draft/verify pair when spec is armed (the ``k<k>``
        geometry mod composes with the replica tag —
        ``engine_verify[k4.r1]``); empty with cfg.spec_decode off, so the
        non-spec declared family is byte-for-byte unchanged."""
        if self._spec_tier is None:
            return []
        km = f"k{self._spec_k}"
        return [self.label(spec_lib.DRAFT_LABEL, km),
                self.label(spec_lib.VERIFY_LABEL, km)]

    # --- jitted programs -------------------------------------------------

    def _prefill_fn(self, params, batch):
        """Per-batch preamble of the batched beam, verbatim: encode once,
        then (kv mode) per-layer cross K/V + copy-head source projection
        replicated per beam, or (full-redecode mode) the per-beam encoder
        states themselves. Identical program prefix => identical values."""
        cfg, model = self.cfg, self.model
        K = cfg.beam_size
        states, mask = model.apply({"params": params}, batch,
                                   method=FiraModel.encode)
        out = {"src_mask": mask, "diff": batch["diff"],
               "sub_token": batch["sub_token"]}
        if cfg.beam_kv_cache:
            cross_k, cross_v, src_proj = model.apply(
                {"params": params}, states, method=FiraModel.decode_init)
            out["cross_k"] = jnp.repeat(cross_k, K, axis=1)
            out["cross_v"] = jnp.repeat(cross_v, K, axis=1)
            out["src_proj"] = jnp.repeat(src_proj, K, axis=0)
            # dtype marker only: fresh slots seed their self-attention
            # cache at zeros of the ENCODER STATE dtype, exactly like the
            # batched beam's cache0 (which may be wider than the compute
            # dtype under stable_residual) — unless the low-precision KV
            # tier pins the arena narrower (cfg.kv_dtype="bf16",
            # decode/quant.py): _ensure_state allocates the pools/stripes
            # at this dtype and the HBM accounting follows it
            out["cache_seed"] = jnp.zeros(
                (), quant.kv_seed_dtype(cfg, states.dtype))
        else:
            out["states"] = jnp.repeat(states, K, axis=0)
        return out

    def _step_fn(self, params, state):
        """Advance every live, not-yet-done slot ``cfg.engine_harvest_every``
        beam positions at its own depth (a lax.scan of identical one-step
        bodies — slots that settle mid-scan self-mask out, so the cadence
        changes WHICH dispatch a harvest lands in, never the math);
        everything else passes through unchanged. Returns (state,
        occupied-slot-step count) — the occupancy numerator, counted
        exactly, micro-step by micro-step.

        ``params`` is the engine's DECODE-SIDE tree (self._decode_params):
        under serve_precision="int8w" the quantized leaves dequant ONCE
        here, at the trace top (per-channel scales embed as trace-time
        constants), so the scan body below reuses one reconstructed tree
        instead of dequantizing per micro-step; f32/bf16 pass through
        untouched (scales is None)."""
        params = quant.dequant_tree(params, self._wq_scales)
        R = max(1, int(self.cfg.engine_harvest_every))
        if R == 1:
            return self._one_step(params, state)

        def body(carry, _):
            st, acc = carry
            st, occ = self._one_step(params, st)
            return (st, acc + occ), None

        (state, occ), _ = jax.lax.scan(
            body, (state, jnp.int32(0)), None, length=R)
        return state, occ

    def _verify_fn(self, params, state, drafts):
        """The speculative verify program: up to ``engine_spec_k`` gated
        EXACT step frames in one dispatch (decode/spec.run_verify over
        this engine's own :meth:`_one_step` — the identical per-position
        HLO the plain step runs, which is the whole exactness argument).
        Returns (state', occ_entry, [tested, matched, iters]); occ_entry
        rides the _pending_occ slot, the counter vector _pending_spec."""
        # same trace-top dequant as _step_fn: the while_loop frames reuse
        # one reconstructed tree (identity for f32/bf16 weight tiers)
        params = quant.dequant_tree(params, self._wq_scales)
        step = functools.partial(self._one_step, params)
        return spec_lib.run_verify(step, state, drafts, self._spec_k,
                                   self.cfg.tar_len)

    def _one_step(self, params, state, gate=None):
        """One beam position for every live, not-yet-done slot.

        ``gate`` (None on every plain path — the trace is unchanged): a
        (S,) bool the spec verify program (decode/spec.py) ANDs into the
        active mask, freezing rows whose drafts already diverged. A frozen
        row is handled by the inactive-row discipline that already exists
        for idle/done slots — blended state, sentinel-masked paged table —
        with ONE extra care: the unpaged cache permute below must not
        scribble a row that will RESUME (see the gated identity blend)."""
        cfg, model = self.cfg, self.model
        S, K, T = self.slots, cfg.beam_size, cfg.tar_len
        L, H = cfg.num_layers, cfg.num_head
        d_head = cfg.embedding_dim // H
        neg = (jnp.float32(-1.0) if cfg.beam_compat_prob_space
               else jnp.float32(-np.inf))

        tokens, probs, finished = (state["tokens"], state["probs"],
                                   state["finished"])
        pos = state["pos"]
        active = state["live"] & ~state["done"]
        if gate is not None:
            active = active & gate
        # idle/done rows clamp to a legal position; their computation is
        # garbage by construction and blended away below
        pos_c = jnp.minimum(pos, T - 2)
        flat = tokens.reshape(S * K, T)
        pos_bk = jnp.repeat(pos_c, K)
        mask_k = jnp.repeat(state["src_mask"], K, axis=0)
        slot_src = {"diff": state["diff"], "sub_token": state["sub_token"]}
        all_fin_before = jnp.all(finished, axis=1)   # (S,)

        out_caches = {}
        if cfg.beam_kv_cache and self._paged:
            # same per-row validity rule as beam_search_cached, at the
            # per-slot position vector (beam.step_valid_mask) — this mask
            # is also what makes unwritten/stale POOL blocks read as an
            # exact 0.0 contribution, so fresh slots need no zeroed cache
            valid = step_valid_mask(flat, pos_bk, T)
            tok_in = jnp.take_along_axis(flat, pos_bk[:, None], axis=1)
            # idle and done slots must neither write nor permute: their
            # table rows may still name blocks harvest already returned
            # to the free list and insert re-granted to ANOTHER slot —
            # the one aliasing hazard the whole-sequence arena never had.
            # Masking their rows to the sentinel P turns every such
            # gather into clamped (blended-away) garbage and every such
            # scatter into a drop.
            tab_step = jnp.where(active[:, None], state["block_tab"],
                                 jnp.int32(self._pool_blocks))
            if cfg.beam_factored_topk:
                gen, copy, gate, k_pool, v_pool = model.apply(
                    {"params": params}, mask_k, tok_in, pos_bk,
                    state["k_pool"], state["v_pool"], tab_step,
                    state["cross_k"], state["cross_v"], state["src_proj"],
                    valid[:, None, None, :],
                    method=FiraModel.dist_parts_step_paged,
                )
                new_tokens, new_probs, new_finished, src_beam = \
                    _select_factored(
                        gen[:, 0, :].reshape(S, K, -1),
                        copy[:, 0, :].reshape(S, K, -1),
                        gate[:, 0, :].reshape(S, K, 2),
                        tokens, probs, finished, pos_c, slot_src, cfg, neg)
            else:
                fused, k_pool, v_pool = model.apply(
                    {"params": params}, mask_k, tok_in, pos_bk,
                    state["k_pool"], state["v_pool"], tab_step,
                    state["cross_k"], state["cross_v"], state["src_proj"],
                    valid[:, None, None, :],
                    method=FiraModel.fused_probs_step_paged,
                )
                dist = fused[:, 0, :].reshape(S, K, -1)
                new_tokens, new_probs, new_finished, src_beam = _select(
                    dist, tokens, probs, finished, pos_c, slot_src, cfg, neg)
            # permute cached histories to follow their beams — the paged
            # twin of the unpaged gather below, moving block CONTENTS
            # within each active slot's own block set (table entries stay
            # put: a slot's grant is host-owned from insert to harvest).
            # Scatter targets are disjoint across slots because grants
            # never overlap; sentinel rows (idle/done, see tab_step) drop.
            idx = src_beam[None, :, None, :, None, None, None]

            def permute_pool(pool):
                blocks = pool[:, tab_step]       # (L, S, W, K, H, BS, dh)
                blocks = jnp.take_along_axis(blocks, idx, axis=3)
                return pool.at[:, tab_step].set(blocks, mode="drop")

            out_caches["k_pool"] = permute_pool(k_pool)
            out_caches["v_pool"] = permute_pool(v_pool)
        elif cfg.beam_kv_cache:
            # same per-row validity rule as beam_search_cached, at the
            # per-slot position vector
            valid = step_valid_mask(flat, pos_bk, T)
            tok_in = jnp.take_along_axis(flat, pos_bk[:, None], axis=1)
            if cfg.beam_factored_topk:
                gen, copy, gate, k_cache, v_cache = model.apply(
                    {"params": params}, mask_k, tok_in, pos_bk,
                    state["k_cache"], state["v_cache"],
                    state["cross_k"], state["cross_v"], state["src_proj"],
                    valid[:, None, None, :],
                    method=FiraModel.dist_parts_step_multi,
                )
                new_tokens, new_probs, new_finished, src_beam = \
                    _select_factored(
                        gen[:, 0, :].reshape(S, K, -1),
                        copy[:, 0, :].reshape(S, K, -1),
                        gate[:, 0, :].reshape(S, K, 2),
                        tokens, probs, finished, pos_c, slot_src, cfg, neg)
            else:
                fused, k_cache, v_cache = model.apply(
                    {"params": params}, mask_k, tok_in, pos_bk,
                    state["k_cache"], state["v_cache"],
                    state["cross_k"], state["cross_v"], state["src_proj"],
                    valid[:, None, None, :],
                    method=FiraModel.fused_probs_step_multi,
                )
                dist = fused[:, 0, :].reshape(S, K, -1)
                new_tokens, new_probs, new_finished, src_beam = _select(
                    dist, tokens, probs, finished, pos_c, slot_src, cfg, neg)
            # permute cached histories to follow their beams (exactly the
            # batched beam's gather). Inactive rows are NOT blended back:
            # a done/idle slot's cache is never read again — it is not
            # stepped, and a refill overwrites its cache rows wholesale
            # (insert zeroes k/v, rewrites cross/src) — so letting the
            # step scribble on it saves two full-cache select passes per
            # micro-step. tokens/probs/finished/pos DO blend below: they
            # must survive until harvest.
            #
            # GATED mode is the one exception: a verify-frozen row RESUMES
            # — permuting its cache by this frame's garbage src_beam would
            # hand the resumed step a shuffled history. Frozen rows get
            # the identity permutation instead (their cache bytes pass
            # through the gather unchanged); the plain trace (gate=None)
            # keeps the cheaper scribble, byte-for-byte as before.
            if gate is not None:
                src_beam = jnp.where(active[:, None], src_beam,
                                     jnp.arange(K)[None, :])
            idx = src_beam[None, :, :, None, None, None]

            def gather_cache(c):
                c = c.reshape(L, S, K, H, T, d_head)
                c = jnp.take_along_axis(c, idx, axis=2)
                return c.reshape(L, S * K, H, T, d_head)

            out_caches["k_cache"] = gather_cache(k_cache)
            out_caches["v_cache"] = gather_cache(v_cache)
        else:
            tar_mask = (flat != 0).at[:, 0].set(True)

            def at_pos(a):  # row b's own position out of the full-prefix decode
                return jnp.take_along_axis(
                    a, pos_bk[:, None, None], axis=1)[:, 0, :]

            if cfg.beam_factored_topk:
                gen, copy, gate = model.apply(
                    {"params": params}, state["states"], mask_k, flat,
                    tar_mask, method=FiraModel.dist_parts)
                new_tokens, new_probs, new_finished, _ = _select_factored(
                    at_pos(gen).reshape(S, K, -1),
                    at_pos(copy).reshape(S, K, -1),
                    at_pos(gate).reshape(S, K, 2),
                    tokens, probs, finished, pos_c, slot_src, cfg, neg)
            else:
                fused = model.apply(
                    {"params": params}, state["states"], mask_k, flat,
                    tar_mask, method=FiraModel.fused_probs)
                dist = at_pos(fused).reshape(S, K, -1)
                new_tokens, new_probs, new_finished, _ = _select(
                    dist, tokens, probs, finished, pos_c, slot_src, cfg, neg)

        tokens = jnp.where(active[:, None, None], new_tokens, tokens)
        probs = jnp.where(active[:, None], new_probs, probs)
        finished = jnp.where(active[:, None], new_finished, finished)
        new_pos = jnp.where(active, pos + 1, pos)
        all_fin_after = jnp.all(finished, axis=1)
        # the early-exit predicate, per slot: stopping is exact once the
        # settling step has re-sorted an all-finished beam set
        # (decode/beam._run_steps; tests/test_beam_early_exit.py), or when
        # the position budget is exhausted — the SLOT's own budget: its
        # decode bucket's tar under cfg.decode_tar_buckets (the paged
        # block reservation it was seated with), cfg.tar_len otherwise
        done = state["done"] | (active & ((new_pos >= state["limit"] - 1)
                                          | (all_fin_before & all_fin_after)))
        return (dict(state, tokens=tokens, probs=probs, finished=finished,
                     pos=new_pos, done=done, **out_caches),
                jnp.sum(active.astype(jnp.int32)))

    def _insert_fn(self, state, chunk, slot_ids, limits, block_rows):
        """Scatter chunk rows into slots. ``slot_ids``: (C,) int32, row j
        goes to slot ``slot_ids[j]``; the out-of-range sentinel S marks
        rows NOT consumed by this call (their scatter drops). ``limits``:
        (C,) int32 per-row tar budget. ``block_rows`` (paged arena only,
        else None): (C, W) int32 block grants, sentinel-P-padded past the
        row's reservation.

        INVARIANT — no cache zeroing, in EITHER arena. A fresh slot's
        unwritten cache positions are exactly -1e9-masked by the step's
        validity rule (beam.step_valid_mask) and exp(-1e9 - m) underflows
        to 0.0 in the stable softmax dtype, so stale values multiply a
        hard zero: the whole-sequence arena's old two full-arena zero
        scatters per refill bought nothing, and the paged arena has
        nothing to zero at all — freed blocks are simply UNMAPPED.
        tests/test_paged_kv.py pins this by object identity on the
        k/v buffers through an eager insert AND by bit-exact reuse of a
        dirty arena, so the zeroing cannot silently reappear."""
        cfg = self.cfg
        K = cfg.beam_size
        C = slot_ids.shape[0]
        tokens0, probs0, finished0, _neg = _init_beam(C, cfg)
        sid = slot_ids.astype(jnp.int32)
        sid_bk = jnp.repeat(sid, K) * K + jnp.tile(jnp.arange(K), C)

        new = dict(state)

        def put(field, value):
            new[field] = state[field].at[sid].set(value, mode="drop")

        put("tokens", tokens0)
        put("probs", probs0)
        put("finished", finished0)
        put("diff", chunk["diff"])
        put("sub_token", chunk["sub_token"])
        put("src_mask", chunk["src_mask"])
        new["pos"] = state["pos"].at[sid].set(0, mode="drop")
        new["live"] = state["live"].at[sid].set(True, mode="drop")
        new["done"] = state["done"].at[sid].set(False, mode="drop")
        new["limit"] = state["limit"].at[sid].set(
            limits.astype(jnp.int32), mode="drop")
        if cfg.beam_kv_cache:
            for f in ("cross_k", "cross_v"):
                new[f] = state[f].at[:, sid_bk].set(chunk[f], mode="drop")
            new["src_proj"] = state["src_proj"].at[sid_bk].set(
                chunk["src_proj"], mode="drop")
            if self._paged:
                # hand the seated rows their block grants; k_pool/v_pool
                # are untouched (see INVARIANT above)
                new["block_tab"] = state["block_tab"].at[sid].set(
                    block_rows.astype(jnp.int32), mode="drop")
        else:
            new["states"] = state["states"].at[sid_bk].set(
                chunk["states"], mode="drop")
        return new

    # --- state ----------------------------------------------------------

    def _ensure_state(self, chunk) -> None:
        """Allocate the slot arena (all slots dead) from the first chunk's
        shapes/dtypes. Plain host zeros + one device_put: no compiled
        program, so nothing for the compile guard to mis-attribute."""
        if self._state is not None:
            return
        cfg = self.cfg
        S, K, T = self.slots, cfg.beam_size, cfg.tar_len
        L, H = cfg.num_layers, cfg.num_head
        d_head = cfg.embedding_dim // H
        z = {
            "tokens": np.zeros((S, K, T), np.int32),
            "probs": np.zeros((S, K), np.float32),
            "finished": np.zeros((S, K), bool),
            "pos": np.zeros((S,), np.int32),
            "live": np.zeros((S,), bool),
            "done": np.zeros((S,), bool),
            "diff": np.zeros((S,) + chunk["diff"].shape[1:],
                             chunk["diff"].dtype),
            "sub_token": np.zeros((S,) + chunk["sub_token"].shape[1:],
                                  chunk["sub_token"].dtype),
            "src_mask": np.zeros((S,) + chunk["src_mask"].shape[1:], bool),
            # per-slot tar budget: full until an insert seats a
            # shorter-bucket sample (cfg.decode_tar_buckets)
            "limit": np.full((S,), T, np.int32),
        }
        if cfg.beam_kv_cache:
            ck = chunk["cross_k"]
            z["cross_k"] = np.zeros((L, S * K) + ck.shape[2:], ck.dtype)
            z["cross_v"] = np.zeros((L, S * K) + ck.shape[2:], ck.dtype)
            sp = chunk["src_proj"]
            z["src_proj"] = np.zeros((S * K,) + sp.shape[1:], sp.dtype)
            cd = chunk["cache_seed"].dtype
            if self._paged:
                P, BS, W = (self._pool_blocks, self._block_size,
                            self._table_width)
                z["k_pool"] = np.zeros((L, P, K, H, BS, d_head), cd)
                z["v_pool"] = np.zeros((L, P, K, H, BS, d_head), cd)
                z["block_tab"] = np.full((S, W), P, np.int32)  # all unmapped
            else:
                z["k_cache"] = np.zeros((L, S * K, H, T, d_head), cd)
                z["v_cache"] = np.zeros((L, S * K, H, T, d_head), cd)
            self._kv_bytes_per_slot = paging.kv_bytes_per_slot(
                cfg, paged=self._paged, block_size=self._block_size,
                pool_blocks=self._pool_blocks, slots=S,
                itemsize=np.dtype(cd).itemsize)
        else:
            st = chunk["states"]
            z["states"] = np.zeros((S * K,) + st.shape[1:], st.dtype)
        # firacheck: allow[RETIRED-RECHECK] arena-state write: retire() deliberately leaves the arena in place ("the arena and stats stay") and a dead engine's _state is never read again — only scheduling/guard state needs the post-dispatch re-check
        self._state = jax.device_put(z, self.device)

    # --- host scheduler --------------------------------------------------

    def _guard_step(self, label: str) -> None:
        if self.guard is not None:
            self.guard.step(label)

    def prewarm(self, warm_batches: Iterable[Tuple[Dict, Optional[str]]]
                ) -> None:
        """Compile the WHOLE program family up front: one all-pad batch
        per decode bucket geometry (the prefill compile keys), then one
        no-op insert (every slot id the drop sentinel), one step over the
        all-dead arena (no slot active — the state is untouched), and one
        harvest row gather. Outputs are unchanged by construction (pinned
        by the byte-equality tests); the point is that NO dispatch after
        prewarm pays a compile — which the per-dispatch wall-clock
        watchdog (docs/FAULTS.md) depends on: a first-use XLA compile
        inside a watchdogged dispatch would read as a hung replica."""
        chunk = None
        for host, tag in warm_batches:
            wire = {k: v for k, v in host.items() if not k.startswith("_")}
            chunk = self._prefill(self.params,
                                  jax.device_put(wire, self.device))
            self._guard_step(self.label(PREFILL_KIND, tag))
            self._ensure_state(chunk)
        if chunk is None:
            return
        C = int(chunk["diff"].shape[0])
        sentinel_ids = np.full((C,), self.slots, dtype=np.int32)  # all drop
        limits = np.full((C,), self.cfg.tar_len, dtype=np.int32)
        block_rows = (np.full((C, self._table_width), self._pool_blocks,
                              dtype=np.int32) if self._paged else None)
        self._state = self._insert(self._state, chunk, sentinel_ids,
                                   limits, block_rows)
        self._guard_step(self.label(INSERT_LABEL))
        self._state, occ = self._step(self._decode_params, self._state)
        self._guard_step(self.label(STEP_LABEL))
        if self._pending_occ is None:
            self._pending_occ = occ  # zero: no slot was active
        self._take_rows(self._state["tokens"], self._state["probs"],
                        jnp.int32(0))
        self._guard_step(self.label(HARVEST_LABEL))
        if self._spec_tier is not None:
            # compile the (S, k) draft/verify pair over the all-dead arena:
            # the verify's while_loop condition is false at frame 0 (no
            # live row), so the state passes through unchanged — but both
            # programs compile here, not inside a watchdogged dispatch
            km = f"k{self._spec_k}"
            drafts = self._draft(self._decode_params, self._state)
            self._guard_step(self.label(spec_lib.DRAFT_LABEL, km))
            self._state, occ, pend = self._verify(self._decode_params,
                                                  self._state, drafts)
            self._guard_step(self.label(spec_lib.VERIFY_LABEL, km))
            self._pending_occ = occ      # zeros: no slot was active
            self._pending_spec = pend

    # --- steppable scheduler pieces (the fleet round-robins these) -------

    def begin_stream(self) -> None:
        """Reset the host-side scheduling state for a fresh input stream
        (the slot arena and stats persist — stats accumulate across runs,
        exactly as before the scheduler was made steppable)."""
        self._staged: "collections.deque[_Staged]" = collections.deque()
        self._staged_rows = 0
        self._free: "collections.deque[int]" = collections.deque(
            range(self.slots))
        self._busy: Dict[int, Tuple[int, Dict, int]] = {}
        # paged-KV block allocator: the free list (a deque — O(1) grants)
        # and the per-slot grant map reset with the scheduler; the POOL
        # CONTENTS do not — stale block values are exactly masked, never
        # read (beam.step_valid_mask). Grants are refcounted: a block
        # returns to _free_blocks only at refcount zero (_release_blocks),
        # and double-grant/double-release assert (allocator_invariants).
        self._free_blocks: "collections.deque[int]" = collections.deque(
            range(self._pool_blocks))
        self._block_refs: Dict[int, int] = {}
        self._slot_blocks: Dict[int, List[int]] = {}
        # in-flight dedup maps (cfg.prefix_cache): digest -> leader
        # position for every admitted-but-unharvested row, and leader
        # position -> coalesced followers awaiting fan-out delivery
        self._inflight: Dict[str, int] = {}
        self._row_digest: Dict[int, str] = {}
        self._followers: Dict[int, List[Tuple[int, Dict, int]]] = {}
        # positions whose seat serves a fan-out group COALESCED ABOVE the
        # engine (the serve loop's fleet-global dedup keeps its followers
        # in the loop, not here) — stamped by the loop each round purely
        # so shared_block_peak meters those seats' grants too
        self.shared_positions: set = set()
        # cache miss-fills DEFERRED to the harvest boundary: admit only
        # schedules the D2H (copy_to_host_async) and parks the chunk
        # here; harvest — the engine's designated sync point — drains it.
        # Admission therefore never blocks on a prefill readback, and the
        # store-later window is covered by dedup (the rows' digests sit
        # in _inflight until the same harvest that drains their fill).
        self._pending_fills: List[Tuple[List[Tuple[int, str]], Dict]] = []

    # --- refcounted paged-block allocator -------------------------------

    def _acquire_blocks(self, need: int) -> List[int]:
        """Grant ``need`` blocks off the free deque at refcount 1. The
        caller checked availability (head-of-line admission); a granted
        block being granted again is an allocator bug, asserted here."""
        grant: List[int] = []
        for _ in range(need):
            b = self._free_blocks.popleft()
            assert self._block_refs.get(b, 0) == 0, \
                f"block {b} granted while already held (double grant)"
            self._block_refs[b] = 1
            grant.append(b)
        if self._leaks is not None:
            for b in grant:
                self._leaks.note_acquire(
                    "block", f"{self.tag or 'engine'}@{id(self):x}:{b}",
                    what=f"paged block {b}")
        return grant

    def _release_blocks(self, blocks) -> None:
        """Decrement each block's refcount; a block returns to the free
        deque only at refcount ZERO. Today every grant is exclusive
        (refcount 1 — fan-out sharing is SEAT-level: one grant serves
        the whole coalesced group, so no second holder exists), so the
        refcounts are the double-grant/double-release guard and the
        forward surface for true multi-holder mappings. Release paths:
        harvest (seat settled), retire (engine dead); a shed follower
        detaches without holding blocks at all."""
        for b in blocks:
            n = self._block_refs.get(b, 0)
            assert n > 0, f"block {b} released while not granted"
            if n == 1:
                del self._block_refs[b]
                self._free_blocks.append(b)
                if self._leaks is not None:
                    self._leaks.note_release(
                        "block", f"{self.tag or 'engine'}@{id(self):x}:{b}")
            else:
                self._block_refs[b] = n - 1

    def allocator_invariants(self) -> List[str]:
        """Machine-checkable allocator health (tier-1-pinned): every pool
        block is exactly free or granted, no block is granted twice, and
        refcounts agree with the grant map. Empty list = healthy."""
        errs: List[str] = []
        free = list(self._free_blocks)
        if len(set(free)) != len(free):
            errs.append("duplicate blocks on the free list")
        granted: Dict[int, int] = {}
        for slot, blocks in self._slot_blocks.items():
            for b in blocks:
                granted[b] = granted.get(b, 0) + 1
        for b, holders in granted.items():
            refs = self._block_refs.get(b, 0)
            if refs < holders:
                errs.append(f"block {b} held by {holders} grant(s) but "
                            f"refcount {refs}")
        for b, refs in self._block_refs.items():
            if refs < 1:
                errs.append(f"block {b} carries refcount {refs} <= 0")
        overlap = set(granted) & set(free)
        if overlap:
            errs.append(f"blocks {sorted(overlap)[:4]} both free and granted")
        if len(free) + len(self._block_refs) != self._pool_blocks:
            errs.append(
                f"free ({len(free)}) + granted ({len(self._block_refs)}) "
                f"!= pool ({self._pool_blocks})")
        return errs

    # --- prefix-cache surface -------------------------------------------

    def _artifact_fields(self) -> Tuple[str, ...]:
        return ((prefix_cache_lib.ARTIFACT_FIELDS_KV + ("cache_seed",))
                if self.cfg.beam_kv_cache
                else prefix_cache_lib.ARTIFACT_FIELDS_NOKV)

    def _drain_pending_fills(self) -> None:
        """Materialize deferred miss-fills (the D2H was scheduled async
        at admit) and store each row by its content digest. Runs at the
        harvest sync boundary only."""
        while self._pending_fills:
            fills, chunk = self._pending_fills.pop(0)
            chunk_host = {}
            for f in self._artifact_fields():
                chunk_host[f] = np.asarray(jax.device_get(chunk[f]))  # firacheck: allow[HOST-SYNC] deferred prefill-cache miss-fill draining at the harvest sync boundary; the D2H itself was scheduled async at admit (copy_to_host_async), so this materialization is the designated host copy, not a mid-admission stall
            entries = prefix_cache_lib.extract_payloads(
                chunk_host, [r for r, _d in fills], self.cfg.beam_size)
            for r, d in fills:
                self.stats.cache_evictions += self._cache.put(d, entries[r])

    def cache_contains(self, digest) -> bool:
        """Non-mutating cache probe (the serve loop partitions admission
        batches into hit/miss chunks with this — serve/server.py)."""
        return self._cache is not None and self._cache.contains(digest)

    def cache_put(self, digest, payload) -> None:
        """Seed one externally-prefilled artifact payload (the
        disaggregated prefill tier's delivery seam — serve/disagg.py):
        the next admission of this digest takes the all-hit cache path —
        host assemble + one device_put, ZERO prefill dispatches on this
        replica. Same eviction meter as a miss-fill; a no-op without a
        cache (cfg.prefix_cache off) or for a pad digest."""
        if self._cache is not None and digest is not None:
            self.stats.cache_evictions += self._cache.put(digest, payload)

    def cache_clear(self) -> None:
        """Drop every cached prefill entry (bench hygiene: a warm pass
        must not hand the timed window its hits)."""
        if self._cache is not None:
            self._cache.clear()

    def cache_len(self) -> int:
        return len(self._cache) if self._cache is not None else 0

    def wants_input(self) -> bool:
        """Prefill-ahead policy: keep ``engine_prefill_depth`` chunks
        staged, and at least enough rows to refill every free slot."""
        depth = max(1, int(self.cfg.engine_prefill_depth))
        return (len(self._staged) < depth
                or self._staged_rows < len(self._free))

    def in_flight(self) -> int:
        return len(self._busy)

    def in_flight_positions(self) -> List[int]:
        """Split positions currently seated in slots (the serving loop
        stamps seat/first-step latencies off this — serve/server.py)."""
        return [pid for (pid, _host, _row) in self._busy.values()]

    @property
    def staged_rows(self) -> int:
        """Admitted (prefilled) rows not yet seated in a slot."""
        return self._staged_rows

    def pending_positions(self) -> List[int]:
        """Every admitted-but-unfinished request position: seated in a
        slot, staged for refill, OR coalesced onto a seat as a dedup
        follower — exactly the set a retirement must requeue onto
        surviving replicas."""
        pos = [pid for (pid, _host, _row) in self._busy.values()]
        pos += [pid for e in self._staged for (_r, pid) in e.rows]
        pos += [fpos for fl in self._followers.values()
                for (fpos, _h, _r) in fl]
        return pos

    def retire(self) -> List[Dict]:
        """Mark THIS engine dead and hand back re-admission payloads for
        every request it still owed: one host batch per partially-served
        chunk with ``valid`` restricted to the owed rows and the rows'
        split positions pinned in ``_positions`` — same geometry, same
        ``_tag``, so re-prefilling them on a surviving replica stays
        inside the declared program family and (by per-row beam
        independence) reproduces the lost rows' results bit-exactly.
        Scheduling state clears; the arena and stats stay (a retired
        replica's commits are still real commits)."""
        self.retired = True  # set FIRST: stops an abandoned watchdog
        #                      thread the moment it wakes up
        groups: Dict[int, List] = {}
        hosts: Dict[int, Dict] = {}
        for _slot, (pid, host, r) in sorted(self._busy.items()):
            hosts[id(host)] = host
            groups.setdefault(id(host), []).append((r, pid))
        for entry in self._staged:
            hosts[id(entry.host)] = entry.host
            groups.setdefault(id(entry.host), []).extend(entry.rows)
        # dedup followers are owed requests too: each re-admits from its
        # OWN host batch (byte-identical payload), so a survivor serves
        # it bit-exactly whether it re-coalesces there or seats fresh —
        # re-admission payloads survive dedup instead of being lost
        for _leader, fl in sorted(self._followers.items()):
            for fpos, fhost, frow in fl:
                hosts[id(fhost)] = fhost
                groups.setdefault(id(fhost), []).append((frow, fpos))
        payloads: List[Dict] = []
        for hid, rows in groups.items():
            host = hosts[hid]
            requeued = dict(host)
            valid = np.zeros_like(np.asarray(host["valid"]))  # firacheck: allow[HOST-SYNC] host["valid"] is the feeder's host-side numpy batch field; no device value exists here
            positions = np.full(valid.shape[0], -1, dtype=np.int64)
            for r, pid in rows:
                valid[r] = True
                positions[r] = pid
            requeued["valid"] = valid
            requeued["_positions"] = positions
            payloads.append(requeued)
        # canonical order for determinism: by the smallest owed position
        payloads.sort(
            key=lambda b: int(b["_positions"][b["_positions"] >= 0].min()))
        self._busy.clear()
        self._staged.clear()
        self._staged_rows = 0
        self._free = collections.deque(range(self.slots))
        # RELEASE every seat's grant through the refcounted path (never
        # scribble the free list wholesale): shared blocks drop to zero
        # holders here, and the invariant checks stay meaningful on a
        # retired engine (the chaos leak check reads exactly this)
        for slot in list(self._slot_blocks):
            self._release_blocks(self._slot_blocks.pop(slot))
        self._inflight.clear()
        self._row_digest.clear()
        self._followers.clear()
        self._pending_fills.clear()   # a dead replica fills no cache
        return payloads

    def admit(self, host: Dict, index: int, device_batch=None) -> None:
        """Prefill one packed batch and stage its real rows for refill.
        ``device_batch``: the feeder's already-transferred wire batch;
        None (or an engine pinned to its own device — a fleet replica
        cannot use a chunk committed elsewhere) re-ships the host batch,
        stripping the "_"-prefixed host-only fields exactly like the
        feeder does.

        With ``cfg.prefix_cache`` armed, two host-side reuse passes run
        first (decode/prefix_cache.py): rows byte-identical to a request
        already in flight COALESCE onto the existing seat (fan-out at
        harvest), and a chunk whose remaining rows are ALL cached seats
        from the cache without dispatching prefill. Dedup/cache maps
        commit only AFTER staging succeeds, so a prefill that raises (or
        a watchdog abandonment) leaves no orphaned followers or phantom
        in-flight digests behind."""
        if self._faults is not None:
            self._faults.check("engine.prefill")
        if self.retired:
            return  # abandoned by a watchdog mid-dispatch; engine is dead
        positions = host.get("_positions")  # bucketed stream only
        valid = host["valid"]
        C = valid.shape[0]
        row_ids: List[Tuple[int, int]] = []
        for r in range(C):
            if not valid[r]:
                continue
            pos_id = (int(positions[r]) if positions is not None  # firacheck: allow[HOST-SYNC] _positions is a host-only numpy field (feeder strips it from the wire); no device value exists here
                      else index * C + r)
            row_ids.append((r, pos_id))
        digests = None
        if self._cache is not None and row_ids:
            digests = host.get("_digests")  # worker-side stamp when present
            if digests is None:
                # digests are TIER-NAMESPACED (decode/quant.py): a cached
                # f32 artifact can never seat a bf16 slot — a tier change
                # is a cache miss, never a wrong answer
                digests = prefix_cache_lib.payload_digests(
                    host, namespace=self._tier_ns)
        # PASS 1 — in-flight dedup (pure reads; maps commit below): rows
        # whose digest matches an admitted-but-unharvested row become
        # followers of that seat instead of taking one of their own
        followers: List[Tuple[int, int, int]] = []  # (leader_pos, pos, row)
        seat_rows: List[Tuple[int, int]] = []
        if digests is not None:
            batch_leaders: Dict[str, int] = {}
            for r, pos_id in row_ids:
                d = digests[r]
                leader = None
                if d is not None:
                    leader = self._inflight.get(d)
                    if leader is None:
                        leader = batch_leaders.get(d)
                if leader is not None:
                    followers.append((leader, pos_id, r))
                else:
                    if d is not None:
                        batch_leaders[d] = pos_id
                    seat_rows.append((r, pos_id))
        else:
            seat_rows = row_ids

        # PASS 2 — prefill-result cache: all-hit chunks assemble host-side
        # from cached artifacts (one device_put, ZERO compiled programs —
        # the insert sees the exact pytree the prefill would have produced)
        chunk = None
        payloads: Dict[int, Dict] = {}
        pending_fill = None
        st = self.stats
        if seat_rows and self._cache is not None and all(
                self._cache.contains(digests[r]) for r, _p in seat_rows):
            for r, _pos in seat_rows:
                payload, outcome = self._cache.take(digests[r])
                if outcome == "integrity_drop":
                    st.cache_integrity_drops += 1
                if payload is None:   # fault_miss / integrity_drop:
                    payloads.clear()  # the whole chunk re-prefills — a
                    break             # cache fault is a miss, never a
                #                       wrong answer
                payloads[r] = payload
        if seat_rows and len(payloads) == len(seat_rows) and payloads:
            st.cache_hits += len(payloads)
            st.cache_hbm_bytes_saved += sum(
                prefix_cache_lib.payload_nbytes(p) for p in payloads.values())
            st.prefills_saved += 1
            chunk = jax.device_put(
                prefix_cache_lib.build_chunk(payloads, C,
                                             self.cfg.beam_size),
                self.device)
            self._ensure_state(chunk)
        elif seat_rows:
            if device_batch is None or self.device is not None:
                wire = {k: v for k, v in host.items()
                        if not k.startswith("_")}
                device_batch = jax.device_put(wire, self.device)
            chunk = self._prefill(self.params, device_batch)
            if self.retired:
                # the watchdog expired while the prefill ran and the
                # replica was retired: its requests were requeued
                # elsewhere — staging them here too would decode them
                # twice (and no dedup/cache map was touched yet)
                return
            self._guard_step(self.label(PREFILL_KIND, host.get("_tag")))
            self._ensure_state(chunk)
            st.prefills += 1
            if self._cache is not None:
                # miss-fill, DEFERRED: schedule the artifact D2H now
                # (async — overlaps the decode steps) and store at the
                # next harvest, the designated sync boundary. Rows whose
                # entries existed but could not serve (this chunk
                # dispatched) count as misses and are refreshed there.
                st.cache_misses += len(seat_rows)
                fills = [(r, digests[r]) for r, _pos in seat_rows
                         if digests[r] is not None]
                if fills:
                    for f in self._artifact_fields():
                        a = chunk[f]
                        if hasattr(a, "copy_to_host_async"):
                            a.copy_to_host_async()
                    # committed below with the other shared maps: retire()
                    # clears _pending_fills ("a dead replica fills no
                    # cache"), and an abandoned thread appending after
                    # that clear would resurrect a fill on a dead engine
                    pending_fill = (fills, chunk)

        # COMMIT — maps and staging mutate only on a fully-successful
        # path, and only on a LIVE engine: the cache-hit branch above
        # dispatches nothing but still crossed a device_put a watchdog
        # could have abandoned this thread inside — committing here
        # would mutate _staged/_inflight/_followers under a concurrent
        # retire() (the same race the miss path's post-dispatch re-check
        # guards)
        if self.retired:
            return
        if pending_fill is not None:
            self._pending_fills.append(pending_fill)
        if followers:
            for leader, pos_id, r in followers:
                self._followers.setdefault(leader, []).append(
                    (pos_id, host, r))
            st.dedup_fanout += len(followers)
            if not seat_rows:
                st.prefills_saved += 1  # whole chunk coalesced: no dispatch
        if not seat_rows:
            return
        if digests is not None:
            for r, pos_id in seat_rows:
                if digests[r] is not None:
                    self._inflight[digests[r]] = pos_id
                    self._row_digest[pos_id] = digests[r]
        # the chunk's tar budget: its bucket geometry is visible in
        # the packed msg width (make_batch slices msg to the bucket's
        # tar) — under decode_tar_buckets that budget caps generation
        # and sizes the paged block reservation; otherwise every slot
        # gets the full arena budget, the historical behavior
        limit = (int(host["msg"].shape[1]) if self.cfg.decode_tar_buckets
                 else self.cfg.tar_len)
        self._staged.append(_Staged(
            chunk=chunk, host=host,
            rows=collections.deque(seat_rows), limit=limit))
        self._staged_rows += len(seat_rows)

    def refill(self, refill_order: str = "fifo") -> None:
        """Insert staged rows into every free slot (one insert dispatch
        per staged chunk touched). Paged arena: each seated row is granted
        its reservation — ceil(limit / block) blocks — from the free
        list; when the pool cannot cover the HEAD row's reservation the
        refill stops there and waits for harvests to return blocks
        (head-of-line, so admission order — hence output bytes — stays a
        pure function of the stream, pool size included)."""
        # retired-engine bail-early (docs/FAULTS.md): checked at every
        # loop boundary so an abandoned watchdog thread that wakes up
        # mid-refill stops mutating scheduling state a concurrent
        # retire() is handing to the survivors
        while not self.retired and self._free and self._staged:
            entry = self._staged[0]
            need = (paging.blocks_per_seq(entry.limit, self._block_size)
                    if self._paged else 0)
            if self._paged and len(self._free_blocks) < need:
                break  # head-of-line: blocks return at the next harvest
            C = entry.host["valid"].shape[0]
            slot_ids = np.full((C,), self.slots, dtype=np.int32)  # S = drop
            limits = np.full((C,), entry.limit, dtype=np.int32)
            block_rows = (np.full((C, self._table_width), self._pool_blocks,
                                  dtype=np.int32)  # P = unmapped sentinel
                          if self._paged else None)
            n_ins = 0
            while not self.retired and self._free and entry.rows and (
                    not self._paged or len(self._free_blocks) >= need):
                r, pos_id = entry.rows.popleft()
                slot = (self._free.popleft() if refill_order == "fifo"
                        else self._free.pop())
                slot_ids[r] = slot
                if self._paged:
                    grant = self._acquire_blocks(need)
                    block_rows[r, :need] = grant
                    self._slot_blocks[slot] = grant
                self._busy[slot] = (pos_id, entry.host, r)
                n_ins += 1
            new_state = self._insert(self._state, entry.chunk, slot_ids,
                                     limits, block_rows)
            if self.retired:
                # the watchdog expired while the insert dispatch ran and
                # the replica was retired: retire() already requeued
                # every owed row — the live loop owns the guard, stats,
                # and staging state now; this abandoned thread must not
                # touch them (RETIRED-RECHECK discipline)
                return
            self._state = new_state
            self._guard_step(self.label(INSERT_LABEL))
            self.stats.refills += 1
            self.stats.slots_refilled += n_ins
            self._staged_rows -= n_ins
            if not entry.rows:
                self._staged.popleft()

    def step_dispatch(self) -> None:
        """Dispatch one step program (async — the fleet dispatches every
        replica's step before any harvest readback, so replica compute
        overlaps across chips)."""
        if self._faults is not None:
            self._faults.check("engine.step")
        if self.retired:
            return  # abandoned by a watchdog mid-dispatch; engine is dead
        # speculative draft->verify->accept replaces the harvest-cadence
        # scan when armed and not cooling down after an acceptance stall
        # (decode/spec.py): the drafter reads the arena, the verify donates
        # it exactly like the plain step. Either program family member
        # advances every live slot at least one frame, so the
        # step->harvest cadence contract is unchanged.
        spec_now = self._spec_tier is not None and self._spec_cd == 0
        if spec_now:
            drafts = self._draft(self._decode_params, self._state)
            new_state, new_occ, new_spec = self._verify(
                self._decode_params, self._state, drafts)
        else:
            new_state, new_occ = self._step(self._decode_params, self._state)
            new_spec = None
        if self.retired:
            # the watchdog expired while the dispatch call was in flight:
            # do NOT touch the shared compile guard or stats from this
            # abandoned thread — the live loop owns them now
            return
        self._state, self._pending_occ = new_state, new_occ
        self._pending_spec = new_spec
        if self._spec_cd > 0:
            self._spec_cd -= 1
        st = self.stats
        if spec_now:
            km = f"k{self._spec_k}"
            self._guard_step(self.label(spec_lib.DRAFT_LABEL, km))
            self._guard_step(self.label(spec_lib.VERIFY_LABEL, km))
            # ONE step: the forwards-per-token accounting (see EngineStats)
            # — the frames the verify actually ran land in spec_frames at
            # harvest, where the device counters are drained
            st.steps += 1
            st.verify_dispatches += 1
        else:
            self._guard_step(self.label(STEP_LABEL))
            st.steps += max(1, int(self.cfg.engine_harvest_every))
        st.step_dispatches += 1
        # pool accounting, re-stamped every dispatch so the bench's stats
        # resets between timed windows keep the HBM fields populated
        st.pool_blocks = self._pool_blocks
        st.kv_block_size = self._block_size
        st.kv_bytes_per_slot = self._kv_bytes_per_slot
        st.kv_dtype = self.cfg.kv_dtype
        st.serve_precision = self.cfg.serve_precision
        if self._paged:
            used = self._pool_blocks - len(self._free_blocks)
            st.block_steps += used
            st.peak_blocks = max(st.peak_blocks, used)
            if self._followers or self.shared_positions:
                # shared blocks: grants whose seat is serving a coalesced
                # fan-out group — one block set, N requests' worth of
                # decode (the dedup half of the HBM-reuse story; groups
                # coalesced by the serve loop arrive via shared_positions)
                fan = self.shared_positions
                shared = sum(
                    len(self._slot_blocks.get(s, ()))
                    for s, (pid, _h, _r) in self._busy.items()
                    if pid in self._followers or pid in fan)
                st.shared_block_peak = max(st.shared_block_peak, shared)

    def harvest(self) -> List[EngineItem]:
        """Read back the dispatched step's done mask and return every
        newly settled slot's sample. The readback is SLICED: one jitted
        dynamic-index gather per settled slot copies only that slot's
        (tokens, probs) rows D2H instead of the whole arena per harvest —
        the saved bytes are metered (``harvest_bytes_saved``). COPIES,
        not views: the next dispatch DONATES the arena buffers, and on
        the CPU backend a zero-copy device_get view into a donated buffer
        dangles. Items are materialized EAGERLY (a plain list, not a lazy
        generator) for the same reason: a caller interleaving refill()
        between items would donate the arena out from under a pending
        row gather."""
        if self._faults is not None:
            self._faults.check("engine.harvest")
        if self.retired:
            return []  # abandoned by a watchdog; engine is dead
        if self._cache is not None and self._pending_fills:
            # commit deferred miss-fills BEFORE any dedup bookkeeping is
            # popped below: a digest leaves _inflight only once its
            # entry is stored, so a repeat arriving next round finds
            # either the in-flight leader or the cached artifacts
            self._drain_pending_fills()
        stats = self.stats
        occ_now = int(np.array(jax.device_get(self._pending_occ)))
        stats.occupied_slot_steps += occ_now
        if self._pending_spec is not None:
            # drain the verify's device counters at the SAME sync boundary
            # the occupancy/done readbacks already pay — spec metering
            # adds no host sync of its own (decode/spec.run_verify)
            tested, matched, iters = (
                int(x) for x in np.array(jax.device_get(self._pending_spec)))
            if self.retired:
                # the counter readback is a sync window a watchdog expiry
                # can abandon this thread inside; survivors own the
                # engine's scheduling state now — touch nothing
                return []
            self._pending_spec = None
            stats.drafted += self._spec_k * occ_now
            stats.accepted += matched
            stats.steps_saved += tested - occ_now
            stats.spec_frames += iters
            if occ_now and matched == 0:
                # acceptance stalled (a rare-token span the drafter cannot
                # see): run a few plain dispatches before re-arming, so a
                # cold stretch does not pay draft+verify per emitted token
                self._spec_cd = spec_lib.STALL_COOLDOWN
        done = np.array(jax.device_get(self._state["done"]))
        newly = [s for s in self._busy if done[s]]
        items: List[EngineItem] = []
        if newly:
            tokens, probs = self._state["tokens"], self._state["probs"]
            full_bytes = tokens.nbytes + probs.nbytes
            row_bytes = full_bytes // self.slots
            # PHASE 1 — readbacks only, no bookkeeping: a watchdog expiry
            # mid-device_get abandons this thread with every settled slot
            # still in _busy, so retire() requeues ALL of them (popping
            # as we read would strand the already-popped, never-delivered
            # requests). Phase 2 is pure host dict work — microseconds,
            # nothing left to hang on.
            reads = []
            for s in newly:
                if self.retired:
                    return []  # abandoned by a watchdog mid-harvest
                toks_s, probs_s = self._take_rows(tokens, probs,
                                                  jnp.int32(s))
                toks_np = np.array(jax.device_get(toks_s))  # firacheck: allow[HOST-SYNC] harvest IS the engine's designated output boundary: settled beams must reach the host to be cooked into text, and the sliced row gather is exactly the copy this readback exists to make
                probs_np = np.array(jax.device_get(probs_s))  # firacheck: allow[HOST-SYNC] same harvest output boundary as the line above
                if self.retired:
                    # the gather/readback above is exactly the window a
                    # watchdog expiry abandons this thread inside: the
                    # live loop owns the shared compile guard now
                    return []
                self._guard_step(self.label(HARVEST_LABEL))
                reads.append((s, toks_np, probs_np))
            if self.retired:
                return []
            # PHASE 2 — every readback landed: retire the bookkeeping
            for s, toks_np, probs_np in reads:
                pos_id, host, r = self._busy.pop(s)
                self._free.append(s)
                # the slot's block grant is RELEASED through the
                # refcounted allocator — contents stay as the slot left
                # them (unmapped, not zeroed; the next grantee's validity
                # mask makes them an exact 0.0), and a block returns to
                # the free deque only at refcount zero
                self._release_blocks(self._slot_blocks.pop(s, ()))
                stats.commits += 1
                stats.harvest_row_reads += 1
                stats.harvest_bytes_read += row_bytes
                items.append(EngineItem(position=pos_id, host=host, row=r,
                                        tokens=toks_np, probs=probs_np))
                # dedup fan-out delivery: every follower coalesced onto
                # this seat gets the leader's settled beams at its OWN
                # output position (one decode, N commits — byte-identical
                # by construction: same digest => same payload bytes)
                d = self._row_digest.pop(pos_id, None)
                if d is not None:
                    self._inflight.pop(d, None)
                for fpos, fhost, frow in self._followers.pop(pos_id, ()):
                    stats.commits += 1
                    items.append(EngineItem(position=fpos, host=fhost,
                                            row=frow, tokens=toks_np,
                                            probs=probs_np))
            stats.harvest_bytes_saved += full_bytes - row_bytes * len(reads)
        return items

    def run(self, feed, *, refill_order: str = "fifo"
            ) -> Iterator[EngineItem]:
        """Drive the engine over ``feed`` — an iterable of
        data.feeder.FedBatch items carrying the SAME packed batches the
        batched-beam path decodes (item.device is the prefill input;
        item.host keeps the text-cooking fields and the packer's
        ``_positions``/``_tag`` metadata).

        ``refill_order``: which freed slot a waiting request lands in —
        "fifo" (queue) or "lifo" (stack). Output is identical either way
        (results are keyed by split position and samples are slot-
        independent); the knob exists so the determinism tests can pin
        exactly that.

        Yields one :class:`EngineItem` per real sample as it settles.
        """
        if refill_order not in ("fifo", "lifo"):
            raise ValueError(f"refill_order {refill_order!r} not in "
                             f"{{'fifo', 'lifo'}}")
        self.begin_stream()
        feed_iter = iter(feed)
        exhausted = False

        while True:
            # prefill ahead: keep `depth` chunks staged, and at least
            # enough rows to refill every currently free slot
            while not exhausted and self.wants_input():
                try:
                    item = next(feed_iter)
                except StopIteration:
                    exhausted = True
                    break
                # a put=False feed (the fleet's shared queue) leaves
                # item.device == item.host; admit re-ships it then
                self.admit(item.host, item.index,
                           None if item.device is item.host else item.device)

            # refill every free slot from the staged queue
            self.refill(refill_order)

            if not self._busy:
                if exhausted:
                    break
                continue  # nothing in flight yet: pull more input

            self.step_dispatch()
            yield from self.harvest()
