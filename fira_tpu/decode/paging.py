"""Paged KV arena: host-side geometry, validation, and byte accounting.

The slot engine's self-attention caches used to be whole-sequence slot
stripes — every slot owned ``tar_len`` cache positions for its K beams,
so slot count and target length were coupled through HBM. Under
``cfg.engine_paged_kv`` (default) the caches live in a FIXED POOL of KV
blocks addressed through per-slot block tables (vLLM's PagedAttention,
SOSP '23 — PAPERS.md "Continuous batching / inference serving" — under
this stack's static-shape discipline: fixed pool size P, fixed table
width W, gather/scatter by block id). A slot is handed exactly the
blocks its decode bucket's tar budget reserves at insert and returns
them WHOLE at harvest — freed blocks are unmapped, never zeroed (the
validity mask already multiplies unwritten positions by an exact 0.0,
beam.step_valid_mask), and longer-target decode buckets become new
reservation sizes against the same pool instead of a per-length arena
blow-up.

This module is the HOST half: block-size/pool resolution, the parse-time
knob validation the CLI turns into exit 2 (named-knob messages, matching
parallel.mesh.divisibility_errors style), and the per-slot HBM
accounting the bench records (``kv_bytes_per_slot`` / ``pool_blocks`` /
``pool_utilization``). The device half — the block-table gather/scatter
the attention reads ride — lives in model/layers.py
(``gather_block_kv`` / ``append_block_kv``) and
model.Decoder.decode_step_paged; the allocator driving it is the
engine's scheduler (decode/engine.py).
"""

from __future__ import annotations

import math
from typing import List, Tuple

from fira_tpu.config import FiraConfig


def declared_decode_tars(cfg: FiraConfig) -> Tuple[int, ...]:
    """Every tar budget a decode slot can be admitted at, ascending.
    ``decode_tar_buckets`` off: just ``cfg.tar_len`` (the decode table
    pins tar full). On: each declared bucket's own tar plus the full
    fallback."""
    tars = {int(cfg.tar_len)}
    if cfg.decode_tar_buckets:
        for _ast, _edges, tar in cfg.buckets:
            # firacheck: allow[HOST-SYNC] cfg.buckets entries are parse-time host ints, not device values; this runs once at engine construction
            tars.add(int(tar))
    return tuple(sorted(tars))


def auto_block_size(tars: Tuple[int, ...]) -> int:
    """Default block size: the largest common divisor of every declared
    tar budget that is <= min(16, smallest_tar // 2) — at least two
    blocks per sequence whenever the geometry allows it, capped at the
    usual lane-friendly 16. Always valid (1 divides everything)."""
    g = 0
    for t in tars:
        # firacheck: allow[HOST-SYNC] tar budgets are host ints from the config table; knob resolution happens once, pre-compile
        g = math.gcd(g, int(t))
    cap = max(1, min(16, min(tars) // 2))
    best = 1
    for d in range(1, g + 1):
        if g % d == 0 and d <= cap:
            best = d
    return best


def resolve_block_size(cfg: FiraConfig) -> int:
    return int(cfg.kv_block_size) or auto_block_size(declared_decode_tars(cfg))


def blocks_per_seq(tar: int, block_size: int) -> int:
    """Blocks one slot reserves for a ``tar``-budget sequence (all K
    beams ride inside the block, so no beam factor here)."""
    return -(-int(tar) // int(block_size))


def resolved_slots(cfg: FiraConfig) -> Tuple[int, int]:
    """(per-replica slots, replica count) under the fleet's slot split:
    a nonzero ``engine_slots`` is the fleet TOTAL; 0 gives every replica
    ``test_batch_size`` slots of its own."""
    reps = max(1, int(cfg.engine_replicas))
    total = int(cfg.engine_slots)
    if total:
        return max(1, total // reps), reps
    return int(cfg.test_batch_size), reps


def auto_pool_blocks(cfg: FiraConfig, slots: int) -> int:
    """Full-residency default: every slot can hold a full ``tar_len``
    sequence concurrently — admission never blocks on blocks, so the
    paged scheduler is step-for-step identical to the unpaged arena."""
    return int(slots) * blocks_per_seq(cfg.tar_len, resolve_block_size(cfg))


def paging_errors(cfg: FiraConfig) -> List[str]:
    """Parse-time paging-knob admission check (the paged twin of
    parallel.mesh.divisibility_errors / fleet_divisibility_errors): one
    named-knob message per violation, CLI exit 2. Checks:

    - ``kv_block_size`` divides every declared decode tar budget (table
      width x block must tile each budget exactly);
    - ``kv_pool_blocks`` splits evenly across ``engine_replicas`` (it is
      the fleet TOTAL, like engine_slots);
    - per replica, pool >= slots x ceil(smallest tar / block) — the
      full-slot-concurrency floor on the smallest geometry — and
      pool >= ceil(largest tar / block) — one worst-case sample must
      always fit when the pool is empty, the no-livelock floor.
    """
    if not (cfg.decode_engine and cfg.beam_kv_cache and cfg.engine_paged_kv):
        return []
    errs: List[str] = []
    tars = declared_decode_tars(cfg)
    bs = resolve_block_size(cfg)
    if bs < 1:
        return [f"kv_block_size {cfg.kv_block_size} must be >= 1"]
    for t in tars:
        if t % bs:
            errs.append(
                f"kv_block_size {bs} does not divide decode tar budget {t} "
                f"(declared tars: {list(tars)}); block tables must tile "
                f"every budget exactly")
    slots, reps = resolved_slots(cfg)
    pool_total = int(cfg.kv_pool_blocks)
    if not pool_total:
        return errs  # auto pool: full residency, floors hold by construction
    if pool_total % reps:
        errs.append(
            f"kv_pool_blocks {pool_total} is not divisible by "
            f"engine_replicas {reps} (the fleet splits the total block "
            f"pool evenly across replicas, like engine_slots)")
        return errs
    pool = pool_total // reps
    if not errs:  # floors only meaningful once bs tiles the tars
        floor = slots * blocks_per_seq(tars[0], bs)
        if pool < floor:
            errs.append(
                f"kv_pool_blocks {pool} per replica < engine slots {slots} "
                f"x ceil(tar {tars[0]} / kv_block_size {bs}) = {floor}; "
                f"the pool must keep every slot servable on the smallest "
                f"decode tar budget")
        worst = blocks_per_seq(tars[-1], bs)
        if pool < worst:
            errs.append(
                f"kv_pool_blocks {pool} per replica < "
                f"ceil(tar {tars[-1]} / kv_block_size {bs}) = {worst}; one "
                f"largest-budget sample must fit an empty pool or the "
                f"scheduler can never admit it (livelock)")
    return errs


def prefix_cache_errors(cfg: FiraConfig) -> List[str]:
    """Parse-time prefix-cache knob admission check (docs/DECODE_ENGINE.md
    "Prefix cache & dedup"): one named-knob message per violation, CLI
    exit 2 — the cache twin of :func:`paging_errors`. The cache seats
    cached prefill artifacts into ENGINE slots, so it requires the engine
    path; its LRU needs at least one entry of capacity."""
    if not cfg.prefix_cache:
        return []
    errs: List[str] = []
    if not cfg.decode_engine:
        errs.append(
            "prefix_cache requires the decode engine (--engine, --perf "
            "production, or cli serve): cached prefill artifacts are "
            "seated into engine slots — the batched beam has no seat to "
            "map them into")
    if cfg.prefix_cache_entries < 1:
        errs.append(
            f"prefix_cache_entries {cfg.prefix_cache_entries} must be "
            f">= 1 cached prefill entry when prefix_cache is on (the LRU "
            f"needs capacity to hold at least one artifact set)")
    if cfg.prefix_cache_bytes < 0:
        errs.append(
            f"prefix_cache_bytes {cfg.prefix_cache_bytes} must be >= 0 "
            f"(0 = unbounded host bytes; otherwise the per-replica LRU "
            f"evicts until its payload bytes fit the budget)")
    return errs


def kv_itemsize(cfg: FiraConfig) -> int:
    """Bytes per K/V arena element under the serving tier (docs/
    DECODE_ENGINE.md "Low-precision tiers"): 2 when ``cfg.kv_dtype`` is
    ``bf16``, else the f32 default's 4. Host-side mirror of the engine's
    own accounting — the engine derives the itemsize from the prefill
    chunk's ``cache_seed`` dtype at allocation time; bench/test callers
    use this helper so their expected-bytes math names the same knob."""
    return 2 if cfg.kv_dtype == "bf16" else 4


def block_bytes(cfg: FiraConfig, block_size: int, itemsize: int) -> int:
    """HBM bytes of ONE pool block pair (K and V): all layers x all beam
    lanes x heads x block positions x head dim."""
    d_head = cfg.embedding_dim // cfg.num_head
    return (2 * cfg.num_layers * cfg.beam_size * cfg.num_head
            * int(block_size) * d_head * int(itemsize))


def kv_bytes_per_slot(cfg: FiraConfig, *, paged: bool, block_size: int,
                      pool_blocks: int, slots: int, itemsize: int) -> int:
    """The machine-recorded HBM claim: committed K+V self-attention cache
    bytes per engine slot. Unpaged: each slot owns a whole-sequence
    stripe. Paged: the pool is the commitment — its bytes amortize over
    the slots it serves, which is exactly where the equal-memory
    slot-count gain (or the longer-tar headroom) shows up."""
    if paged:
        return block_bytes(cfg, block_size, itemsize) * int(pool_blocks) \
            // max(1, int(slots))
    return block_bytes(cfg, 1, itemsize) * int(cfg.tar_len)
