"""Tracing and step-timing hooks.

The reference has no profiling at all — an unused ``import time`` and
step-rate prints (/root/reference/run_model.py:114-115,181-182). Here:

- ``trace(log_dir)``: context manager around ``jax.profiler`` producing a
  TensorBoard-loadable XPlane trace of everything inside it;
- ``step_annotation(step)``: names each training step in the trace so device
  timelines line up with host steps;
- ``Meter``: windowed wall-clock meter for steady-state throughput
  (items/sec) and step latency percentiles, excluding warm-up/compile steps.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Iterator, List, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Profile everything inside the block to ``log_dir`` (no-op if None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_annotation(step: int):
    """Label the current host step on the device timeline."""
    import jax

    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)


@dataclasses.dataclass
class Meter:
    """Steady-state throughput/latency meter with feed-stall attribution.

    ``warmup`` leading intervals are discarded (they contain compilation).
    Call ``tick(n_items, stall_s=...)`` once per completed step after
    syncing with the device — ``stall_s`` is how much of the interval the
    host spent blocked waiting on the input feed (data/feeder.py hands it
    per batch); read ``summary()`` at the end. ``feed_stall_frac`` is the
    denominator the next perf round needs: the share of steady-state wall
    clock that was feed, not device compute.
    """

    warmup: int = 1
    _intervals: List[float] = dataclasses.field(default_factory=list)
    _items: List[int] = dataclasses.field(default_factory=list)
    _stalls: List[float] = dataclasses.field(default_factory=list)
    _last: Optional[float] = None
    _seen: int = 0

    def start(self) -> None:
        self._last = time.perf_counter()

    def pause(self) -> None:
        """Exclude the time until the next start() (e.g. a dev-eval pass)."""
        self._last = None

    def tick(self, n_items: int = 1, stall_s: float = 0.0) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._seen += 1
            if self._seen > self.warmup:
                self._intervals.append(now - self._last)
                self._items.append(n_items)
                self._stalls.append(stall_s)
        self._last = now

    def summary(self) -> Dict[str, float]:
        if not self._intervals:
            return {"steps": 0, "items_per_sec": 0.0,
                    "mean_step_ms": 0.0, "p50_step_ms": 0.0,
                    "p99_step_ms": 0.0, "feed_stall_frac": 0.0,
                    "feed_stall_ms_per_step": 0.0}
        total_t = sum(self._intervals)
        xs = sorted(self._intervals)

        def pct(p: float) -> float:
            return xs[min(len(xs) - 1, int(p * len(xs)))]

        total_stall = sum(self._stalls)
        return {
            "steps": float(len(xs)),
            "items_per_sec": sum(self._items) / total_t,
            "mean_step_ms": 1e3 * total_t / len(xs),
            "p50_step_ms": 1e3 * pct(0.50),
            "p99_step_ms": 1e3 * pct(0.99),
            # share of measured wall clock the host spent blocked on the
            # input feed (assembly + transfer not hidden behind compute)
            "feed_stall_frac": min(1.0, total_stall / total_t),
            "feed_stall_ms_per_step": 1e3 * total_stall / len(xs),
        }
