"""Force-JAX-onto-CPU guard, shared by tests/conftest.py and the driver
contract (`__graft_entry__.dryrun_multichip`).

The sandbox registers a TPU-tunnel PJRT plugin ("axon") in every interpreter
via sitecustomize and pins JAX_PLATFORMS=axon. jax's first backends() call
then eagerly dials the tunnel even for CPU-only work — and hangs indefinitely
when the tunnel is down or busy. Multi-chip correctness checks run on virtual
CPU devices (XLA_FLAGS=--xla_force_host_platform_device_count=N), so any code
path that must work without the tunnel calls :func:`force_cpu_backend` BEFORE
its first jax API call.

Round-1 post-mortem: tests/conftest.py carried this guard but the driver's
`dryrun_multichip` did not, and the official multi-chip artifact timed out
(VERDICT.md "What's weak" #1). The guard now lives here so both entry points
share one implementation.
"""

from __future__ import annotations

import os
import re

_DEVICE_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _disabled_factory(*_a, **_k):
    raise RuntimeError(
        "non-cpu backend disabled by fira_tpu.utils.backend_guard")


def force_cpu_backend(n_virtual_devices: int | None = None) -> None:
    """Pin this interpreter to the CPU backend, immune to the TPU tunnel.

    Idempotent; safe to call multiple times. Must run before jax creates its
    first backend (calling it later still flips jax_platforms but cannot
    un-dial an already-initialized non-CPU backend — callers that may run
    after arbitrary jax use should prefer a fresh process).

    Args:
      n_virtual_devices: if given, ensure XLA_FLAGS requests at least this
        many virtual CPU host devices (no-op if the flag is already present —
        the driver sets it itself).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_virtual_devices is not None:
        xf = os.environ.get("XLA_FLAGS", "")
        m = re.search(_DEVICE_COUNT_FLAG + r"=(\d+)", xf)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                xf + f" {_DEVICE_COUNT_FLAG}={n_virtual_devices}").strip()
        elif int(m.group(1)) < n_virtual_devices:
            # A smaller preexisting count (e.g. leftover from a smaller run)
            # would make jax.devices("cpu") come up short; raise it.
            os.environ["XLA_FLAGS"] = (
                xf[:m.start()]
                + f"{_DEVICE_COUNT_FLAG}={n_virtual_devices}"
                + xf[m.end():])

    try:
        from jax._src import xla_bridge as xb

        for name in list(getattr(xb, "_backend_factories", {})):
            if name != "cpu":
                # Keep the name registered (mlir.register_lowering validates
                # platform names against this table — chex/checkify registers
                # tpu lowerings at import) but make the factory inert so
                # nothing ever dials the tunnel.
                import dataclasses as _dc

                entry = xb._backend_factories[name]
                if entry.factory is not _disabled_factory:
                    xb._backend_factories[name] = _dc.replace(
                        entry, factory=_disabled_factory)
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # older/newer jax layouts: fall back to the env vars alone
