"""Typed configuration for FIRA-TPU.

The reference keeps hyperparameters in a hardcoded DotDict literal in the
driver (/root/reference/run_model.py:30-46) with no CLI surface beyond the
positional ``train|test``. Here every knob is a frozen dataclass field, with
the reference values as defaults, plus named configs (fira-tiny / fira-full /
fira-large per BASELINE.json) and the three paper ablations as switches.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class FiraConfig:
    # --- sequence geometry (reference run_model.py:31-35) ---
    sou_len: int = 210          # diff tokens incl. <start>/<eos>
    tar_len: int = 30           # message tokens incl. <start>/<eos>
    att_len: int = 25           # max sub-tokens per integral token
    ast_change_len: int = 280   # AST-type nodes + edit-op nodes
    sub_token_len: int = 160    # deduplicated sub-token nodes

    # --- model (reference run_model.py:37-39, gnn_transformer.py:41-43) ---
    embedding_dim: int = 256
    num_head: int = 8
    num_layers: int = 6         # shared by GCN stack and decoder
    dropout_rate: float = 0.1   # attention / FFN / combination dropout
    gcn_dropout_rate: float = 0.2  # GCN-layer dropout (gnn_transformer.py:43)
    ffn_mult: int = 4           # FFN hidden = 4 * d (gnn_transformer.py:166)

    # --- vocabulary (filled in from data; run_model.py:44-56) ---
    vocab_size: int = 0
    ast_change_vocab_size: int = 0

    # --- optimization (run_model.py:36,40-43,396) ---
    lr: float = 1e-4
    batch_size: int = 170       # per-chip batch; reference scales 170 x n_gpus
    test_batch_size: int = 20
    epochs: int = 150
    beam_size: int = 3
    seed: int = 0
    # dev-gating cadence (run_model.py:89: epoch>=15, every 10 batches)
    dev_start_epoch: int = 15
    dev_every_batches: int = 10

    # --- ablations (paper Table 3; OUTPUT/output_fira_{no_edit,no_subtoken,nothing}) ---
    use_edit: bool = True           # False => drop change nodes + change edges
    use_subtoken_copy: bool = True  # False => no sub-token copy labels/pointer span

    # --- TPU-first data layout ---
    # Adjacency travels host->device as padded COO (senders/receivers/values),
    # NOT a dense graph_len^2 array (the reference densifies per sample,
    # Dataset.py:336-343 — its biggest throughput sin). Densification to a
    # batch of graph_len^2 happens once per step inside the jitted program.
    # Padded COO length per sample. The full-scale 90,661-commit corpus
    # measures p100 < 6,000 edges (fullscale/FULLSCALE.json era builds), so
    # 6144 keeps headroom while cutting the per-step adjacency scatter
    # stream 25% vs the old 8192 (the scatter is the single biggest op in
    # the round-4 step attribution, scripts/tpu_diag3.py ~22 ms of 86).
    # make_batch raises loudly if a sample ever exceeds it.
    max_edges: int = 6144
    # "dense": scatter COO into a (B, graph_len^2) adjacency once per step and
    #   run the GCN as a bmm (MXU-friendly at the reference's 650 nodes);
    # "segment": gather/scatter message passing directly on the COO triplets —
    #   O(edges) memory, the path that scales past the 650-node geometry.
    adjacency_impl: str = "dense"
    # Sort each sample's COO edges by (sender, receiver) on the host so the
    # on-device scatter gets indices_are_sorted=True (XLA can lower sorted
    # scatters without its sorting prologue). Semantically a no-op —
    # scatter-add order is irrelevant; equality is pinned by tests.
    sort_edges: bool = False
    # Lower the dense-adjacency build as ONE linearized 1-D scatter
    # (flat = (b*N+s)*N+r) instead of the batched 3-D scatter. With
    # sort_edges the flat stream is fully ascending, the friendliest index
    # pattern XLA can be promised. Bit-identical output (pinned by tests);
    # a measured perf candidate, dense path only.
    flat_scatter: bool = False
    # "single": one persistent (B, graph_len, d) encoder node buffer; each
    #   round static-update-slices the Combination rows in place. "split":
    #   the diff rows and the [sub||ast] rows live as two tensors for the
    #   whole stack and the GCN's A.x runs as two column-slab bmms
    #   (A[:,:,:sou] @ top + A[:,:,sou:] @ rest — same FLOPs; the two
    #   adjacency slabs are loop-invariant so XLA hoists them once) — no
    #   650-row buffer update ever materializes (the update-slice's
    #   (B,650,256) copy pairs are the largest single item in the round-4
    #   per-op trace, docs/TPU_OP_TIMES.json). Split sums the bmm in two
    #   parts, so outputs match "single" to matmul reassociation tolerance,
    #   not bitwise; dense adjacency only.
    encoder_buffer: str = "single"
    # "xla": pointer scores materialize the (B,T,S,D) tanh intermediate;
    # "pallas": fused kernel streams it through VMEM (ops/copy_score.py) —
    #   same math, no HBM intermediate (runs interpreted off-TPU).
    copy_head_impl: str = "xla"

    # --- precision ---
    # Compute dtype for matmuls/attention. Params and the fused output
    # distribution stay float32 for parity; bf16 is the TPU fast path.
    compute_dtype: str = "float32"
    # True (default): post-LN residual streams stay in the stable dtype
    # (f32 under bf16 compute) between layers — the reference's f32
    # numerics. False: LayerNorm statistics still compute in f32 but the
    # output is cast back to the compute dtype, halving every inter-layer
    # activation's HBM bytes under bf16. Exact no-op in f32; a measured
    # perf knob, not a parity path.
    stable_residual: bool = True
    # True (default): the copy head's (B,T,S,D) tanh intermediate is
    # rematerialized in backward (jax.checkpoint) instead of stored —
    # ~1 GB bf16 at flagship. False stores it: ~16 GB HBM chips can afford
    # that at batch 170, trading memory for the recompute.
    copy_head_remat: bool = True

    # --- decode ---
    beam_compat_prob_space: bool = True  # reference prob-space accumulation
                                         # (run_model.py:271,305); False => log-space
    beam_kv_cache: bool = True  # O(T) cached decode vs full-prefix re-decode
    # Beam candidate selection from the distribution FACTORS: per-side
    # top-k over the generation softmax (vocab) and the copy softmax
    # (sou+sub positions), gate-scaled and merged — 2k candidates per beam
    # instead of a top-k over the assembled 25,020-way fused tensor. Exact
    # for the top-k VALUES (the fused dist is the two sides scaled by their
    # gate weights, so any global top-k entry is inside a side's top-k);
    # ties between exactly-equal probabilities may break differently than
    # the fused scan order, which is why this is a knob and the
    # token-equality pins ride the test fixtures.
    beam_factored_topk: bool = False
    # Stop the decode loop once every beam of every batch item has emitted
    # EOS (plus ONE settling step), instead of always scanning tar_len-1
    # positions. Bit-exact vs the full scan: finished beams are masked to
    # the sentinel construction, whose only effect past saturation is a
    # single prob-descending re-sort of the beams — the settling step runs
    # it, after which the state is an element-wise fixed point (top_k is
    # stable on the already-sorted sentinel vector). The reference's own
    # Python loop early-exits the same way (run_model.py:276-279). Wall
    # clock scales with the batch's LONGEST message instead of tar_len —
    # the win on real corpora (mean message ~8-10 of 30 positions) is
    # bounded by the per-batch max length, so smaller test batches win
    # more. Parity default off; pinned equivalent in all four
    # kv-cache x factored-topk modes by tests/test_beam_early_exit.py.
    beam_early_exit: bool = False

    # --- continuous-batching decode engine (decode/engine.py) ---
    # True routes run_test through the slot-refill engine: S static slots
    # each advance their own beam one token per step program; EOS-settled
    # slots are harvested and refilled mid-flight from the packer stream,
    # so decode wall clock scales with TOTAL tokens emitted instead of
    # per-batch max length (Orca/vLLM iteration-level batching under this
    # stack's static shapes — docs/DECODE_ENGINE.md). Output is bit-exact
    # per sample vs the batched beam in all four kv-cache x factored-topk
    # modes (tests/test_engine.py).
    decode_engine: bool = False
    # Slot count S (the engine's fixed arena). 0 = test_batch_size: equal
    # geometry with the batched beam — the apples-to-apples default the
    # golden tests pin.
    engine_slots: int = 0
    # Prefilled chunks staged ahead of the refill loop (each holds one
    # packed batch's encoder outputs on device): 1 = prefill strictly on
    # demand; higher overlaps the next chunk's encoder work with the step
    # loop at O(depth * chunk encoder state) extra device memory.
    engine_prefill_depth: int = 2
    # Harvest cadence R: each step dispatch advances live slots R beam
    # positions (a lax.scan of identical one-step bodies) before the host
    # harvests/refills. Slots that settle mid-scan self-mask out, so the
    # cadence changes WHICH dispatch a harvest lands in, never the output
    # (pinned by tests/test_engine.py). R divides per-dispatch overhead
    # (dispatch latency + the done-mask readback sync + insert dispatch
    # coalescing) by R at the cost of settled slots idling up to R-1
    # micro-steps before refill — the R=4 default measures fastest on the
    # CPU length-mix bench (scripts/tpu_decode_bench.py engine_mixed row)
    # and the occupancy loss shows up honestly in slot_occupancy.
    engine_harvest_every: int = 4
    # --- paged KV arena (decode/paging.py; docs/DECODE_ENGINE.md) ---
    # True (default): the engine's per-slot self-attention K/V caches live
    # in a FIXED POOL of KV blocks addressed through per-slot block tables
    # (vLLM/PagedAttention under this stack's static shapes — gather/
    # scatter by block id, fixed pool size, fixed table width). Slot
    # residency decouples from sequence length: a slot holds only the
    # blocks its decode bucket's tar budget reserves, so engine_slots can
    # grow past what whole-sequence arenas allow at equal HBM and longer
    # tar buckets become new bucket-table entries instead of a per-length
    # arena blow-up. Per-sample BIT-exact (tokens AND probs) vs the
    # unpaged arena at the base tar geometry in all four kv-cache x
    # factored-topk modes (tests/test_paged_kv.py). False keeps the
    # whole-sequence arena — the comparator the equivalence tests pin
    # against. Only meaningful with beam_kv_cache (the non-cached engine
    # path holds no K/V to page).
    engine_paged_kv: bool = True
    # KV block size (positions per block). Must divide EVERY declared
    # decode tar length (cfg.tar_len plus, under decode_tar_buckets, each
    # bucket's tar) so block tables tile each budget exactly — validated
    # at parse time (decode/paging.paging_errors, CLI exit 2). 0 = auto:
    # the largest common divisor of the declared tars <= min(16, tar/2).
    kv_block_size: int = 0
    # Total KV pool size in blocks (the fleet-TOTAL, split evenly across
    # engine_replicas like engine_slots). Must keep every slot servable:
    # per replica, pool >= slots x ceil(smallest decode tar / block) and
    # >= ceil(largest decode tar / block) (one worst-case sample must
    # always fit — the no-livelock floor). 0 = auto: full residency,
    # slots x ceil(tar_len / block) per replica — byte-identical
    # scheduling to the unpaged arena.
    kv_pool_blocks: int = 0
    # True: the decode bucket table keeps each declared bucket's OWN
    # tar_len instead of pinning tar full, and the engine caps each
    # slot's generation at its bucket's tar budget (its block
    # reservation). Packing assigns by reference-message extent
    # (smallest admissible tar bucket). This is the longer-target-
    # geometry door: raise cfg.tar_len (say 64) and declare the common
    # case (say tar 30) as a bucket — short messages reserve half the
    # blocks, long ones get the full budget, ONE step program serves
    # both. Off (default): tar pinned full on every decode bucket, the
    # byte-identical historical behavior.
    decode_tar_buckets: bool = False
    # --- cross-request prefix cache + in-flight dedup (decode/prefix_cache
    # .py; docs/DECODE_ENGINE.md "Prefix cache & dedup") ---
    # True arms BOTH reuse mechanisms on the engine path: (a) the
    # content-addressed prefill-result cache — each request's prefill
    # artifacts (encoder output / per-beam cross K/V / copy-head src
    # projections) are keyed by a keyed-blake2b digest of its packed
    # payload, and a repeat request seats from the cached artifacts
    # WITHOUT dispatching prefill — and (b) in-flight dedup: a request
    # byte-identical to one already admitted coalesces onto the existing
    # seat and is delivered by fan-out at harvest (one decode, N output
    # positions, each request keeping its own arrival/deadline/TTFT
    # stamps). Both are host-side (no new program geometry: zero
    # post-warmup retraces hold with the cache armed) and bit-exact: a
    # cache-hit or deduped response is byte-identical to its cold run
    # (tests/test_prefix_cache.py). False (default) keeps the historical
    # byte-identical behavior — the equivalence comparator. `cli serve`
    # defaults this ON (--prefix-cache off opts out); drain decode opts
    # in via --prefix-cache on.
    prefix_cache: bool = False
    # LRU capacity of the prefill-result cache, in cached request entries
    # (per engine replica — caches are per-chip like the KV arena they
    # feed). Must be >= 1 when prefix_cache is on (validated at parse
    # time, exit 2 — decode/paging.prefix_cache_errors).
    prefix_cache_entries: int = 256
    # Optional HOST-memory budget for the cache in bytes, per replica
    # (entry payloads are per-beam cross K/V + src projections — MBs per
    # entry at production geometry, so an entry-count bound alone can
    # pin gigabytes of host RAM). 0 = unbounded (the entry cap is the
    # only bound); otherwise LRU entries evict until total payload bytes
    # fit. Must be >= 0 (validated at parse time, exit 2).
    prefix_cache_bytes: int = 0
    # Replicated-engine decode fleet (parallel/fleet.py; docs/MULTICHIP.md):
    # N SlotEngine replicas — one per device/data-mesh slice, each with its
    # own per-chip KV arena and compiled program set — pull packed chunks
    # from ONE shared admission queue, with harvest/refill interleaved
    # across replicas. 1 = the single-engine path, byte-identical behavior.
    # A nonzero engine_slots is the fleet-TOTAL arena and must divide by
    # the replica count (validated at parse time, exit 2); engine_slots=0
    # keeps the per-replica default (test_batch_size slots EACH). Decoded
    # file bytes are invariant to the replica count and to refill
    # interleaving (tests/test_fleet.py).
    engine_replicas: int = 1
    # --- speculative draft-and-verify decode (decode/spec.py;
    # docs/DECODE_ENGINE.md "Speculative drafting") ---
    # "off" (default) | "copy" | "draft": arm draft-and-verify on the slot
    # engine. A drafter proposes engine_spec_k tokens per live slot —
    # "copy": the copy-head distribution alone, scored from the cached
    # source projections against the raw target embedding (NO decoder
    # stack — near-free, rides FIRA's verbatim-copy fraction); "draft": a
    # greedy argmax roll of the existing cached step program on each
    # slot's top beam only (1/beam of the step's decoder rows, scratch
    # caches, real state untouched). ONE verify program then advances the
    # exact one-step body per drafted position under a per-row accept
    # gate (lax.while_loop — early-exits the dispatch once every row has
    # diverged), so ACCEPTED output is bit-exact vs the plain engine BY
    # CONSTRUCTION: every advanced position ran the identical step math,
    # and rejected tails simply were never advanced (tests/test_spec.py
    # pins tokens+probs+file bytes across kv x factored x paged modes,
    # k, replica count, and harvest cadence). Default off: the plain f32
    # non-spec path stays the byte-identical contract path.
    spec_decode: str = "off"
    # Drafted tokens per slot per verify dispatch (the (S, k) geometry of
    # the engine_draft/engine_verify program family). Must be in
    # [1, smallest declared decode tar budget - 1] and requires
    # decode_engine (validated at parse time, exit 2 —
    # decode/spec.spec_errors).
    engine_spec_k: int = 4
    # --- low-precision serving tiers (decode/quant.py;
    # docs/DECODE_ENGINE.md "Low-precision tiers") ---
    # Storage dtype of the decode self-attention K/V arena — the paged
    # pool's blocks AND the unpaged comparator stripes. "f32" (default)
    # is the byte-identical contract path; "bf16" stores the arena at
    # half the bytes (append casts on write, gathers upcast on read, so
    # attention math stays in the compute dtype) — kv_bytes_per_slot
    # halves and the equal-HBM slot count doubles again on top of the
    # paged pool's gain (docs/QUANT_BENCH_r01.jsonl). Engine/fleet
    # program labels carry the tier (…|bf16kv) and prefix-cache digests
    # are tier-namespaced, so a cached f32 artifact can never seat a
    # bf16 slot. Must be f32|bf16; a serving-tier knob, rejected on the
    # training path (validated at parse time, exit 2 —
    # decode/quant.quant_errors).
    kv_dtype: str = "f32"
    # Weight tier of the DECODE-ONLY program family (step / spec draft /
    # verify — prefill and the encoder stay f32): "f32" (default) is the
    # contract path; "bf16" stores the dominant decode matmul weights
    # (decoder stack, copy-head/vocab projections) in bf16 with the
    # matmuls accumulating in the compute dtype; "int8w" stores them as
    # per-channel symmetric int8 with on-the-fly dequant and f32
    # accumulate — quantized ONCE at engine build (and once per
    # respawn/spare prewarm), static shapes unchanged, labels suffixed
    # (…|int8w). Quality is measured, never assumed: BLEU delta +
    # per-request logprob divergence vs the f32 reference land in the
    # bench records (docs/QUANT_BENCH_r01.jsonl). Must be f32|bf16|int8w;
    # int8w/bf16 require decode_engine and are rejected on the training
    # path (validated at parse time, exit 2 — decode/quant.quant_errors).
    serve_precision: str = "f32"

    # --- online serving (serve/; docs/SERVING.md) ---
    # Offered load in requests/second for the open-loop Poisson arrival
    # generator (serve/arrivals.poisson_times). Only read by the serve
    # driver when no arrival-trace file is given; must then be > 0
    # (validated at parse time, CLI exit 2 — serve.server.serve_errors).
    serve_rate: float = 0.0
    # Latency-aware refill: the maximum prefill dispatches interleaved
    # between consecutive step dispatches, PER REPLICA. Every prefill
    # admitted mid-stream stalls the seated slots' next decode step, so
    # a small budget bounds the per-admission stall seated requests pay
    # (tail latency) while a large one maximizes admission throughput —
    # the A/B knob of the serve bench. Must be >= 1 and <= the
    # per-replica slot count (validated at parse time, exit 2).
    serve_prefill_budget: int = 1
    # Per-request deadline in STEP DISPATCHES (the scheduler's clock-free
    # time unit): a request still queued after this many step dispatches
    # since its arrival is SHED (recorded, never a hang); a seated
    # request always runs to harvest and a late completion is flagged,
    # not killed. 0 = no deadline. Must be 0 or >= 1 — a request cannot
    # complete in less than one step (validated at parse time, exit 2).
    serve_deadline_steps: int = 0
    # Admission-queue bound: an arrival that finds this many requests
    # already queued is rejected on the spot (structured shed-on-
    # backpressure — the rejection is recorded in ServeStats and the
    # output file keeps the position with an empty line). 0 = unbounded.
    serve_queue_cap: int = 0

    # --- disaggregated serving tiers (serve/disagg.py; docs/SERVING.md
    # "Disaggregated tiers") ---
    # Tier topology: "off" = historical in-process serve (prefill and
    # decode share the scheduler's jax runtime); "prefill-pool" =
    # DistServe-style process split — a pool of prefill worker processes
    # (each with its own jax runtime + params) computes seat-ready
    # artifacts (the prefix-cache payload) and ships them over a
    # pipe/shared-memory transport, so decode replicas admit every
    # request through the all-hit cache path and NEVER dispatch a
    # prefill program post-warmup. Requires prefix_cache and
    # decode_engine. Must be off|prefill-pool (validated at parse time,
    # exit 2 — serve.disagg.disagg_errors).
    serve_tiers: str = "off"
    # Prefill-pool width: worker processes in the prefill tier. Each
    # holds a full jax runtime (spawn-context process, the
    # ingest_exec=process template), so startup costs one runtime init +
    # per-bucket prefill compile per worker. Output bytes are invariant
    # to this knob by contract (tests/test_disagg.py). Must be >= 1
    # (validated at parse time, exit 2 — serve.disagg.disagg_errors).
    prefill_workers: int = 2
    # Backpressure bound on the prefill tier: total artifact bytes
    # in flight (submitted to workers, not yet delivered to the decode
    # tier's caches) stays under this budget, so a fast prefill tier
    # cannot OOM the host by racing ahead of decode. Sized from the
    # per-row artifact estimate the worker ready-handshake reports; a
    # single over-budget group alone still ships (same degrade rule as
    # the prefix cache's byte cap). 0 = unbounded. Must be >= 0
    # (validated at parse time, exit 2 — serve.disagg.disagg_errors).
    serve_artifact_budget_mb: int = 64

    # --- online raw-diff ingest (ingest/; docs/INGEST.md) ---
    # Feeder workers dedicated to per-request diff ingest tasks (parse +
    # AST extraction + encode + single-row assembly, run worker-side so
    # the scheduler thread never pays them). 0 = reuse feeder_workers —
    # the default; ingest is the same bounded worker pool as corpus
    # assembly, just heavier per task. Must be >= 0 (validated at parse
    # time, CLI exit 2 — ingest.service.ingest_errors).
    ingest_workers: int = 0
    # Over-budget policy for a diff whose measured extents exceed the
    # config geometry (sou/sub/ast-change/max_edges budgets):
    # "clip" (default) deterministically truncates — trailing diff
    # tokens at a chunk-safe boundary, whole tokens' sub-token lists,
    # trailing AST/change nodes with their edges, trailing family edges
    # — and records exactly what was dropped in the request's ingest
    # stamps; "shed" rejects the request with a recorded error (empty
    # output line, the quarantine contract). Either way the assembled
    # payload ALWAYS fits its bucket: admissibility is decided here, at
    # ingest, never by a mid-loop make_batch backstop. Must be
    # clip|shed (validated at parse time, exit 2).
    ingest_truncate: str = "clip"
    # --- ingest fast path (ingest/cache.py; docs/INGEST.md "Fast path") ---
    # True (default) arms BOTH ingest reuse layers on the raw-diff path:
    # (a) the whole-diff result cache — requests content-addressed by a
    # keyed blake2b digest of the raw diff BYTES at intake, in front of
    # lex/parse: a byte-identical repeat skips the entire lex/AST/
    # assemble pipeline and seats from a capacity/byte-bounded LRU of
    # assembled wire payloads, its `_ingest` stamps replayed with a
    # `cached` flag (the PR-10 prefill cache then also fires on the same
    # payload digest — two cache layers, one repeat); and (b) hunk-level
    # AST memoization — the AST parse/diff stage is memoized per typed
    # hunk content, so NEAR-identical diffs (one file changed out of
    # many) reuse parsed sub-results with the merge re-run
    # deterministically. Both are bit-exact: cache-on output bytes equal
    # cache-off equal the frozen-corpus path (tests + check.sh ingest-
    # cache smoke). False is the pristine comparator.
    ingest_cache: bool = True
    # Whole-diff result-cache LRU capacity in cached request entries.
    # 0 = unbounded (the byte budget, if set, is then the only bound).
    # Must be >= 0 (validated at parse time, CLI exit 2).
    ingest_cache_entries: int = 512
    # Optional host-memory budget for the whole-diff cache in bytes
    # (assembled single-row payloads are ~tens of KB at tiny geometry,
    # ~MB at production). 0 = unbounded. Must be >= 0 (validated at
    # parse time, exit 2).
    ingest_cache_bytes: int = 0
    # Execution mode for the GIL-bound AST parse/diff stage of ingest:
    # "thread" (default) runs it inline on the feeder worker threads
    # (the native astdiff calls release the GIL, but the JSON/tree/edge
    # mapping around them is pure Python); "process" ships the stage to
    # a spawned process pool sized by the ingest worker count — the
    # worker thread parks on the result (GIL released) while OTHER
    # workers keep lexing/assembling, so a slow AST parse never
    # head-of-line-blocks the next request's lex. Output is bit-exact
    # either way (the stage is a pure function of its inputs). Must be
    # thread|process (validated at parse time, exit 2).
    ingest_exec: str = "thread"

    # --- robustness / fault injection (robust/; docs/FAULTS.md) ---
    # Seeded fault-injection spec "site:kind:rate:seed[,...]" arming named
    # injection points along the request path (sites: feeder.assemble,
    # feeder.device_put, ingest.parse, engine.prefill, engine.step,
    # engine.harvest, fleet.replica, serve.admit, cache.lookup,
    # ingest.cache, disagg.transport, disagg.worker; kinds:
    # raise | hang | corrupt).
    # Deterministic given the seed — every chaos run replays exactly —
    # and validated at parse time (robust.faults.robust_errors, CLI
    # exit 2). "" = off: the injector is None and every site check is one
    # is-None branch, zero hot-path overhead.
    inject_faults: str = ""
    # Per-dispatch wall-clock watchdog in seconds: a fleet/serve replica
    # dispatch (prefill/step/harvest) that exceeds it is ABANDONED on its
    # worker thread and the replica retired, its in-flight requests
    # requeued onto survivors; in train, a dev gate that exceeds it is
    # skipped with a recorded warning instead of wedging the epoch.
    # 0 = off (dispatches run inline, zero overhead); must be 0 or > 0
    # (validated at parse time, exit 2).
    dispatch_watchdog_s: float = 0.0
    # Poison-request quarantine depth: how many retries (with backoff) a
    # request gets when its host-side assembly, admission, or prefill
    # raises, before it is SHED with a recorded error and an empty output
    # line (extending the serve shed contract — the feeder's per-task
    # error channel keeps one bad sample from poisoning the whole feed).
    # Must be >= 0 (validated at parse time, exit 2).
    robust_retries: int = 1
    # Wall seconds an injected "hang" fault sleeps — bounded on purpose,
    # so an unwatched chaos run stalls and recovers instead of wedging
    # forever; set it well above dispatch_watchdog_s to exercise
    # retirement. Must be > 0 (validated at parse time, exit 2).
    fault_hang_s: float = 2.0

    # --- self-healing fleet (robust/recovery.py; docs/FAULTS.md
    # "Recovery contracts") ---
    # Replacement budget PER REPLICA LINEAGE: how many times a retired
    # replica slot may be respawned (fresh engine on the dead replica's
    # device — params re-device_put, paged pool re-allocated, prewarmed
    # through the declared label family — or a warm spare attached)
    # before the lineage degrades permanently (the PR-9 retire-and-
    # requeue behavior). 0 (default) = respawn off: retirement stays
    # terminal, byte-identical historical behavior. Must be >= 0
    # (validated at parse time, exit 2 — recovery.recovery_errors).
    max_respawns: int = 0
    # Pre-built prewarmed standby engines (the warm-spare pool): a
    # retirement attaches a spare to the shared admission queue in O(1)
    # instead of paying a mid-run engine build + prewarm. Spares idle
    # until attached and count against max_respawns when they attach
    # (the budget bounds REPLACEMENTS, however they are built). Only
    # meaningful with max_respawns >= 1 (validated at parse time,
    # exit 2). Must be >= 0.
    engine_spares: int = 0
    # Respawn backoff BASE in wall seconds: a crash-looping lineage waits
    # the shared robust.faults.backoff_s curve (linear in the attempt,
    # capped at 5x) rescaled to this base between replacements — and, on
    # the deterministic virtual clock, min(attempt, 5) scheduler rounds
    # (wall sleeps only happen on the wall clock, the quarantine-backoff
    # split). Must be > 0 (validated at parse time, exit 2).
    respawn_backoff_s: float = 0.25

    # --- typed edges (beyond-parity extension) ---
    # The reference computes six edge families then flattens them into one
    # untyped adjacency (process_edge's `kind` is dead, Dataset.py:346-357;
    # SURVEY Appendix B). True learns one scalar gain per family
    # (graph_build.EDGE_KIND_*) applied to the normalized edge weights;
    # initialized to 1.0, i.e. exactly the reference graph at init.
    typed_edges: bool = False

    # --- dropout PRNG ---
    # "threefry" (default): JAX's counter-based generator, reproducible
    # across backends. "rbg": hardware random-bit generator — faster random
    # bits on TPU (dropout costs ~10 ms of the measured 107 ms fira-full
    # step, scripts/tpu_ablate.py det_nodropout). Param init is threefry
    # either way (identical initial weights); checkpoints store the key, so
    # a resume must use the impl it was trained with.
    rng_impl: str = "threefry"

    # --- gradient accumulation ---
    # >1 accumulates A micro-batches of batch_size into ONE optimizer step
    # normalized over the global (sum, count) — the single-chip reproduction
    # of the reference's 4-GPU DataParallel batch-680 dynamics
    # (run_model.py:102-105; A=4, batch_size=170 matches it exactly).
    # Mutually exclusive with fused_steps>1. Epoch tails smaller than A run
    # as ONE accumulated step padded with all-invalid micro-batches — the
    # same smaller-final-batch dynamics as the reference's DataLoader tail.
    # Composes with cfg.buckets: the grouped scheduler (data/grouping.py)
    # packs A same-geometry micro-batches per dispatch, per bucket.
    accum_steps: int = 1

    # --- device loop ---
    # >1 runs K train steps per dispatch via lax.scan over K stacked batches
    # (train.step.make_multi_step): host/dispatch overhead drops to 1/K and
    # the host loop can't jitter the chip. Semantics are step-identical to
    # K single dispatches (pinned by tests); dev-gate/log/checkpoint
    # boundaries round to group edges. NOTE the gate fires BEFORE the group
    # with the params from before it, so best-checkpoint evaluation can be
    # up to K-1 steps stale and multiple due gates inside one group collapse
    # to one — pick K dividing dev_every_batches (then the only staleness is
    # the gate-before-group ordering, same as the reference's evaluate-then-
    # train batch loop; train() now warns loudly — console + TrainResult
    # .warnings — when K does not divide the cadence). Epoch-tail batches
    # (< K) run per-step. Composes with cfg.buckets: the grouped scheduler
    # (data/grouping.py) packs K same-geometry batches per dispatch.
    fused_steps: int = 1

    # --- host input pipeline (data/feeder.py; docs/PIPELINE.md) ---
    # Background threads assembling batches (make_batch + sharded
    # device_put) ahead of the train/dev/decode loops. 0 = synchronous
    # assembly on the consumer thread (debug fallback + the control leg
    # feed_stall_frac is measured against). Batch ORDER is identical for
    # any worker count — the deterministic (seed, epoch) sequence is
    # computed up front and reassembled in order (pinned by tests).
    feeder_workers: int = 2
    # Max batches in flight (dispatched, not yet consumed): bounds host
    # memory at O(depth * batch_bytes) while keeping assembly + H2D ahead
    # of the step dispatch.
    feeder_depth: int = 4

    # --- bucketed padding geometry (data/buckets.py; docs/BUCKETING.md) ---
    # Declared family of smaller padding geometries, each entry
    # (ast_change_len, max_edges, tar_len) <= the full values above; the
    # full geometry is always the implicit fallback bucket. The packer
    # assigns every sample to its smallest admissible bucket and groups
    # same-bucket samples into batches, so XLA compiles |buckets|+1
    # programs per entry point — all pre-warmed at startup, zero
    # post-warmup retraces (the sanitizer learns the declared family).
    # () = off: the single-geometry path, byte-identical batches.
    # sou_len/sub_token_len are NOT bucketable (the copy-label id space
    # and fused output width bake them in). Composes with the grouped
    # device programs: fused_steps/accum_steps > 1 makes the scheduler
    # (data/grouping.py) pack bucket-HOMOGENEOUS groups of K (or A)
    # same-geometry batches per dispatch — the program family becomes
    # (geometry x entrypoint x group size), all pre-warmed, still zero
    # post-warmup retraces. The CLI's --buckets auto fills this from the
    # corpus length histograms.
    buckets: tuple = ()

    # --- long context ---
    # >1 routes decoder cross-attention through ring attention
    # (parallel/ring.py) over a (data, seq) mesh with that many sequence
    # shards: K/V blocks rotate on the ICI ring, peak attention memory drops
    # to O(T_local^2) per device. 0/1 = dense attention (FIRA's 370-key
    # geometry fits one chip; the knob is the long-context scaling path).
    seq_shards: int = 0

    @property
    def graph_len(self) -> int:
        # 650 = 210 + 160 + 280 (run_model.py note; paper §5.4 "up to 650 nodes")
        return self.sou_len + self.sub_token_len + self.ast_change_len

    @property
    def copy_len(self) -> int:
        # pointer span: diff positions + sub-token positions
        return self.sou_len + self.sub_token_len

    @property
    def output_vocab_size(self) -> int:
        # fused gen+copy distribution width (Model.py:81: 24650+210+160=25020)
        return self.vocab_size + self.sou_len + self.sub_token_len

    def replace(self, **kw) -> "FiraConfig":
        return dataclasses.replace(self, **kw)


# Named configs per BASELINE.json "configs".
def fira_full(**kw) -> FiraConfig:
    """Paper hyperparameters (reference run_model.py:30-46)."""
    return FiraConfig(**kw)


def fira_tiny(**kw) -> FiraConfig:
    """2-layer GNN, d=64 — CPU smoke / overfit config."""
    base = dict(
        embedding_dim=64,
        num_layers=2,
        num_head=4,
        sou_len=32,
        tar_len=12,
        att_len=6,
        ast_change_len=24,
        sub_token_len=24,
        batch_size=16,
        test_batch_size=8,
        epochs=30,
        dev_start_epoch=0,
        dev_every_batches=4,
        max_edges=512,
    )
    base.update(kw)
    return FiraConfig(**base)


def fira_large(**kw) -> FiraConfig:
    """8-layer, d=512, beam-8 (BASELINE.json v4-32 config)."""
    base = dict(
        embedding_dim=512,
        num_layers=8,
        beam_size=8,
    )
    base.update(kw)
    return FiraConfig(**base)


# The measured production performance knob set — the "stacked" row of the
# round-4 honest TPU ablation (docs/PERF.md: 68.75 ms/step vs 86.0 with the
# parity defaults at fira-full/170/bf16; the knobs interact, their solo
# deltas sum to less). Every knob is semantics-preserving or
# equivalence-tested; presets keep parity defaults, callers opt in:
#   cfg.replace(**PRODUCTION_PERF_KNOBS)
# bench.py applies this set by default (FIRA_BENCH_PRODUCTION_KNOBS
# overrides), so the single definition lives here.
PRODUCTION_PERF_KNOBS = {
    "rng_impl": "rbg",
    "fused_steps": 8,
    "sort_edges": True,
    "stable_residual": False,
    "copy_head_remat": False,
}


# The decode-side production set (VERDICT r5 item 5, the CPU-provable
# half): the three beam levers whose output equivalence is already pinned —
# beam_kv_cache (token-identical to full-prefix re-decode), factored
# per-side top-k (token-exact vs the assembled 25,020-way fused tensor),
# and the while_loop early exit (bit-exact tokens AND probs in all four
# kv x factored modes, tests/test_beam_early_exit.py). TPU bracket rows
# for the set (DECODE_BATCH 170/512, random + eos-saturated paramsets) are
# queued in the watchdog harvest (scripts/tpu_watchdog2.sh ->
# scripts/tpu_decode_bench.py); per-config defaults stay parity until
# those rows land. `--perf production` on the CLI applies this set
# alongside PRODUCTION_PERF_KNOBS.
DECODE_PERF_KNOBS = {
    "beam_kv_cache": True,
    "beam_factored_topk": True,
    "beam_early_exit": True,
    # Slot-refill continuous batching (decode/engine.py): run_test decodes
    # through the S-slot engine — per-sample bit-exact vs the batched beam
    # (tests/test_engine.py), wall clock scales with total tokens emitted.
    # engine_slots/engine_prefill_depth keep their config defaults (slots
    # = test_batch_size).
    "decode_engine": True,
}


NAMED_CONFIGS = {
    "fira-tiny": fira_tiny,
    "fira-full": fira_full,
    "fira-large": fira_large,
}


def get_config(name: str, **kw) -> FiraConfig:
    if name not in NAMED_CONFIGS:
        raise KeyError(f"unknown config {name!r}; choose from {sorted(NAMED_CONFIGS)}")
    return NAMED_CONFIGS[name](**kw)


def config_errors(cfg: FiraConfig) -> list:
    """Parse-time admission for the core train-loop knobs the CLI
    exposes with bare integer flags (--epochs/--fused-steps/
    --accum-steps/--seq-shards): one named-knob message per violation,
    CLI exit 2 — the same contract as mesh.divisibility_errors /
    serve.server.serve_errors, enforced for every CLI-writable knob by
    the firacheck KNOB-VALIDATE lint (docs/ANALYSIS.md)."""
    errs = []
    if cfg.epochs < 1:
        errs.append(f"epochs {cfg.epochs} must be >= 1")
    if cfg.fused_steps < 1:
        errs.append(
            f"fused_steps {cfg.fused_steps} must be >= 1 (1 = per-step "
            f"dispatch; K > 1 runs K steps per dispatch as one device "
            f"loop)")
    if cfg.accum_steps < 1:
        errs.append(
            f"accum_steps {cfg.accum_steps} must be >= 1 (1 = no "
            f"gradient accumulation)")
    if cfg.fused_steps > 1 and cfg.accum_steps > 1:
        errs.append(
            f"fused_steps {cfg.fused_steps} and accum_steps "
            f"{cfg.accum_steps} are mutually exclusive (one device-loop "
            f"axis per dispatch); set one of them to 1")
    if cfg.seq_shards < 0:
        errs.append(
            f"seq_shards {cfg.seq_shards} must be >= 0 (0/1 = dense "
            f"cross-attention, N > 1 ring-shards K/V over N devices)")
    return errs


def apply_ablation(cfg: FiraConfig, ablation: Optional[str]) -> FiraConfig:
    """Map the paper's ablation names onto config switches.

    no_edit     -> drop edit (change) nodes and their edges (Table 3 row 2)
    no_subtoken -> drop the sub-token copy pointer span (Table 3 row 3)
    nothing     -> both (Table 3 row 4)
    """
    if ablation in (None, "", "none", "full"):
        return cfg
    if ablation == "no_edit":
        return cfg.replace(use_edit=False)
    if ablation == "no_subtoken":
        return cfg.replace(use_subtoken_copy=False)
    if ablation == "nothing":
        return cfg.replace(use_edit=False, use_subtoken_copy=False)
    raise KeyError(f"unknown ablation {ablation!r}")
