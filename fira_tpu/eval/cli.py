"""Metric CLIs, drop-in equivalents of the reference Metrics/ scripts.

Usage (mirrors /root/reference/README.md:44-52):
    python -m fira_tpu.eval.cli bnorm   REF < HYP
    python -m fira_tpu.eval.cli penalty REF < HYP
    python -m fira_tpu.eval.cli rouge   -r REF -g HYP
    python -m fira_tpu.eval.cli meteor  -r REF -g HYP
"""

from __future__ import annotations

import argparse
import sys

from fira_tpu.eval import bnorm_bleu, penalty_bleu, rouge_l_files


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fira-metrics")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_bnorm = sub.add_parser("bnorm", help="B-Norm BLEU (ref file, hyps on stdin)")
    p_bnorm.add_argument("ref")
    p_pen = sub.add_parser("penalty", help="Penalty-BLEU (ref file, hyps on stdin)")
    p_pen.add_argument("ref")
    for name in ("rouge", "meteor"):
        p = sub.add_parser(name)
        p.add_argument("-r", "--ref_path", required=True)
        p.add_argument("-g", "--gen_path", required=True)

    args = parser.parse_args(argv)
    if args.cmd == "bnorm":
        with open(args.ref) as rf:
            print(bnorm_bleu(sys.stdin.readlines(), rf.readlines()))
    elif args.cmd == "penalty":
        with open(args.ref) as rf:
            print(penalty_bleu(sys.stdin.readlines(), rf.readlines()))
    elif args.cmd == "rouge":
        print(rouge_l_files(args.gen_path, args.ref_path))
    elif args.cmd == "meteor":
        from fira_tpu.eval.meteor import meteor_detail_files

        d = meteor_detail_files(args.gen_path, args.ref_path)
        if not d["wordnet"]:
            print("WARNING: wordnet corpus unavailable - native exact+stem "
                  "METEOR (strict lower bound, ~0.5 below the "
                  "wordnet-complete value; see eval/meteor.py)",
                  file=sys.stderr)
        print(d["value"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
