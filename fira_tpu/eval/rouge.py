"""ROUGE-L for commit messages, sumeval-equivalent.

The reference shells out to the ``sumeval`` CLI (/root/reference/Metrics/
Rouge.py:8-11), which is not installable in this environment, so ROUGE-L is
implemented in-repo. The pipeline was pinned EMPIRICALLY against the paper's
own numbers: lower-case, strip every non-alphanumeric character, whitespace
split, no stopword removal, no stemming, LCS F-measure with alpha=0.5,
averaged x100 over index-paired lines. On the shipped OUTPUT/ files this
reproduces all four published ROUGE-L rows simultaneously —
21.58 / 21.15 / 20.97 / 20.15 (FIRA / -edit / -subtoken / -nothing,
preprint Table 1+3) — each within +-0.005, which pins the tokenization as
sumeval's (tests/test_metrics_golden.py)."""

from __future__ import annotations

import re
from typing import Iterable, List, Sequence

_NON_ALNUM = re.compile(r"[^a-z0-9 ]")


def _tokenize(line: str) -> List[str]:
    """sumeval-equivalent preprocessing: lower-case, drop every character
    outside [a-z0-9 ], whitespace split."""
    return _NON_ALNUM.sub(" ", line.strip().lower()).split()


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0] * (len(b) + 1)
        for j, y in enumerate(b, start=1):
            cur[j] = prev[j - 1] + 1 if x == y else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


def rouge_l_sentence(hyp: str, ref: str, alpha: float = 0.5) -> float:
    h, r = _tokenize(hyp), _tokenize(ref)
    lcs = _lcs_len(h, r)
    if lcs == 0:
        return 0.0
    precision = lcs / len(h)
    recall = lcs / len(r)
    return precision * recall / ((1 - alpha) * precision + alpha * recall)


def rouge_l(hyp_lines: Iterable[str], ref_lines: Iterable[str]) -> float:
    """Mean sentence ROUGE-L F1 x100 over index-matched pairs."""
    refs = [r.strip() for r in ref_lines if r.strip()]
    hyps = list(hyp_lines)
    if not refs:
        return 0.0
    total = 0.0
    n = 0
    for i, ref in enumerate(refs):
        if i >= len(hyps):
            break
        total += rouge_l_sentence(hyps[i], ref)
        n += 1
    return total * 100.0 / max(n, 1)


def rouge_l_files(hyp_path: str, ref_path: str) -> float:
    with open(hyp_path) as h, open(ref_path) as r:
        return rouge_l(h.readlines(), r.readlines())
