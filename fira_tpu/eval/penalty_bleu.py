"""Penalty-BLEU: reference-length-weighted corpus BLEU.

Behavior-identical rebuild of /root/reference/Metrics/Bleu-Penalty.py: the
per-pair cooking is shared with B-Norm BLEU, but the corpus score is a
weighted mean where each pair's weight is its *effective reference length*
share (Bleu-Penalty.py:172-186 — the variable is named ``test_len`` there but
score_cooked returns totalcomps['reflen'] at :124, i.e. the shortest-ref
length; we reproduce that behavior, not the name). The reference prints the
raw [0,1] value; we scale x100 so the paper's Table 2 number (13.30) reads
directly. Golden test pins 13.299 on OUTPUT/output_fira.
"""

from __future__ import annotations

from typing import Iterable

from fira_tpu.eval.bnorm_bleu import _pair_by_index, sentence_bleu_stats


def penalty_bleu(hyp_lines: Iterable[str], ref_lines: Iterable[str]) -> float:
    pairs = _pair_by_index(hyp_lines, ref_lines)
    if not pairs:
        return 0.0
    scores = []
    weights = []
    for hyp, ref in pairs:
        score, ref_len = sentence_bleu_stats(hyp, [ref])
        scores.append(score)
        weights.append(ref_len)
    total_weight = float(sum(weights))
    if total_weight == 0:
        return 0.0
    return 100.0 * sum(w / total_weight * s for w, s in zip(weights, scores))


def penalty_bleu_files(hyp_path: str, ref_path: str) -> float:
    with open(hyp_path) as h, open(ref_path) as r:
        return penalty_bleu(h.readlines(), r.readlines())
