"""In-loop dev/test BLEU: NLTK sentence_bleu with method2 smoothing.

The reference gates its best-checkpoint decision on THIS metric
(/root/reference/run_model.py:22,171: nltk sentence_bleu, SmoothingFunction
method2), which differs from the reported B-Norm number. To reproduce the
same "best" checkpoint selection we implement method2 exactly: BLEU-4 with
uniform weights where every n-gram numerator and denominator gets +1 for
n > 1, and the standard exp brevity penalty. Falls back to NLTK itself when
available (they agree to float precision; see tests/test_metrics_golden.py).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import List, Sequence


def _ngrams(tokens: Sequence[str], n: int) -> Counter:
    return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))


def sentence_bleu_method2(
    references: List[Sequence[str]], hypothesis: Sequence[str]
) -> float:
    """nltk.translate.bleu_score.sentence_bleu(..., smoothing_function=method2).

    NLTK semantics replicated (verified against the installed NLTK in
    tests/test_metrics_golden.py): modified precision clips against the
    per-reference max count with denominator floored at 1 (so 4-grams of a
    3-token hypothesis contribute 0/1); a zero unigram match zeroes the whole
    score BEFORE smoothing; method2 then adds 1 to numerator and denominator
    for n >= 2 only; brevity penalty uses the closest reference length
    (ties -> shorter).
    """
    hyp_len = len(hypothesis)
    if hyp_len == 0:
        return 0.0

    # closest reference length (nltk closest_ref_length)
    ref_lens = [len(r) for r in references]
    closest = min(ref_lens, key=lambda rl: (abs(rl - hyp_len), rl))

    p_log_sum = 0.0
    for n in range(1, 5):
        hyp_counts = _ngrams(hypothesis, n)
        max_counts: Counter = Counter()
        for ref in references:
            for gram, c in _ngrams(ref, n).items():
                if c > max_counts[gram]:
                    max_counts[gram] = c
        clipped = sum(min(c, max_counts[g]) for g, c in hyp_counts.items())
        total = max(hyp_len - n + 1, 1)  # nltk modified_precision denominator
        if n == 1 and clipped == 0:
            return 0.0
        if n >= 2:
            clipped += 1
            total += 1
        p_log_sum += 0.25 * math.log(clipped / total)

    if hyp_len > closest:
        bp = 1.0
    else:
        bp = math.exp(1 - closest / hyp_len)
    return bp * math.exp(p_log_sum)


def nltk_sentence_bleu(references, hypothesis) -> float:
    """Prefer real NLTK when importable (exact reference behavior); otherwise
    use the in-repo replication above."""
    try:
        import nltk.translate.bleu_score as bleu_score

        smooth = bleu_score.SmoothingFunction().method2
        return bleu_score.sentence_bleu(
            references, hypothesis, smoothing_function=smooth
        )
    except Exception:
        return sentence_bleu_method2(list(references), hypothesis)
