"""Human-evaluation aggregation (reference C18).

The reference ships raw rater data only — HumanEvaluation/scores_{1..6}.csv,
one file per rater, 100 commits x 3 approaches, scores 0-4 — and reports the
per-approach means in the paper's Table 6 (FIRA 2.15 / CODISUM 2.06 /
NNGen 0.98). No aggregation code exists in the reference; this module is the
executable version of that table.

Column mapping (recovered by matching the means against Table 6):
approach1 = CODISUM, approach2 = FIRA, approach3 = NNGen.
"""

from __future__ import annotations

import csv
import glob
import os
from typing import Dict

APPROACH_NAMES = {"approach1": "CODISUM", "approach2": "FIRA",
                  "approach3": "NNGen"}


def aggregate(scores_dir: str) -> Dict[str, dict]:
    """Aggregate every scores_*.csv in ``scores_dir``.

    Returns {approach_name: {"mean": float, "n": int,
    "per_rater": {rater_file: mean}}}, scores averaged over
    commits x raters like the paper's Table 6.
    """
    paths = sorted(glob.glob(os.path.join(scores_dir, "scores_*.csv")))
    if not paths:
        raise FileNotFoundError(f"no scores_*.csv under {scores_dir}")
    totals = {k: 0 for k in APPROACH_NAMES}
    counts = {k: 0 for k in APPROACH_NAMES}
    per_rater: Dict[str, Dict[str, float]] = {k: {} for k in APPROACH_NAMES}
    for path in paths:
        rater = os.path.basename(path)
        r_tot = {k: 0 for k in APPROACH_NAMES}
        r_n = 0
        # utf-8-sig: the shipped files carry a BOM before the header
        with open(path, encoding="utf-8-sig") as f:
            for row in csv.DictReader(f):
                for k in APPROACH_NAMES:
                    score = int(row[k])
                    if not 0 <= score <= 4:
                        raise ValueError(f"{rater}: score {score} out of 0-4")
                    totals[k] += score
                    counts[k] += 1
                    r_tot[k] += score
                r_n += 1
        for k in APPROACH_NAMES:
            per_rater[k][rater] = r_tot[k] / max(r_n, 1)
    return {
        APPROACH_NAMES[k]: {
            "mean": totals[k] / max(counts[k], 1),
            "n": counts[k],
            "per_rater": per_rater[k],
        }
        for k in APPROACH_NAMES
    }


def main(argv=None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(
        description="aggregate FIRA human-evaluation rater CSVs (Table 6)")
    p.add_argument("scores_dir", help="directory holding scores_*.csv")
    args = p.parse_args(argv)
    result = aggregate(args.scores_dir)
    print(json.dumps(
        {k: {"mean": round(v["mean"], 4), "n": v["n"]}
         for k, v in result.items()}, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
