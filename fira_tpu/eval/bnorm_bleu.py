"""B-Norm BLEU — the metric of record for commit-message quality.

Behavior-identical rebuild of the reference scorer
(/root/reference/Metrics/Bleu-B-Norm.py): punctuation pre-split + lower-case
pairing keyed by line index (:131-155), NIST mteval-v11a normalization
(:10-42), per-sentence BLEU-4 with +1 smoothing on n>1 and the
(reflen+1)/(testlen+1) brevity penalty (:94-129), averaged x100 over pairs
(:160-169). Golden tests in tests/test_metrics_golden.py pin this module to
the frozen reference predictions (17.666 on OUTPUT/output_fira etc.).

One deliberate divergence: an empty hypothesis line is scored as the empty
string instead of crashing (the reference raises at Bleu-B-Norm.py:142); the
shipped OUTPUT files contain no empty lines, so golden numbers are unaffected.
"""

from __future__ import annotations

import math
import re
import sys
import xml.sax.saxutils
from typing import Iterable, List, Sequence, Tuple

_N = 4  # BLEU order

# mteval-v11a language-independent pass (Bleu-B-Norm.py:10-16)
_PRE_RULES = [
    (re.compile("<skipped>"), ""),
    (re.compile(r"-\n"), ""),
    (re.compile(r"\n"), " "),
]

# mteval-v11a western-language tokenization pass (Bleu-B-Norm.py:18-24)
_POST_RULES = [
    (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
    (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
    (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
    (re.compile(r"([0-9])(-)"), r"\1 \2 "),
]


def mteval_tokenize(text) -> List[str]:
    """NIST mteval-v11a normalize + tokenize (Bleu-B-Norm.py:26-42)."""
    if not isinstance(text, str):
        text = " ".join(text)
    for pat, rep in _PRE_RULES:
        text = pat.sub(rep, text)
    text = xml.sax.saxutils.unescape(text, {"&quot;": '"'})
    text = " %s " % text
    text = text.lower()
    for pat, rep in _POST_RULES:
        text = pat.sub(rep, text)
    return text.split()


def split_punct(line: str) -> str:
    """Word/punct splitter applied before pairing (Bleu-B-Norm.py:131-132)."""
    return " ".join(re.findall(r"[\w]+|[^\s\w]", line))


def _ngram_counts(words: Sequence[str], max_n: int = _N) -> dict:
    counts: dict = {}
    for n in range(1, max_n + 1):
        for i in range(len(words) - n + 1):
            gram = tuple(words[i : i + n])
            counts[gram] = counts.get(gram, 0) + 1
    return counts


def sentence_bleu_stats(
    hypothesis: str, references: Sequence[str]
) -> Tuple[float, int]:
    """Smoothed sentence BLEU and the effective (shortest) reference length.

    Mirrors cook_refs/cook_test/score_cooked (Bleu-B-Norm.py:52-129) for a
    single sentence pair: clipped n-gram matches against the per-n max count
    over references, +1 smoothing for n>1, and brevity penalty
    min(0, 1 - (reflen+1)/(testlen+1)).
    """
    ref_token_lists = [mteval_tokenize(r) for r in references]
    hyp = mteval_tokenize(hypothesis)

    max_ref_counts: dict = {}
    for ref in ref_token_lists:
        for gram, c in _ngram_counts(ref).items():
            if c > max_ref_counts.get(gram, 0):
                max_ref_counts[gram] = c
    ref_len = min(len(r) for r in ref_token_lists)

    guess = [max(len(hyp) - n + 1, 0) for n in range(1, _N + 1)]
    correct = [0] * _N
    for gram, c in _ngram_counts(hyp).items():
        correct[len(gram) - 1] += min(max_ref_counts.get(gram, 0), c)

    tiny = sys.float_info.min  # keeps log() total, as the reference does (:110)
    log_bleu = 0.0
    for n in range(_N):
        smooth = 1 if n > 0 else 0
        log_bleu += math.log(correct[n] + smooth + tiny) - math.log(
            guess[n] + smooth + tiny
        )
    log_bleu /= float(_N)
    log_bleu += min(0.0, 1.0 - float(ref_len + 1) / (len(hyp) + 1))
    return math.exp(log_bleu), ref_len


def _pair_by_index(
    hyp_lines: Iterable[str], ref_lines: Iterable[str]
) -> List[Tuple[str, str]]:
    """Index-matched (hyp, ref) pairs after the reference's cooking.

    References: blank lines dropped before numbering (Bleu-B-Norm.py:173).
    Both sides: strip, lower, punct-split (:146,153). Unpaired trailing
    hypotheses are silently ignored (OUTPUT/ground_truth is 7,660 lines vs
    7,661 predictions — the last prediction never scores).
    """
    refs = [r.strip() for r in ref_lines if r.strip()]
    hyps = list(hyp_lines)
    pairs = []
    for i, ref in enumerate(refs):
        if i >= len(hyps):
            break
        hyp = hyps[i]
        pairs.append(
            (split_punct(hyp.strip().lower()), split_punct(ref.strip().lower()))
        )
    return pairs


def bnorm_bleu(hyp_lines: Iterable[str], ref_lines: Iterable[str]) -> float:
    """Corpus B-Norm BLEU x100 (mean of per-pair smoothed BLEU-4)."""
    pairs = _pair_by_index(hyp_lines, ref_lines)
    if not pairs:
        return 0.0
    total = 0.0
    for hyp, ref in pairs:
        score, _ = sentence_bleu_stats(hyp, [ref])
        total += score
    return total * 100.0 / len(pairs)


def bnorm_bleu_files(hyp_path: str, ref_path: str) -> float:
    with open(hyp_path) as h, open(ref_path) as r:
        return bnorm_bleu(h.readlines(), r.readlines())
