from fira_tpu.eval.bnorm_bleu import bnorm_bleu, bnorm_bleu_files
from fira_tpu.eval.penalty_bleu import penalty_bleu, penalty_bleu_files
from fira_tpu.eval.rouge import rouge_l, rouge_l_files
from fira_tpu.eval.meteor import meteor, meteor_files
from fira_tpu.eval.dev_bleu import nltk_sentence_bleu, sentence_bleu_method2

__all__ = [
    "bnorm_bleu",
    "bnorm_bleu_files",
    "penalty_bleu",
    "penalty_bleu_files",
    "rouge_l",
    "rouge_l_files",
    "meteor",
    "meteor_files",
    "nltk_sentence_bleu",
    "sentence_bleu_method2",
]
