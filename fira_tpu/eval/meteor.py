"""METEOR, matching /root/reference/Metrics/Meteor.py:8-13: mean per-line
``nltk.translate.meteor_score`` x100 over index-paired files.

Two paths:

- wordnet available -> delegate to NLTK itself (exact parity with the
  reference by construction; its old NLTK split raw strings on whitespace,
  which we replicate by passing ``.split()`` tokens).
- wordnet corpus missing (this image is offline and ships no NLTK data) ->
  a native implementation of the same algorithm (Lavie-Agarwal alignment:
  exact stage, Porter-stem stage, fmean alpha=0.9, fragmentation penalty
  gamma=0.5 beta=3) MINUS the wordnet-synonym stage. The result is a strict
  lower bound on real METEOR: every synonym pair the wordnet stage would
  align is left unmatched. ``meteor_detail()`` reports which path ran; the
  paper's 14.93 can only be pinned where wordnet exists (documented in
  tests/test_metrics_golden.py).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple


def _wordnet_or_none():
    try:
        from nltk.corpus import wordnet

        wordnet.synsets("test")  # force the corpus load
        return wordnet
    except Exception:
        return None


# ---- native path (NLTK's algorithm, minus the wordnet stage) ----

def _match_stage(enum_hyp: List[Tuple[int, str]],
                 enum_ref: List[Tuple[int, str]], key) -> List[Tuple[int, int]]:
    """Greedy stage alignment over the not-yet-matched words, mirroring
    NLTK's _match_enums/_enum_stem_match traversal order. ``key`` is applied
    once per word (NLTK stems once too), not once per comparison."""
    keyed_hyp = [key(w) for _, w in enum_hyp]
    keyed_ref = [key(w) for _, w in enum_ref]
    matches = []
    for i in range(len(enum_hyp))[::-1]:
        for j in range(len(enum_ref))[::-1]:
            if keyed_hyp[i] == keyed_ref[j]:
                matches.append((enum_hyp[i][0], enum_ref[j][0]))
                enum_hyp.pop(i)
                keyed_hyp.pop(i)
                enum_ref.pop(j)
                keyed_ref.pop(j)
                break
    return matches


def _count_chunks(matches: List[Tuple[int, int]]) -> int:
    chunks = 1
    matches = sorted(matches, key=lambda m: m[0])
    for i in range(len(matches) - 1):
        if (matches[i + 1][0] == matches[i][0] + 1
                and matches[i + 1][1] == matches[i][1] + 1):
            continue
        chunks += 1
    return chunks


def _native_single(ref_words: List[str], hyp_words: List[str], *,
                   alpha: float = 0.9, beta: float = 3.0,
                   gamma: float = 0.5) -> float:
    from nltk.stem.porter import PorterStemmer

    stemmer = PorterStemmer()
    enum_hyp = list(enumerate([w.lower() for w in hyp_words]))
    enum_ref = list(enumerate([w.lower() for w in ref_words]))
    n_hyp, n_ref = len(enum_hyp), len(enum_ref)
    matches = _match_stage(enum_hyp, enum_ref, lambda w: w)
    matches += _match_stage(enum_hyp, enum_ref, stemmer.stem)
    m = len(matches)
    if m == 0 or n_hyp == 0 or n_ref == 0:
        return 0.0
    precision = m / n_hyp
    recall = m / n_ref
    fmean = precision * recall / (alpha * precision + (1 - alpha) * recall)
    frag = _count_chunks(matches) / m
    return (1.0 - gamma * frag ** beta) * fmean


def meteor_detail(hyp_lines: Iterable[str], ref_lines: Iterable[str]) -> dict:
    """{'value': mean x100, 'wordnet': bool}. See module docstring."""
    try:
        import nltk  # noqa: F401  (both paths need it: meteor_score / Porter)
    except Exception as e:  # pragma: no cover
        raise RuntimeError(f"nltk unavailable for METEOR: {e}")
    hyps = [h.rstrip("\n") for h in hyp_lines]
    refs = [r.rstrip("\n") for r in ref_lines]
    wn = _wordnet_or_none()
    scores: List[float] = []
    if wn is not None:
        from nltk.translate.meteor_score import meteor_score

        for ref, hyp in zip(refs, hyps):
            scores.append(meteor_score([ref.split()], hyp.split()))
    else:
        for ref, hyp in zip(refs, hyps):
            scores.append(_native_single(ref.split(), hyp.split()))
    value = 100.0 * sum(scores) / len(scores) if scores else 0.0
    return {"value": value, "wordnet": wn is not None}


def meteor(hyp_lines: Iterable[str], ref_lines: Iterable[str]) -> float:
    return meteor_detail(hyp_lines, ref_lines)["value"]


def meteor_detail_files(hyp_path: str, ref_path: str) -> dict:
    # reference splits on "\n" (Meteor.py:9-10), pairing trailing empty strings
    # too; zip() truncates to the shorter list the same way.
    with open(hyp_path) as h, open(ref_path) as r:
        return meteor_detail(h.read().split("\n"), r.read().split("\n"))


def meteor_files(hyp_path: str, ref_path: str) -> float:
    return meteor_detail_files(hyp_path, ref_path)["value"]
