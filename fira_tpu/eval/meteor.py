"""METEOR via NLTK, matching /root/reference/Metrics/Meteor.py:8-13:
mean nltk meteor_score over line-paired files, x100.

Modern NLTK requires pre-tokenized inputs (and the wordnet corpus); the
reference ran an older NLTK that accepted raw strings and split internally.
We pass ``.split()`` tokens, which is what old NLTK did with strings. If the
wordnet corpus is unavailable (offline image), ``meteor`` raises a clear
RuntimeError and callers should treat the metric as unavailable.
"""

from __future__ import annotations

from typing import Iterable


def meteor(hyp_lines: Iterable[str], ref_lines: Iterable[str]) -> float:
    try:
        from nltk.translate.meteor_score import meteor_score
    except Exception as e:  # pragma: no cover
        raise RuntimeError(f"nltk unavailable for METEOR: {e}")

    hyps = [h.rstrip("\n") for h in hyp_lines]
    refs = [r.rstrip("\n") for r in ref_lines]
    scores = []
    try:
        for ref, hyp in zip(refs, hyps):
            scores.append(meteor_score([ref.split()], hyp.split()))
    except LookupError as e:  # wordnet corpus missing
        raise RuntimeError(f"METEOR needs the NLTK wordnet corpus: {e}")
    if not scores:
        return 0.0
    return 100.0 * sum(scores) / len(scores)


def meteor_files(hyp_path: str, ref_path: str) -> float:
    # reference splits on "\n" (Meteor.py:9-10), pairing trailing empty strings
    # too; zip() truncates to the shorter list the same way.
    with open(hyp_path) as h, open(ref_path) as r:
        return meteor(h.read().split("\n"), r.read().split("\n"))
