"""Fused Bahdanau pointer scoring: score[b,t,s] = w . tanh(src[b,s] + tgt[b,t]) + bias.

This is the copy head's hot op (reference CopyNet, /root/reference/Model.py:
7-20: ``LinearRes(tanh(W_s.src_j + W_t.tgt_i))``). Naively it materializes a
(B, T, S, D) intermediate — 7.7 GB at the flagship geometry (B=170, T=30,
S=370, D=256) — which either OOMs alongside model+optimizer state or forces
rematerialization and small batches. The Pallas kernel streams S in chunks
through VMEM and never writes the intermediate to HBM: forward emits only
the (B, T, S) scores; the custom-VJP backward recomputes tanh chunkwise and
emits exactly the gradients (dsrc, dtgt, dw, dbias). Peak memory is
O(B.S.D) — the win is memory headroom, i.e. batch size. Wall-clock is at
parity in f32 (the op is tanh-VPU-bound: 8.1 vs 8.4 ms fwd at B=64 on v5e)
and ~8% behind XLA in bf16 training (the kernel pins tanh to f32 for
precision; XLA's fused path runs it in bf16) — so "xla" stays the default
and "pallas" is the choice when the intermediate doesn't fit.

Off-TPU the same kernels run under the Pallas interpreter, so CPU tests
validate the math; ``copy_scores_reference`` is the XLA oracle both paths
are checked against.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_CHUNK = 128          # S-chunk streamed through VMEM
_T_ALIGN = 8          # sublane alignment for the T dimension


def copy_scores_reference(src, tgt, w, bias):
    """XLA oracle: materializes the (B, T, S, D) intermediate."""
    inter = jnp.tanh(src[:, None, :, :] + tgt[:, :, None, :])
    return jnp.dot(inter, w)[..., 0] + bias[0]


def _pad_to(x, axis: int, mult: int):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _fwd_kernel(src_ref, tgt_ref, w_ref, out_ref):
    # tanh + matvec run in f32 whatever the input dtype: Mosaic rejects
    # HIGHEST-precision matmuls on bf16 operands, and f32 keeps parity with
    # XLA's fused path; the op is VPU-tanh-bound so this costs nothing.
    tgt = tgt_ref[0].astype(jnp.float32)                 # (Tp, D)
    Tp, D = tgt.shape
    n_chunks = src_ref.shape[1] // _CHUNK

    def body(j, _):
        s = src_ref[0, pl.ds(j * _CHUNK, _CHUNK), :].astype(jnp.float32)
        x = jnp.tanh(s[None, :, :] + tgt[:, None, :])    # (Tp, C, D)
        sc = jnp.dot(x.reshape(-1, D), w_ref[:, :],
                     preferred_element_type=jnp.float32,
                     precision=jax.lax.Precision.HIGHEST)  # (Tp*C, 1)
        out_ref[0, :, pl.ds(j * _CHUNK, _CHUNK)] = (
            sc.reshape(Tp, _CHUNK).astype(out_ref.dtype))
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)


def _bwd_kernel(src_ref, tgt_ref, w_ref, dout_ref,
                dsrc_ref, dtgt_ref, dw_ref):
    tgt = tgt_ref[0].astype(jnp.float32)                 # (Tp, D)
    Tp, D = tgt.shape
    w = w_ref[:, 0].astype(jnp.float32)                  # (D,)
    n_chunks = src_ref.shape[1] // _CHUNK

    def body(j, carry):
        dtgt_acc, dw_acc = carry
        s = src_ref[0, pl.ds(j * _CHUNK, _CHUNK), :].astype(jnp.float32)
        dout = dout_ref[0, :, pl.ds(j * _CHUNK, _CHUNK)].astype(jnp.float32)
        x = jnp.tanh(s[None, :, :] + tgt[:, None, :])    # (Tp, C, D)
        g = (1.0 - x * x) * w[None, None, :] * dout[..., None]
        dsrc_ref[0, pl.ds(j * _CHUNK, _CHUNK), :] = (
            jnp.sum(g, axis=0).astype(dsrc_ref.dtype))
        dtgt_acc = dtgt_acc + jnp.sum(g, axis=1)
        dw_acc = dw_acc + jnp.sum(x * dout[..., None], axis=(0, 1))
        return dtgt_acc, dw_acc

    dtgt_acc = jnp.zeros((Tp, D), jnp.float32)
    dw_acc = jnp.zeros((D,), jnp.float32)
    dtgt_acc, dw_acc = jax.lax.fori_loop(0, n_chunks, body,
                                         (dtgt_acc, dw_acc))
    dtgt_ref[0] = dtgt_acc.astype(dtgt_ref.dtype)
    dw_ref[0] = dw_acc[:, None].astype(dw_ref.dtype)


def _use_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def copy_scores(src, tgt, w, bias, interpret: Optional[bool] = None):
    """Fused pointer scores. src: (B,S,D), tgt: (B,T,D), w: (D,1),
    bias: (1,). Returns (B,T,S) in src.dtype."""
    return _copy_scores_fwd_impl(src, tgt, w, bias, interpret)


def _copy_scores_fwd_impl(src, tgt, w, bias, interpret):
    B, S, D = src.shape
    T = tgt.shape[1]
    src_p = _pad_to(src, 1, _CHUNK)
    tgt_p = _pad_to(tgt, 1, _T_ALIGN)
    Sp, Tp = src_p.shape[1], tgt_p.shape[1]

    out = pl.pallas_call(
        _fwd_kernel,
        out_shape=jax.ShapeDtypeStruct((B, Tp, Sp), src.dtype),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Sp, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, Tp, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((D, 1), lambda b: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Tp, Sp), lambda b: (b, 0, 0)),
        interpret=_use_interpret(interpret),
    )(src_p, tgt_p, w.astype(jnp.float32))
    return out[:, :T, :S] + bias[0].astype(src.dtype)


def _copy_scores_fwd(src, tgt, w, bias, interpret):
    return _copy_scores_fwd_impl(src, tgt, w, bias, interpret), (src, tgt, w)


def _copy_scores_bwd(interpret, residuals, dout):
    src, tgt, w = residuals
    B, S, D = src.shape
    T = tgt.shape[1]
    src_p = _pad_to(src, 1, _CHUNK)
    tgt_p = _pad_to(tgt, 1, _T_ALIGN)
    Sp, Tp = src_p.shape[1], tgt_p.shape[1]
    # zero-padded dout => padded rows/cols contribute nothing to any grad
    dout_p = _pad_to(_pad_to(dout, 1, _T_ALIGN), 2, _CHUNK)

    dsrc_p, dtgt_p, dw_part = pl.pallas_call(
        _bwd_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B, Sp, D), src.dtype),
            jax.ShapeDtypeStruct((B, Tp, D), tgt.dtype),
            jax.ShapeDtypeStruct((B, D, 1), jnp.float32),
        ],
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Sp, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, Tp, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((D, 1), lambda b: (0, 0)),
            pl.BlockSpec((1, Tp, Sp), lambda b: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, Sp, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, Tp, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, D, 1), lambda b: (b, 0, 0)),
        ],
        interpret=_use_interpret(interpret),
    )(src_p, tgt_p, w.astype(jnp.float32), dout_p)

    dsrc = dsrc_p[:, :S, :]
    dtgt = dtgt_p[:, :T, :]
    dw = jnp.sum(dw_part, axis=0).astype(w.dtype)
    dbias = jnp.sum(dout).reshape(1).astype(w.dtype)
    return dsrc, dtgt, dw, dbias


copy_scores.defvjp(_copy_scores_fwd, _copy_scores_bwd)
