"""Self-healing fleet: replica respawn, warm spares, crash-resume
(docs/FAULTS.md "Recovery contracts").

PR 9's degradation machinery stops at *retirement*: a faulted replica
leaves the rotation and survivors absorb its requests, but the capacity
is gone for good and losing every replica sheds the remaining stream.
This module closes the loop from failure back to full capacity, with
output bytes a pure function of the request stream under ANY
failure/recovery trace:

- **Replica respawn** — :class:`RecoveryManager` tracks one
  :class:`ReplicaSlot` per replica LINEAGE (``r1`` and every engine that
  ever replaced it share one respawn budget), gates each respawn on the
  shared backoff curve (:func:`respawn_backoff_s` — the
  ``robust.faults.backoff_s`` shape rescaled to the
  ``cfg.respawn_backoff_s`` base), and delegates construction to
  ``EngineFleet.replace_slot`` (fresh ``SlotEngine`` on the dead
  replica's device, params re-``device_put``, paged pool re-allocated,
  prewarmed through the declared label family) or to the warm-spare
  pool (``cfg.engine_spares`` pre-built prewarmed standby engines —
  replacement becomes O(attach) instead of O(compile)). A crash-looping
  lineage exhausts ``cfg.max_respawns`` and degrades permanently
  instead of flapping.

- **Crash-resume** — :class:`Journal`, an append-only write-ahead
  request journal next to the output file (one fsync'd JSONL record per
  request at admit and at done/shed, riding the atomic-metrics idiom).
  After a SIGKILL, :func:`recover_output` reads the
  ``OrderedStreamWriter`` crash pair (the plain ``.partial`` prefix plus
  the position-tagged ``.partial.tail``, torn trailing lines dropped)
  and ``cli serve --resume`` re-serves exactly the positions with no
  terminal line on disk: every position is emitted exactly once, and on
  a run whose requests all complete the final file is byte-identical to
  an uninterrupted run — machine-checked (tests/test_recovery.py,
  scripts/chaos_bench.py --recovery-smoke). A terminal outcome that
  REACHED disk — a finished prediction or a recorded shed's empty
  line — is final across a resume (re-adjudicating sheds could not be
  byte-stable either: shed decisions depend on load timing the resumed
  run does not reproduce).

Determinism: which bytes land at which position never depends on the
failure/recovery trace (per-row beam independence + the position-keyed
writer — the PR 9 contract); recovery only changes WHEN capacity comes
back, and that schedule is itself deterministic on the virtual clock
(backoff is measured in scheduler rounds there; on the wall clock it is
GATED in wall seconds — never slept on the serve scheduler thread, so
surviving replicas keep being stepped through a lineage's backoff).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.robust import faults as faults_lib

# the shared curve caps at 5x its base (faults.backoff_s: linear in the
# attempt, capped) — the same cap bounds the round-gated backoff below
_BACKOFF_CAP_ATTEMPTS = 5
# respawn tags: lineage origin + "~" + respawn ordinal ("r1" dies ->
# "r1~1" -> "r1~2"); "~" never appears in fleet ("r<i>") or spare
# ("sp<i>") tags, so origin recovery is one split
RESPAWN_TAG_SEP = "~"


# --------------------------------------------------------------------------
# parse-time knob validation (CLI exit 2 — the recovery twin of
# robust.faults.robust_errors / serve.server.serve_errors)
# --------------------------------------------------------------------------

def recovery_errors(cfg: FiraConfig) -> List[str]:
    """Named-knob recovery admission check: spare count, respawn budget,
    backoff base — one message per violation, CLI exit 2."""
    errs: List[str] = []
    if cfg.engine_spares < 0:
        errs.append(
            f"engine_spares {cfg.engine_spares} must be >= 0 pre-built "
            f"prewarmed standby engines")
    if cfg.max_respawns < 0:
        errs.append(
            f"max_respawns {cfg.max_respawns} must be >= 0 (0 = replica "
            f"respawn off — the PR-9 retire-and-degrade behavior)")
    if cfg.respawn_backoff_s <= 0:
        errs.append(
            f"respawn_backoff_s {cfg.respawn_backoff_s} must be > 0 wall "
            f"seconds (the per-lineage respawn backoff base; the shared "
            f"robust.faults.backoff_s curve scales from it)")
    if cfg.engine_spares > 0 and cfg.max_respawns == 0:
        errs.append(
            f"engine_spares {cfg.engine_spares} builds a standby pool "
            f"nothing can attach: max_respawns is 0 (respawn disabled); "
            f"set max_respawns >= 1 to let spares replace dead replicas")
    return errs


def respawn_backoff_s(attempt: int, base: float) -> float:
    """Per-lineage respawn backoff, wall seconds: the shared quarantine
    curve (robust.faults.backoff_s — linear in the attempt, capped at
    5x) rescaled from its 0.01 s base to ``cfg.respawn_backoff_s``. One
    curve definition repo-wide, so the backoff POLICY cannot silently
    fork between the retry sites and the respawn site."""
    return faults_lib.backoff_s(attempt) * (float(base) / 0.01)  # firacheck: allow[HOST-SYNC] base is the respawn_backoff_s config float; no device value exists here


def origin_of(tag: Optional[str]) -> str:
    """A replica tag's lineage origin: ``r1~2`` -> ``r1`` (every respawn
    of a slot shares the original replica's budget)."""
    return (tag or "r0").split(RESPAWN_TAG_SEP)[0]


# --------------------------------------------------------------------------
# write-ahead request journal (crash-resume)
# --------------------------------------------------------------------------

def times_digest(times) -> str:
    """Content digest of an arrival schedule (nanosecond-rounded), the
    resume admission check: a journal written for a different request
    stream must be rejected, not silently half-replayed."""
    t = np.asarray(times, dtype=np.float64)
    msg = ",".join(f"{x:.9f}" for x in t).encode()
    return hashlib.blake2b(msg, digest_size=8).hexdigest()


class Journal:
    """Append-only JSONL write-ahead request journal.

    One fsync'd record per request at admit and at done/shed (the
    OrderedStreamWriter/atomic-metrics crash discipline applied to
    request lifecycle): a SIGKILL at any instant leaves a parseable
    prefix whose torn trailing line :func:`read_journal` drops. The
    ``begin`` record pins the stream identity (request count + arrival
    digest + request-mix digest) so ``--resume`` can refuse a journal
    from a different run.
    """

    def __init__(self, path: str, *, n: int, times, mix=None,
                 resume: bool = False):
        self.path = path
        # resume APPENDS a new generation (the prior records are the
        # recovery source); a fresh run truncates
        self._f = open(path, "a" if resume else "w")
        try:
            self.append({"kind": "begin", "n": int(n),
                         "times_digest": times_digest(times),
                         "mix_digest": (times_digest(mix) if mix is not None
                                        else None),
                         "resume": bool(resume)})
        except BaseException:
            # the begin-record fsync can fail (full/dying disk); no caller
            # holds the half-built Journal yet, so nobody else can close
            # the handle we just opened (firacheck RES-LEAK)
            self._f.close()
            raise

    def append(self, rec: Dict) -> None:
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def append_many(self, recs: List[Dict]) -> None:
        """One write + one fsync for a batch of records (the per-round
        admit/done batches — still one RECORD per request)."""
        if not recs:
            return
        self._f.write("".join(json.dumps(r) + "\n" for r in recs))
        self._f.flush()
        os.fsync(self._f.fileno())

    def admit(self, positions: List[int]) -> None:
        self.append_many([{"kind": "admit", "pos": int(p)}
                          for p in positions])

    def done(self, positions: List[int]) -> None:
        self.append_many([{"kind": "done", "pos": int(p)}
                          for p in positions])

    def shed(self, pos: int, status: str, error: Optional[str]) -> None:
        self.append({"kind": "shed", "pos": int(pos), "status": status,
                     "error": error})

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def read_journal(path: str) -> Tuple[Optional[Dict], Dict[int, Dict]]:
    """Parse a journal: (first begin record, terminal record per
    position). A torn trailing line (no newline, or a partial JSON
    document — the SIGKILL case) is DROPPED, never an error; a done and
    a shed for the same position keep the latest (a resumed run may
    complete a request the killed run had shed un-persisted)."""
    meta: Optional[Dict] = None
    terminal: Dict[int, Dict] = {}
    if not os.path.exists(path):
        return None, {}
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    if lines and lines[-1] != b"":
        lines = lines[:-1]   # torn tail: the kill landed mid-write
    for line in lines:
        if not line:
            continue
        try:
            rec = json.loads(line)
        except (ValueError, UnicodeDecodeError):
            continue   # a torn interior line can only be the last one
            #            fsync'd mid-kill; skipping it is the truncation
        kind = rec.get("kind")
        if kind == "begin" and meta is None:
            meta = rec
        elif kind in ("done", "shed") and "pos" in rec:
            terminal[int(rec["pos"])] = rec  # firacheck: allow[HOST-SYNC] rec is a parsed JSON journal record (host dict); no device value exists here
    return meta, terminal


class ResumeError(ValueError):
    """A ``--resume`` admission failure (missing/mismatched journal):
    the CLI converts exactly this — never an arbitrary mid-run
    ValueError — into its named exit-2 contract."""


def missing_journal_error(path: str) -> str:
    """The one definition of the no-prior-run message (the CLI's early
    pre-dataset check and :func:`resume_errors` both print it — one
    string, no drift)."""
    return (f"--resume requires an existing serve journal at {path} "
            f"(no prior `cli serve` run to resume)")


def resume_errors(path: str, n: int, times, mix=None) -> List[str]:
    """Admission check for ``--resume``: the journal must exist, parse,
    and pin the SAME request stream (count + arrival digest +
    request-mix digest). Named messages, CLI exit 2."""
    if not os.path.exists(path):
        return [missing_journal_error(path)]
    meta, _ = read_journal(path)
    if meta is None:
        return [f"--resume: journal {path} holds no begin record (the "
                f"prior run died before its first fsync — rerun without "
                f"--resume)"]
    errs: List[str] = []
    if int(meta.get("n", -1)) != int(n):
        errs.append(
            f"--resume: journal {path} was written for {meta.get('n')} "
            f"requests but this run offers {n} (a different request "
            f"stream cannot be resumed)")
    elif meta.get("times_digest") != times_digest(times):
        errs.append(
            f"--resume: journal {path} was written for a different "
            f"arrival schedule (digest mismatch — same trace/seed/rate "
            f"required)")
    elif meta.get("mix_digest") != (times_digest(mix)
                                    if mix is not None else None):
        errs.append(
            f"--resume: journal {path} was written for a different "
            f"request->sample mix (mix digest mismatch — recovered lines "
            f"and the re-served suffix would mix two request identities)")
    return errs


def _complete_lines(path: str) -> List[str]:
    """Every COMPLETE (newline-terminated) line of ``path``, bytes split
    on b"\\n" only — never str.splitlines, whose extra boundaries
    (\\x0b, \\u2028, ...) would shift positions inside a prediction line
    and silently break resume byte-identity. A torn trailing fragment
    (the SIGKILL case) is dropped."""
    with open(path, "rb") as f:
        raw = f.read()
    pieces = raw.split(b"\n")[:-1]   # the post-final-\n fragment (torn
    #                                  or empty) carries no complete line
    return [(p + b"\n").decode("utf-8") for p in pieces]


def recover_output(out_path: str, expected: int) -> Dict[int, str]:
    """Recover every finished line of an interrupted (or completed) run:
    the contiguous ``.partial`` prefix plus the position-tagged
    ``.partial.tail`` spill (the OrderedStreamWriter crash pair), torn
    trailing lines dropped; a completed run recovers from the final file
    itself. Returns {position: line-with-newline} — the exactly-once
    seed the resume writer re-emits verbatim."""
    recovered: Dict[int, str] = {}
    partial = out_path + ".partial"
    tail = out_path + ".partial.tail"
    if os.path.exists(out_path) and not os.path.exists(partial):
        for pos, line in enumerate(_complete_lines(out_path)):
            if pos < expected:
                recovered[pos] = line
        return recovered
    if os.path.exists(partial):
        for pos, line in enumerate(_complete_lines(partial)):
            if pos < expected:
                recovered[pos] = line
    if os.path.exists(tail):
        for raw in _complete_lines(tail):
            if "\t" not in raw:
                continue   # malformed tail record
            pos_s, line = raw.split("\t", 1)
            try:
                pos = int(pos_s)  # firacheck: allow[HOST-SYNC] pos_s is a position tag parsed from the writer's on-disk tail spill; no device value exists here
            except ValueError:
                continue
            if 0 <= pos < expected:
                recovered[pos] = line
    return recovered


# --------------------------------------------------------------------------
# respawn policy
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ReplicaSlot:
    """One replica lineage's health record: the original replica and
    every engine that ever replaced it share this budget/backoff state."""

    origin: str
    device: Any = None
    respawns: int = 0            # replacement attempts consumed (spares
    #                              count — the budget bounds REPLACEMENTS)
    alive: bool = True
    retired_round: int = -1      # scheduler round of the latest retirement
    retired_wall: float = -1.0   # monotonic stamp of it (wall-clock gate)
    last_error: str = ""


class RecoveryManager:
    """Health-driven respawn policy over one engine fleet.

    Decisions only — construction is ``fleet.replace_slot`` (which owns
    the spare pool, the device placement, and the prewarm-through-the-
    declared-family contract). Backoff is gated in scheduler ROUNDS
    (``retired_round + min(attempt, 5)`` — deterministic on the virtual
    clock) and additionally GATED (never slept — the scheduler thread
    keeps stepping the survivors) in wall seconds on the wall clock via
    the shared curve (:func:`respawn_backoff_s`)."""

    def __init__(self, fleet, cfg: FiraConfig, *, wall_clock: bool = False):
        self.fleet = fleet
        self.max_respawns = int(cfg.max_respawns)
        self.backoff_base = float(cfg.respawn_backoff_s)
        self.wall_clock = bool(wall_clock)
        self.slots: Dict[str, ReplicaSlot] = {}
        # spares attached to a lineage keep their own (pre-compiled) tag;
        # this map folds their future deaths back onto the lineage budget
        self._lineage: Dict[str, str] = {}
        for eng in fleet.engines:
            o = origin_of(eng.tag)
            self.slots[o] = ReplicaSlot(origin=o, device=eng.device)

    def _slot_of(self, eng) -> ReplicaSlot:
        o = self._lineage.get(eng.tag or "r0", origin_of(eng.tag))
        if o not in self.slots:
            self.slots[o] = ReplicaSlot(origin=o, device=eng.device)
        return self.slots[o]

    def note_retirement(self, eng, round_: int, error: str = "") -> None:
        """Record one retirement against the engine's lineage (the
        respawn clock starts here)."""
        s = self._slot_of(eng)
        s.alive = False
        s.retired_round = int(round_)
        s.retired_wall = time.monotonic()  # firacheck: allow[WALL-CLOCK] the respawn backoff is wall-gated BY DESIGN on wall-clock serves (crash-looping hardware backs off in real seconds); virtual replays gate on rounds instead (due() round branch), so no wall time reaches the virtual schedule
        s.last_error = error

    def can_recover(self) -> bool:
        """True while any dead lineage still has respawn budget — the
        all-replicas-lost branch pauses admission on this instead of
        shedding the remainder."""
        return any(not s.alive and s.respawns < self.max_respawns
                   for s in self.slots.values())

    def due(self, round_: int) -> List[ReplicaSlot]:
        """Dead lineages whose backoff has elapsed and whose budget is
        not exhausted, origin order (deterministic). Round-gated always
        (``min(attempt, 5)`` rounds); on the wall clock ALSO gated by
        the shared curve in wall seconds — gated, never slept, so the
        surviving replicas keep being stepped through a lineage's
        backoff window."""
        out = []
        for o in sorted(self.slots):
            s = self.slots[o]
            if s.alive or s.respawns >= self.max_respawns:
                continue
            if self.wall_clock:
                # wall clock: the gate is wall seconds alone — rounds
                # are step dispatches and FREEZE during a total outage
                # (the serve pause branch), so a round gate could never
                # elapse there
                age = time.monotonic() - s.retired_wall  # firacheck: allow[WALL-CLOCK] wall-gate branch runs ONLY under self.wall_clock (the wall-serve mode); the virtual-clock path below gates on rounds, so replay determinism is untouched
                if (s.retired_wall >= 0
                        and age < respawn_backoff_s(s.respawns + 1,
                                                    self.backoff_base)):
                    continue
            else:
                wait = min(s.respawns + 1, _BACKOFF_CAP_ATTEMPTS)
                if round_ - s.retired_round < wait:
                    continue
            out.append(s)
        return out

    def respawn(self, slot: ReplicaSlot, round_: int):
        """One replacement attempt for ``slot``: spare attach when the
        pool has one, else a fresh build on the lineage's device. Every
        attempt — success, spare, or builder failure — consumes budget
        (a builder that keeps failing must exhaust, not spin). Returns
        (engine, from_spare) or (None, False) on failure."""
        slot.respawns += 1
        try:
            eng, from_spare = self.fleet.replace_slot(slot.origin,
                                                      slot.device)
        except Exception as e:
            slot.retired_round = int(round_)   # backoff restarts
            slot.retired_wall = time.monotonic()  # firacheck: allow[WALL-CLOCK] same wall-gated respawn backoff stamp as note_retirement (round-gated on virtual replays)
            slot.last_error = f"respawn failed: {type(e).__name__}: {e}"
            return None, False
        slot.alive = True
        if from_spare:
            self._lineage[eng.tag or "r0"] = slot.origin
        return eng, from_spare

    def heal_all(self) -> List:
        """Drain-mode healing (no scheduler rounds): respawn every dead
        lineage with budget left, immediately, wall-backed-off — the
        sleep is fine HERE because the drain driver is single-threaded
        batch work with no open-loop arrivals to starve. Returns the new
        engines (the fleet run loop appends them to its live list)."""
        new = []
        for o in sorted(self.slots):
            s = self.slots[o]
            while not s.alive and s.respawns < self.max_respawns:
                time.sleep(respawn_backoff_s(s.respawns + 1,  # firacheck: allow[SCHED-BLOCK] drain-mode heal: single-threaded batch work with no open-loop arrivals to starve (docstring above); the serve loop's _heal never sleeps — it gates in due()
                                             self.backoff_base))
                eng, _sp = self.respawn(s, s.retired_round)
                if eng is not None:
                    new.append(eng)
        return new
