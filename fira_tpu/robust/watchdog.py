"""Per-dispatch wall-clock watchdog (docs/FAULTS.md).

A hung device dispatch (driver wedge, injected ``hang`` fault, a remote
backend that stopped answering) cannot be interrupted from Python — but
it CAN be abandoned: run the dispatch in a worker thread, wait the
timeout, and on expiry raise :class:`WatchdogTimeout` to the caller
while the thread runs on into the void. The caller MUST then retire
whatever state the abandoned call mutates (the fleet/serve loops retire
the whole replica — its engine sets ``retired`` and every steppable
piece bails early if the abandoned thread ever wakes up; see
decode/engine.py), because the thread may still complete later.

``timeout_s <= 0`` is the off switch: the callable runs inline on the
caller's thread with zero overhead — the hot-path default. When ARMED,
every guarded dispatch pays one thread spawn+join (~100 µs on this
class of host) — an accepted cost for a robustness/debugging mode; a
deployment that arms the watchdog on a latency-critical path should
move to a persistent per-replica worker thread first.
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class WatchdogTimeout(RuntimeError):
    """A dispatch exceeded its wall-clock budget and was abandoned."""


def run_with_watchdog(fn: Callable[[], Any], timeout_s: float, *,
                      label: str = "",
                      cancel_event: "threading.Event" = None) -> Any:
    """Run ``fn()`` under a ``timeout_s`` wall-clock watchdog.

    ``timeout_s <= 0``: call inline (no thread, no overhead). Otherwise
    the call runs on a daemon worker thread; if it has not returned
    within the timeout, :class:`WatchdogTimeout` raises HERE and the
    thread is abandoned — the caller owns retiring the state it may
    still mutate. The callable's own exception (if it finishes in time)
    re-raises unchanged.

    ``cancel_event``: set on expiry BEFORE the timeout raises — a
    cooperative kill switch for callables that can poll it (the dev gate
    checks it per eval batch, train/loop.py) so an abandoned-but-alive
    call stops doing work instead of racing its replacement."""
    if timeout_s <= 0:
        return fn()
    box: dict = {}

    def body() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # re-raised on the caller's thread
            box["error"] = e

    t = threading.Thread(target=body, name="fira-dispatch-watchdog",
                         daemon=True)
    from fira_tpu.analysis.sanitizer import leak_guard

    lg = leak_guard()
    t.start()
    if lg is not None:
        lg.track_thread(t, what="dispatch-watchdog thread")
    t.join(timeout_s)
    if t.is_alive():
        if lg is not None:
            # sanctioned: a blown dispatch is ABANDONED by design — the
            # daemon thread bails via engine.retired the moment it wakes
            # (docs/FAULTS.md); the ledger records the reason instead of
            # calling it a leak at teardown
            lg.abandon_thread(t, "watchdog expiry — abandoned by design")
        if cancel_event is not None:
            cancel_event.set()
        raise WatchdogTimeout(
            f"dispatch{f' {label}' if label else ''} exceeded the "
            f"{timeout_s:.3f}s wall-clock watchdog and was abandoned")
    if lg is not None:
        lg.note_joined(t)
    if "error" in box:
        raise box["error"]
    return box.get("value")
