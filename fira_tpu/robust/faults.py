"""Seeded, site-addressed fault-injection registry (docs/FAULTS.md).

The serving stack's graceful-degradation contracts — poison-request
quarantine, replica retirement + requeue, dispatch watchdog — are only
real if a test can *trigger* the failure deterministically. This module
is the trigger: named injection points ("sites") along the request path,
armed by a parse-time-validated spec string

    site:kind:rate:seed[,site:kind:rate:seed...]

with kinds ``raise`` (the site throws :class:`InjectedFault`), ``hang``
(the site sleeps ``fault_hang_s`` wall seconds — the watchdog's prey),
and ``corrupt`` (the site's host payload is deterministically scrambled
in place, same shapes/dtypes — the ``CORRUPT_SITES`` that own a host
payload only). Whether a given event fires is a pure
function of ``(seed, site, event key)`` via a keyed blake2b digest — NO
process-global RNG, NO call-order dependence — so every chaos run
replays exactly, thread pools included (feeder sites key by task
sequence number, single-threaded scheduler sites by a per-site counter).

Off by default: with no spec armed the injector is ``None`` and every
site check is a single ``is not None`` branch — zero hot-path overhead.
Faults act on the HOST side only (raise before a dispatch, sleep,
scramble a numpy batch in place): no new jitted program ever exists, so
the zero-post-warmup-retrace contract holds with faults armed (pinned
under the compile guard by tests/test_robust.py).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from fira_tpu.config import FiraConfig

SITES = (
    "feeder.assemble",    # host batch assembly on a feeder worker
    "feeder.device_put",  # the worker-side H2D transfer
    "ingest.parse",       # raw-diff ingest on a feeder worker
    #                       (ingest/service.py): raise/hang fire before
    #                       the parse (the malformed-request class — the
    #                       quarantine sheds with the reason recorded);
    #                       corrupt scrambles the ASSEMBLED payload (a
    #                       garbage request the downstream must serve or
    #                       shed, never crash on)
    "ingest.cache",       # a whole-diff result-cache lookup
    #                       (ingest/cache.py): raise => absorbed as a
    #                       MISS (full re-ingest, bytes unchanged, never
    #                       a shed); corrupt => the read payload is
    #                       scrambled, the entry's content checksum
    #                       catches it, the entry is dropped and the
    #                       request re-ingests (never a wrong answer)
    "engine.prefill",     # the engine's prefill dispatch (admit)
    "engine.step",        # the engine's step dispatch
    "engine.harvest",     # the done-mask readback + sliced row gather
    "fleet.replica",      # one replica's whole service round
    "serve.admit",        # a request's admission into the serve queue
    "cache.lookup",       # a prefix-cache lookup (decode/prefix_cache.py):
    #                       raise => absorbed as a MISS (re-prefill, never a
    #                       wrong answer); corrupt => the read payload is
    #                       scrambled, the entry's content checksum catches
    #                       it, and the entry is dropped
    "disagg.transport",   # a prefill-tier artifact delivery at the
    #                       decode-side receive boundary
    #                       (serve/disagg.py): raise => the message is
    #                       treated as lost and its requests resubmit to
    #                       the pool; hang sleeps the receive; corrupt
    #                       scrambles the shipped payload — the per-row
    #                       content checksum catches it at seat and the
    #                       row re-prefills (never a wrong answer)
    "disagg.worker",      # one prefill-worker work item (child-side,
    #                       serve/disagg.py _worker_main): raise kills
    #                       the worker PROCESS (the uncaught exception
    #                       exits it) => the parent retires the worker
    #                       and requeues its in-flight work to
    #                       survivors; all-workers-lost => recorded
    #                       in-process prefill fallback; hang sleeps
    #                       inside the child (the lifecycle watchdog's
    #                       prey)
)
KINDS = ("raise", "hang", "corrupt")
# corrupt scrambles a HOST payload in place; only the sites that own a
# host payload qualify (every other site is a dispatch boundary with
# nothing host-mutable): batch assembly, raw-diff ingest assembly, the
# two content-cache read paths, and the disagg transport's shipped
# artifact rows (whose checksums must catch the scramble —
# docs/FAULTS.md)
CORRUPT_SITES = ("feeder.assemble", "ingest.parse", "ingest.cache",
                 "cache.lookup", "disagg.transport")


class InjectedFault(RuntimeError):
    """A fault fired by the injection registry — the exception the
    degradation machinery must absorb (quarantine or retirement), never
    a bug in itself."""

    def __init__(self, site: str, key) -> None:
        super().__init__(f"injected fault at {site} (event {key})")
        self.site = site
        self.key = key


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed site: fire ``kind`` with probability ``rate`` per event,
    deterministically under ``seed``."""

    site: str
    kind: str
    rate: float
    seed: int


def parse_fault_specs(spec: str) -> List[FaultSpec]:
    """Parse ``site:kind:rate:seed[,...]``; raises ValueError with a
    named-knob message on any malformed entry (the CLI turns it into
    exit 2 via :func:`robust_errors`)."""
    specs: List[FaultSpec] = []
    seen: set = set()
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        fields = entry.split(":")
        if len(fields) != 4:
            raise ValueError(
                f"inject_faults entry {entry!r} is not site:kind:rate:seed "
                f"(four ':'-separated fields); see docs/FAULTS.md")
        site, kind, rate_s, seed_s = fields
        if site not in SITES:
            raise ValueError(
                f"inject_faults site {site!r} is not a registered fault "
                f"site; choose from {', '.join(SITES)}")
        if kind not in KINDS:
            raise ValueError(
                f"inject_faults kind {kind!r} at site {site} is not one of "
                f"{', '.join(KINDS)}")
        if kind == "corrupt" and site not in CORRUPT_SITES:
            raise ValueError(
                f"inject_faults kind 'corrupt' is only meaningful at "
                f"{', '.join(CORRUPT_SITES)} (the site that owns a host "
                f"payload to scramble); {site} is a dispatch boundary")
        try:
            rate = float(rate_s)  # firacheck: allow[HOST-SYNC] rate_s is a parse-time CLI spec string field, not a device value
        except ValueError:
            raise ValueError(
                f"inject_faults rate {rate_s!r} at site {site} is not a "
                f"float")
        if not 0.0 <= rate <= 1.0:
            raise ValueError(
                f"inject_faults rate {rate} at site {site} must be in "
                f"[0, 1] (a per-event fire probability)")
        try:
            seed = int(seed_s)  # firacheck: allow[HOST-SYNC] seed_s is a parse-time CLI spec string field, not a device value
        except ValueError:
            raise ValueError(
                f"inject_faults seed {seed_s!r} at site {site} is not an "
                f"integer")
        if site in seen:
            raise ValueError(
                f"inject_faults arms site {site} twice; one spec per site "
                f"(the event-key stream is per site)")
        seen.add(site)
        specs.append(FaultSpec(site=site, kind=kind, rate=rate, seed=seed))
    return specs


def robust_errors(cfg: FiraConfig) -> List[str]:
    """Parse-time robustness-knob admission check (the chaos twin of
    parallel.mesh.divisibility_errors / serve.server.serve_errors): one
    named-knob message per violation, CLI exit 2. Checks the fault-spec
    grammar, the watchdog timeout (0 = off, else > 0), the quarantine
    retry count (>= 0), and the injected-hang duration (> 0)."""
    errs: List[str] = []
    if cfg.inject_faults:
        try:
            parse_fault_specs(cfg.inject_faults)
        except ValueError as e:
            errs.append(str(e))
    if cfg.dispatch_watchdog_s < 0:
        errs.append(
            f"dispatch_watchdog_s {cfg.dispatch_watchdog_s} must be 0 "
            f"(watchdog off) or > 0 wall seconds per dispatch")
    if cfg.robust_retries < 0:
        errs.append(
            f"robust_retries {cfg.robust_retries} must be >= 0 (retries "
            f"granted to a poisoned request before it is shed)")
    if cfg.fault_hang_s <= 0:
        errs.append(
            f"fault_hang_s {cfg.fault_hang_s} must be > 0 wall seconds "
            f"(the duration an injected 'hang' fault sleeps)")
    return errs


def backoff_s(attempt: int) -> float:
    """The quarantine retry backoff curve, shared by every retry site
    (feeder assembly, serve admission, serve prefill): linear in the
    attempt number, capped — long enough to outlive a transient blip,
    short enough that a virtual-clock replay stays fast. One definition
    so the quarantine policy cannot silently diverge between sites."""
    return min(0.01 * max(1, attempt), 0.05)


class FaultInjector:
    """The armed registry: one :class:`FaultSpec` per site, a keyed
    deterministic draw per event, and an observability counter of what
    actually fired (``summary()`` lands in stats artifacts)."""

    def __init__(self, specs: List[FaultSpec], *, hang_s: float = 2.0):
        self._by_site: Dict[str, FaultSpec] = {s.site: s for s in specs}
        self._counters: Dict[str, int] = {}
        self.hang_s = float(hang_s)
        self.fired: "collections.Counter" = collections.Counter()
        # per-site event keys that actually fired — feeder sites key by
        # task sequence, so for serve request streams (one single-row
        # task per split position) these ARE the affected positions; the
        # chaos smoke reads them to bound the corrupt blast radius
        self.fired_keys: Dict[str, List] = collections.defaultdict(list)
        # fired accounting is mutated from concurrent feeder workers —
        # Counter += is a non-atomic read-modify-write
        self._lock = threading.Lock()
        # lock-discipline sanitizer (--sanitize / tests): exactly the
        # unlocked-increment bug the PR 9 review caught here — armed, a
        # `fired[site] += 1` outside `with self._lock` raises at the line
        from fira_tpu.analysis.sanitizer import guard_structures

        self._lock, (self.fired,) = guard_structures(
            self, self._lock, [(self.fired, "fired")])

    def _record_fire(self, site: str, key) -> None:
        with self._lock:
            self.fired[site] += 1
            self.fired_keys[site].append(key)

    def armed(self, site: str) -> bool:
        return site in self._by_site

    @staticmethod
    def _draw(spec: FaultSpec, key) -> bool:
        """One uniform in [0, 1) per (seed, site, key), via a keyed
        blake2b digest: deterministic across processes and thread
        schedules (tuple ``hash()`` is salted per process — never use
        it for replayable chaos)."""
        msg = f"{spec.seed}:{spec.site}:{key}".encode()
        u = int.from_bytes(hashlib.blake2b(msg, digest_size=8).digest(),
                           "big") / 2.0 ** 64
        return u < spec.rate

    def check(self, site: str, key=None) -> None:
        """Fire the site's raise/hang fault for this event if the draw
        says so. ``key`` identifies the event deterministically (feeder
        sites pass the task sequence number so thread scheduling cannot
        reorder draws); ``None`` uses a per-site monotone counter —
        correct for the single-threaded scheduler sites. Every call is a
        FRESH draw, so a retried event may succeed (rate < 1)."""
        spec = self._by_site.get(site)
        if spec is None or spec.kind == "corrupt":
            return
        if key is None:
            key = self._counters[site] = self._counters.get(site, 0) + 1
        if not self._draw(spec, key):
            return
        self._record_fire(site, key)
        if spec.kind == "hang":
            # a bounded stall, not an exception: the watchdog (or the
            # caller's patience) decides whether this retires anything
            time.sleep(self.hang_s)
            return
        raise InjectedFault(site, key)

    def corrupt(self, site: str, key, batch: Dict) -> Dict:
        """Deterministically scramble ONE host batch: the integer content
        fields roll one position, same shapes and dtypes — a different
        (garbage) sample the downstream must degrade on, never crash on,
        and whose blast radius is exactly its own output row (per-row
        beam independence)."""
        spec = self._by_site.get(site)
        if spec is None or spec.kind != "corrupt" \
                or not self._draw(spec, key):
            return batch
        self._record_fire(site, key)
        out = dict(batch)
        for f in ("diff", "sub_token"):
            if f in out:
                out[f] = np.roll(out[f], 1, axis=-1)
        return out

    def summary(self) -> Dict[str, int]:
        """Fired-event counts per site (the machine record chaos rows and
        serve_metrics.json carry)."""
        with self._lock:
            return {site: int(n) for site, n in sorted(self.fired.items())}


def injector_from(cfg: FiraConfig) -> Optional[FaultInjector]:
    """The armed injector for ``cfg.inject_faults``, or None when no spec
    is armed (the zero-overhead default every driver branches on)."""
    if not cfg.inject_faults:
        return None
    return FaultInjector(parse_fault_specs(cfg.inject_faults),
                         hang_s=cfg.fault_hang_s)
