"""Fault injection + graceful degradation for the serving stack
(docs/FAULTS.md).

Every runtime layer built before this package was fail-stop: a feeder
worker exception poisoned the whole feed, a fleet replica whose dispatch
raised or hung took down the entire run, and one malformed request killed
the serve loop. This package holds the machinery that turns those into
*degradation* instead of collapse, and the seeded fault-injection
registry that proves it deterministically in tier-1:

- :mod:`fira_tpu.robust.faults` — named injection sites armed by a
  parse-time-validated spec (``site:kind:rate:seed``), deterministic
  given the seed, off by default with zero hot-path overhead;
- :mod:`fira_tpu.robust.watchdog` — a per-dispatch wall-clock watchdog
  (run the dispatch in a worker thread, abandon it on expiry) backing
  replica retirement in the fleet/serve loops and the dev-gate skip in
  train/loop.py;
- :mod:`fira_tpu.robust.recovery` — the self-healing half (docs/FAULTS
  .md "Recovery contracts"): replica respawn with warm spares and
  per-lineage budget/backoff, plus the write-ahead request journal and
  crash-resume machinery behind ``cli serve --resume``.
"""

from fira_tpu.robust.faults import (FaultSpec, FaultInjector,  # noqa: F401
                                    InjectedFault, injector_from,
                                    parse_fault_specs, robust_errors)
from fira_tpu.robust.recovery import (Journal, RecoveryManager,  # noqa: F401
                                      read_journal, recover_output,
                                      recovery_errors, respawn_backoff_s,
                                      resume_errors)
from fira_tpu.robust.watchdog import (WatchdogTimeout,  # noqa: F401
                                      run_with_watchdog)
