"""Ring attention: sequence-parallel exact attention over a mesh axis.

The reference has no long-context machinery at all — every sequence length is
a small compile-time constant (/root/reference/run_model.py:31-35) and
attention spans at most 370 keys. This module is the capability the TPU
framework adds on top of parity: exact attention whose keys/values are
sharded across devices on a ``seq`` mesh axis, with K/V blocks rotating
around the ICI ring (``jax.lax.ppermute``) while each device keeps a running
flash-style online softmax. Peak memory per device is O(T_local^2) instead of
O(T^2), and the rotation overlaps with compute, so sequences can scale with
the mesh.

Numerics contract: identical (up to fp error) to the repo's dense attention
— additive ``-1e9`` masking where mask==0 (model/layers.py Attention), NOT
-inf, so fully-masked queries produce the same uniform-ish softmax as the
dense path instead of NaN.

Usage: the ``ring_*`` functions are per-shard bodies meant to run inside
``shard_map`` over a mesh with a ``seq`` axis (see ``seq_mesh`` /
``ring_attention_sharded``).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level (check_vma keyword)
    from jax import shard_map as _shard_map

    def _sharded(body, mesh, in_specs, out_specs):
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
except ImportError:  # jax 0.4.x: experimental module, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    def _sharded(body, mesh, in_specs, out_specs):
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

SEQ_AXIS = "seq"
NEG_INF = -1e9


def seq_mesh(n_data: int, n_seq: int,
             devices: Optional[Sequence] = None) -> Mesh:
    """A (data, seq) mesh for sequence-parallel attention."""
    from fira_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data=n_data, n_model=n_seq, devices=devices,
                     axis_names=("data", SEQ_AXIS))


def _block(q, k, v, kv_mask, bias):
    """One attention block's (unnormalized) contribution with running max.

    Returns (m, l, o): rowwise max of the masked scores, sum of exp, and the
    exp-weighted value accumulation, all float32.
    """
    d_head = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    s = s / np.sqrt(d_head)
    s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)                                   # (B,H,Tq)
    p = jnp.exp(s - m[..., None])                             # (B,H,Tq,Tk)
    l = jnp.sum(p, axis=-1)                                   # (B,H,Tq)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, o


def ring_attention(q, k, v, kv_mask, *, axis_name: str = SEQ_AXIS,
                   causal: bool = False):
    """Exact attention with K/V sharded over ``axis_name`` (per-shard body).

    q:       (B, H, Tq_local, Dh)  — queries of this shard
    k, v:    (B, H, Tk_local, Dh)  — this shard's K/V block (rotates)
    kv_mask: (B, Tk_local) bool    — key-padding mask (rotates with K/V)
    causal:  mask out keys with global position > the query's global
             position (both sequences assumed sharded contiguously:
             global position = shard_index * local_len + local offset).

    Returns (B, H, Tq_local, Dh) in q.dtype.
    """
    n_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, H, Tq, Dh = q.shape
    Tk = k.shape[2]

    q_pos = my_idx * Tq + jnp.arange(Tq)                      # global q rows

    def causal_bias(src_idx):
        k_pos = src_idx * Tk + jnp.arange(Tk)
        allowed = k_pos[None, :] <= q_pos[:, None]            # (Tq, Tk)
        return jnp.where(allowed, 0.0, NEG_INF)[None, None, :, :]

    def merge(carry, k_i, v_i, mask_i, src_idx):
        m_run, l_run, o_run = carry
        bias = causal_bias(src_idx) if causal else None
        m_blk, l_blk, o_blk = _block(q, k_i, v_i, mask_i, bias)
        m_new = jnp.maximum(m_run, m_blk)
        alpha = jnp.exp(m_run - m_new)                        # rescale old
        beta = jnp.exp(m_blk - m_new)                         # rescale new
        l_new = l_run * alpha + l_blk * beta
        o_new = o_run * alpha[..., None] + o_blk * beta[..., None]
        return m_new, l_new, o_new

    def step(i, carry):
        acc, k_i, v_i, mask_i = carry
        # rotate FIRST (the local block was consumed before the loop), so
        # the final iteration's rotation isn't dead work: n_shards-1
        # permutes total, like standard ring-attention schedules
        perm = [(j, (j - 1) % n_shards) for j in range(n_shards)]
        k_i = jax.lax.ppermute(k_i, axis_name, perm)
        v_i = jax.lax.ppermute(v_i, axis_name, perm)
        mask_i = jax.lax.ppermute(mask_i, axis_name, perm)
        acc = merge(acc, k_i, v_i, mask_i, (my_idx + i) % n_shards)
        return acc, k_i, v_i, mask_i

    # Initial running max NEG_INF (matches dense masking floor); one block is
    # always processed, so l > 0 even fully masked, exactly like the dense
    # softmax over all -1e9 rows.
    m0 = jnp.full((B, H, Tq), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, Tq), dtype=jnp.float32)
    o0 = jnp.zeros((B, H, Tq, Dh), dtype=jnp.float32)

    acc = merge((m0, l0, o0), k, v, kv_mask, my_idx)  # local block, no comm
    (m_f, l_f, o_f), *_ = jax.lax.fori_loop(1, n_shards, step,
                                            (acc, k, v, kv_mask))
    out = o_f / l_f[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, kv_mask, mesh: Mesh, *,
                           causal: bool = False,
                           batch_axis: str = "data",
                           seq_axis: str = SEQ_AXIS):
    """shard_map wrapper: q/k/v (B, H, T, Dh) sharded on batch + sequence
    axes; returns the attention output with the same sharding as q."""
    qkv_spec = P(batch_axis, None, seq_axis, None)
    mask_spec = P(batch_axis, seq_axis)
    body = functools.partial(ring_attention, causal=causal,
                             axis_name=seq_axis)
    fn = _sharded(
        body, mesh,
        (qkv_spec, qkv_spec, qkv_spec, mask_spec),
        qkv_spec,
    )
    return fn(q, k, v, kv_mask)


def dense_reference_attention(q, k, v, kv_mask, *, causal: bool = False):
    """Single-device oracle with the exact masking semantics ring_attention
    must reproduce (used by tests and docs)."""
    d_head = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / np.sqrt(d_head)
    s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        allowed = jnp.arange(Tk)[None, :] <= jnp.arange(Tq)[:, None]
        s = s + jnp.where(allowed, 0.0, NEG_INF)[None, None, :, :]
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)
