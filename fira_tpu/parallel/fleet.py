"""Replicated slot-engine decode fleet: N engines, one admission queue.

The slot-refill engine (decode/engine.py) made decode wall clock scale
with tokens emitted — on ONE chip. This module is the multi-chip half of
that story (ROADMAP item 3; Orca's iteration-level scheduling generalized
to a serving fleet, PAPERS.md "Continuous batching / inference serving"):
N :class:`~fira_tpu.decode.engine.SlotEngine` replicas — one per
data-mesh slice, each with its own per-chip KV arena, params copy, and
compiled program set — pull packed chunks from ONE shared admission queue
(the async feeder stream every decode driver already uses) and
harvest/refill interleave across replicas.

Scheduling is the single engine's own steppable scheduler, round-robined:

- **admission**: replicas claim chunks from the shared queue in replica
  order whenever their prefill-ahead policy wants input (same
  ``engine_prefill_depth`` staging per replica). The feeder runs
  ``put=False`` — which replica a chunk lands on is a scheduling
  decision, so the H2D transfer happens at admission, onto the claiming
  replica's own device.
- **step interleave**: every live replica's step program is dispatched
  BEFORE any replica's harvest readback, so replica compute overlaps
  across chips while the host walks the fleet.
- **harvest/refill**: each replica harvests its settled slots (yielding
  :class:`~fira_tpu.decode.engine.EngineItem` exactly like the single
  engine) and refills from its staged chunks on the next round.

Output invariance (pinned by tests/test_fleet.py): per-sample results are
bit-exact regardless of which replica/slot computes them — same params,
same prefill batches (a chunk is always prefilled WHOLE, wherever it
lands), same per-slot step math — so the decoded file bytes are identical
to the single-engine path for ANY replica count and refill interleaving.

Guard labels: each replica suffixes its labels with ``r<i>``
(``engine_step[r1]``, ``engine_prefill[a16.e256.t12.r1]``) because each
replica compiles its own program set (per-device executables); the
declared family is the union over replicas (:meth:`EngineFleet.labels`)
and still closes at one compile per label.

Cross-request reuse (``cfg.prefix_cache`` — decode/prefix_cache.py):
each replica owns a PER-CHIP prefix cache and in-flight dedup map,
exactly like its per-chip KV arena (cached artifacts re-enter via
``device_put`` onto the owning replica's device, so no cross-chip
traffic exists to coordinate). Dedup therefore coalesces within a
replica in drain mode (the serve loop's admission-time dedup,
serve/server.py, is the fleet-GLOBAL layer); output bytes stay invariant
either way because a coalesced delivery is byte-identical to a fresh
decode of the same payload. Retirement RELEASES a dead replica's shared
block grants through the refcounted allocator and folds its coalesced
followers into the re-admission payloads — requeued requests survive
dedup (re-coalescing or seating fresh on a survivor, both bit-exact)
instead of being lost or decoded twice.

Graceful degradation (docs/FAULTS.md): a replica whose dispatch raises —
or exceeds ``cfg.dispatch_watchdog_s`` wall seconds and is abandoned on
its watchdog thread — is RETIRED: removed from the service rotation, its
in-flight and staged requests requeued onto the surviving replicas (the
dead replica excluded by construction), and the drain continues
degraded. Requeued requests re-prefill inside the same declared program
family and, by per-row beam independence, produce bit-identical results
wherever they land — so the decoded file bytes of a run that lost a
replica equal the no-fault run's exactly (pinned by tests/test_robust
.py). Retirements and requeues are machine-recorded in FleetStats.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence

import jax
import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.decode.engine import EngineItem, EngineStats, SlotEngine
from fira_tpu.model.model import FiraModel
from fira_tpu.robust import recovery as recovery_lib
from fira_tpu.robust.watchdog import run_with_watchdog


def fleet_divisibility_errors(cfg: FiraConfig) -> List[str]:
    """Parse-time fleet admission check (the decode twin of
    parallel.mesh.divisibility_errors): a nonzero ``engine_slots`` is the
    fleet-TOTAL arena, split evenly across replicas — reject a non-divisor
    up front instead of failing in the arena allocation mid-run. The
    paged-KV pool splits the same way (``kv_pool_blocks`` is the fleet
    total), but its split and floors are owned by
    decode/paging.paging_errors, which the CLI runs right after this
    check — one message per violation, not two."""
    reps = max(1, int(cfg.engine_replicas))
    if reps > 1 and cfg.engine_slots and cfg.engine_slots % reps:
        return [f"engine_slots {cfg.engine_slots} is not divisible by "
                f"engine_replicas {reps} (the fleet splits the total slot "
                f"arena evenly across replicas)"]
    return []


@dataclasses.dataclass
class FleetStats:
    """Aggregate + per-replica accounting for one fleet run."""

    replicas: List[EngineStats]
    # degradation accounting (docs/FAULTS.md): one entry per retired
    # replica ({"replica": tag, "error": str}) and the total requests
    # requeued onto survivors across all retirements
    retirements: List[Dict] = dataclasses.field(default_factory=list)
    requeues: int = 0
    # recovery accounting (robust/recovery.py): one entry per respawned
    # replacement ({"replica": new tag, "origin": lineage, "spare": bool})
    respawns: List[Dict] = dataclasses.field(default_factory=list)

    @property
    def commits(self) -> int:
        return sum(r.commits for r in self.replicas)

    def summary(self) -> Dict:
        tot = lambda f: sum(getattr(r, f) for r in self.replicas)  # noqa: E731
        steps_x_slots = sum(r.steps * r.slots for r in self.replicas)
        # fleet-wide paged-KV pool accounting: pools are per-chip, so
        # blocks total across replicas and utilization weights each
        # replica's pool by its own dispatch count
        pool_capacity = sum(r.step_dispatches * r.pool_blocks
                            for r in self.replicas)
        if pool_capacity:
            pool_util = round(tot("block_steps") / pool_capacity, 4)
        else:
            pool_util = (1.0 if any(r.kv_bytes_per_slot
                                    for r in self.replicas) else 0.0)
        return {
            "pool_blocks": tot("pool_blocks"),
            "kv_block_size": max((r.kv_block_size for r in self.replicas),
                                 default=0),
            "kv_bytes_per_slot": max((r.kv_bytes_per_slot
                                      for r in self.replicas), default=0),
            # low-precision serving tiers (decode/quant.py): cfg-uniform
            # across the fleet, so any replica's stamp is THE answer —
            # "f32" when no replica has dispatched yet
            "kv_dtype": next((r.kv_dtype for r in self.replicas
                              if r.step_dispatches), "f32"),
            "serve_precision": next((r.serve_precision
                                     for r in self.replicas
                                     if r.step_dispatches), "f32"),
            "peak_blocks": tot("peak_blocks"),
            "pool_utilization": pool_util,
            "replicas": len(self.replicas),
            "slots": tot("slots"),
            "prefills": tot("prefills"),
            "refills": tot("refills"),
            "slots_refilled": tot("slots_refilled"),
            "steps_run": tot("steps"),
            "step_dispatches": tot("step_dispatches"),
            "commits": self.commits,
            "dispatches": sum(r.dispatches for r in self.replicas),
            # sliced-harvest readback accounting (decode/engine.py):
            # per-replica D2H bytes total across the fleet
            "harvest_row_reads": tot("harvest_row_reads"),
            "harvest_bytes_read": tot("harvest_bytes_read"),
            "harvest_bytes_saved": tot("harvest_bytes_saved"),
            # cross-request reuse accounting (decode/prefix_cache.py):
            # caches are per-chip, so counts total across replicas and
            # the hit rate is the fleet-wide served-from-cache fraction
            "cache_hits": tot("cache_hits"),
            "cache_misses": tot("cache_misses"),
            "cache_hit_rate": round(
                tot("cache_hits") / (tot("cache_hits")
                                     + tot("cache_misses")), 4)
            if tot("cache_hits") + tot("cache_misses") else 0.0,
            "cache_evictions": tot("cache_evictions"),
            "cache_integrity_drops": tot("cache_integrity_drops"),
            "prefills_saved": tot("prefills_saved"),
            "cache_hbm_bytes_saved": tot("cache_hbm_bytes_saved"),
            "dedup_fanout": tot("dedup_fanout"),
            "shared_block_peak": tot("shared_block_peak"),
            # speculative draft-and-verify accounting (decode/spec.py):
            # drafters are per-replica, so counts total across the fleet
            # and the acceptance rate is the fleet-wide accepted fraction;
            # per-replica rates ride alongside like occupancy does
            "drafted": tot("drafted"),
            "accepted": tot("accepted"),
            "acceptance_rate": round(tot("accepted") / tot("drafted"), 4)
            if tot("drafted") else 0.0,
            "verify_dispatches": tot("verify_dispatches"),
            "steps_saved": tot("steps_saved"),
            "spec_frames": tot("spec_frames"),
            "per_replica_acceptance": [
                round(r.acceptance_rate, 4) for r in self.replicas],
            # fleet-wide mean fraction of slots doing real beam work
            "slot_occupancy": round(
                tot("occupied_slot_steps") / steps_x_slots, 4
            ) if steps_x_slots else 0.0,
            "per_replica_occupancy": [
                round(r.slot_occupancy, 4) for r in self.replicas],
            "per_replica_commits": [r.commits for r in self.replicas],
            # graceful-degradation record: which replicas were retired
            # (dispatch raised / watchdog expired) and how many requests
            # were requeued onto survivors
            "retirements": len(self.retirements),
            "retired_replicas": [r["replica"] for r in self.retirements],
            "requeues": self.requeues,
            # self-healing record (robust/recovery.py): replacements that
            # joined the fleet mid-run, and whether each was a warm-spare
            # attach or a fresh mid-run build
            "respawns": len(self.respawns),
            "respawned_replicas": [r["replica"] for r in self.respawns],
            "spare_attaches": sum(1 for r in self.respawns if r["spare"]),
        }


class EngineFleet:
    """N-replica slot-engine decode over one shared admission queue.

    ``replicas``: engine count. ``slots``: fleet-TOTAL arena (must divide
    by ``replicas``); 0/None falls back to each replica's own default
    (``cfg.engine_slots`` total when nonzero, else ``cfg.test_batch_size``
    slots PER replica). ``devices``: one device per replica; defaults to
    ``jax.devices()`` round-robin, so on an N-device mesh each replica
    owns its own chip and on a single chip the replicas share it (still
    output-identical — the tests pin exactly that).
    """

    def __init__(self, model: FiraModel, params, cfg: FiraConfig, *,
                 replicas: int, slots: Optional[int] = None, guard=None,
                 devices: Optional[Sequence] = None, faults=None):
        if replicas < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {replicas}")
        total = int(slots or cfg.engine_slots or 0)
        if total and total % replicas:
            raise ValueError(
                f"engine_slots {total} is not divisible by engine_replicas "
                f"{replicas} (the fleet splits the total slot arena evenly "
                f"across replicas)")
        per_replica = total // replicas if total else None
        # kv_pool_blocks is the fleet TOTAL like engine_slots: each
        # replica owns a per-chip pool of total/replicas blocks (0 keeps
        # each engine's own full-residency auto size)
        pool_total = int(cfg.kv_pool_blocks)
        if pool_total and pool_total % replicas:
            raise ValueError(
                f"kv_pool_blocks {pool_total} is not divisible by "
                f"engine_replicas {replicas} (the fleet splits the total "
                f"KV block pool evenly across replicas)")
        per_replica_pool = pool_total // replicas if pool_total else None
        if devices is None:
            devs = jax.devices()
            devices = [devs[i % len(devs)] for i in range(replicas)]
        elif len(devices) < replicas:
            raise ValueError(f"{len(devices)} devices for {replicas} "
                             f"replicas")
        self.cfg = cfg
        self.faults = faults
        # degradation record (docs/FAULTS.md) — ``engines`` stays the
        # FULL roster (stats/labels must keep counting a retired
        # replica's commits); the run loop keeps its own live list
        self.retirements: List[Dict] = []
        self.requeues: int = 0
        # recovery machinery (robust/recovery.py): replace_slot needs the
        # build inputs a respawn re-runs (the ORIGINAL params — each
        # replacement re-device_puts its own copy), the stored warm
        # batches, the per-lineage respawn ordinals, and the warm-spare
        # pool (built on demand by build_spares)
        self._model = model
        self._params = params
        self._guard = guard
        self._per_replica = per_replica
        self._per_replica_pool = per_replica_pool
        self._devices = list(devices)
        self._warm: Optional[List] = None
        self._respawn_counts: Dict[str, int] = {}
        self._spare_seq = 0
        self.respawns: List[Dict] = []
        self.spares: List[SlotEngine] = []
        self.engines = [
            SlotEngine(model, jax.device_put(params, devices[i]), cfg,
                       slots=per_replica, guard=guard, device=devices[i],
                       tag=f"r{i}", pool_blocks=per_replica_pool,
                       faults=faults)
            for i in range(replicas)
        ]

    @property
    def stats(self) -> FleetStats:
        return FleetStats([e.stats for e in self.engines],
                          retirements=list(self.retirements),
                          requeues=self.requeues,
                          respawns=list(self.respawns))

    def labels(self, table=None) -> List[str]:
        """The fleet's declared program family: the union of every
        replica's (geometry x {prefill, step, insert}) labels."""
        return [lbl for e in self.engines for lbl in e.labels(table)]

    def cache_put(self, digest, payload) -> None:
        """Fan one externally-prefilled artifact payload out to EVERY
        live replica's prefix cache (the disaggregated prefill tier's
        delivery seam — serve/disagg.py): whichever replica's rotation
        claims the request, its admission takes the all-hit cache path.
        The payload is host numpy shared by reference — the caches store
        it read-only and ``build_chunk`` re-packs copies at seat."""
        for eng in self.engines:
            eng.cache_put(digest, payload)

    def prewarm(self, warm_batches) -> None:
        """Compile every replica's prefill family up front (each replica
        owns its own executables — per-device compiles are real compiles,
        and the guard budget prices them per replica label). The batches
        are KEPT: a respawned replacement prewarms through the same
        declared family (replace_slot), so post-warmup dispatches on it
        never pay a first-use compile either."""
        batches = list(warm_batches)
        self._warm = batches
        for eng in self.engines:
            eng.prewarm(batches)

    # --- self-healing (robust/recovery.py; docs/FAULTS.md) ---------------

    def _build_replacement(self, device, tag: str) -> SlotEngine:
        """One fresh engine on ``device``: params re-``device_put``, the
        per-replica paged pool re-allocated, labels declared under the
        new tag, and the stored warm batches prewarmed — the replacement
        pays its compiles HERE (each new label's warmup dispatch), never
        on a post-warmup serving dispatch."""
        params = (jax.device_put(self._params, device)
                  if device is not None else self._params)
        eng = SlotEngine(self._model, params, self.cfg,
                         slots=self._per_replica, guard=self._guard,
                         device=device, tag=tag,
                         pool_blocks=self._per_replica_pool,
                         faults=self.faults)
        if self._guard is not None and self._guard.family_closed:
            # additive declare into the ALREADY-closed family only: on an
            # open family (the unbucketed drivers never declare) a first
            # declare here would close it around just the replacement's
            # labels and outlaw every serving replica's programs
            tags = [t for (_h, t) in (self._warm or [])] or [None]
            self._guard.declare(eng.labels_for_tags(tags))
        if self._warm:
            eng.prewarm(self._warm)
        return eng

    def build_spares(self, count: int) -> None:
        """Build the warm-spare pool: ``count`` prewarmed standby engines
        (tags ``sp<i>``, devices round-robin like the fleet), idle until
        a retirement attaches one. Refills up to ``count`` — a reused
        warm fleet must not double its pool — and tags from a monotone
        sequence, never reusing an attached spare's tag (labels and
        heartbeat/lineage records key on it)."""
        while len(self.spares) < int(count):  # firacheck: allow[HOST-SYNC] count is the engine_spares config int; no device value exists here
            i = self._spare_seq
            self._spare_seq += 1
            self.spares.append(self._build_replacement(
                self._devices[i % len(self._devices)], f"sp{i}"))

    def take_spare(self, device) -> Optional[SlotEngine]:
        """Pop a spare, preferring one already on ``device`` (zero
        cross-device params movement); any spare otherwise — restored
        capacity beats placement."""
        for i, sp in enumerate(self.spares):
            if sp.device is device:
                return self.spares.pop(i)
        return self.spares.pop(0) if self.spares else None

    def replace_slot(self, origin: str, device):
        """Replace one retired lineage: a warm spare when the pool has
        one (O(attach)), else a fresh build on the lineage's device
        (O(compile)). The replacement joins the ROSTER here (its commits
        count in FleetStats); the caller owns adding it to the live
        service rotation. Returns (engine, from_spare)."""
        spare = self.take_spare(device)
        if spare is not None:
            self.engines.append(spare)
            self.respawns.append({"replica": spare.tag or "r0",
                                  "origin": origin, "spare": True})
            return spare, True
        k = self._respawn_counts.get(origin, 0) + 1
        self._respawn_counts[origin] = k
        tag = f"{origin}{recovery_lib.RESPAWN_TAG_SEP}{k}"
        eng = self._build_replacement(device, tag)
        self.engines.append(eng)
        self.respawns.append({"replica": tag, "origin": origin,
                              "spare": False})
        return eng, False

    @staticmethod
    def _as_payload(item) -> Dict:
        """Normalize a feeder item into a requeue-able admission payload:
        positions pinned in ``_positions`` (the unbucketed stream derives
        them from the item index, exactly like SlotEngine.admit would),
        so the SAME host batch can be admitted on ANY replica, including
        after the first attempt's replica died mid-prefill."""
        host = dict(item.host)
        if host.get("_positions") is None:
            C = host["valid"].shape[0]
            host["_positions"] = (item.index * C
                                  + np.arange(C, dtype=np.int64))
        return host

    def _retire(self, eng: SlotEngine, alive: List[SlotEngine],
                pending: "collections.deque", err: BaseException,
                recovery=None) -> None:
        """Retire one replica: drop it from the service rotation, requeue
        every request it still owed at the FRONT of the shared admission
        stream (they arrived earliest), and record the event. With
        ``recovery`` armed (cfg.max_respawns — robust/recovery.py) dead
        lineages with budget left are respawned HERE, immediately and
        wall-backed-off (drain mode has no scheduler rounds to gate on),
        and the replacements join the live rotation. With no survivors
        and no respawn budget there is nothing to degrade onto — a drain
        run must fail loudly, never hang."""
        alive.remove(eng)
        payloads = eng.retire()
        # TOCTOU guard: an admit the watchdog abandoned can finish
        # STAGING in the window between the timeout raising here and
        # retire() flipping the retired flag — its chunk would then come
        # back in `payloads` while ALSO still sitting at pending[0]
        # (never popleft'd, because the admit call raised). Requeuing
        # both copies would decode the same positions twice and blow the
        # ordered writer's duplicate check, so rows already owed by a
        # queued payload are masked out here (the serve loop dedups the
        # same way via its `seen` set).
        pending_pos = set()
        for b in pending:
            v = np.asarray(b["valid"], dtype=bool)  # firacheck: allow[HOST-SYNC] requeue payloads are host numpy batches (SlotEngine.retire / _as_payload); no device value exists in this dedup
            pending_pos.update(int(p) for p in  # firacheck: allow[HOST-SYNC] requeue payloads are host numpy batches (SlotEngine.retire / _as_payload); no device value exists in this dedup
                               np.asarray(b["_positions"])[v])  # firacheck: allow[HOST-SYNC] requeue payloads are host numpy batches (SlotEngine.retire / _as_payload); no device value exists in this dedup
        n_req = 0
        kept = []
        for p in payloads:
            v = np.asarray(p["valid"], dtype=bool).copy()  # firacheck: allow[HOST-SYNC] requeue payloads are host numpy batches (SlotEngine.retire / _as_payload); no device value exists in this dedup
            pos = np.asarray(p["_positions"])  # firacheck: allow[HOST-SYNC] requeue payloads are host numpy batches (SlotEngine.retire / _as_payload); no device value exists in this dedup
            for r in range(v.shape[0]):
                if v[r] and int(pos[r]) in pending_pos:  # firacheck: allow[HOST-SYNC] requeue payloads are host numpy batches (SlotEngine.retire / _as_payload); no device value exists in this dedup
                    v[r] = False
            if v.any():
                p["valid"] = v.astype(np.asarray(p["valid"]).dtype)  # firacheck: allow[HOST-SYNC] requeue payloads are host numpy batches (SlotEngine.retire / _as_payload); no device value exists in this dedup
                kept.append(p)
                n_req += int(v.sum())
        for p in reversed(kept):
            pending.appendleft(p)
        self.requeues += n_req
        self.retirements.append({"replica": eng.tag or "r0",
                                 "error": f"{type(err).__name__}: {err}"})
        if recovery is not None:
            recovery.note_retirement(eng, -1,
                                     error=f"{type(err).__name__}: {err}")
            for new in recovery.heal_all():
                new.begin_stream()
                alive.append(new)
        if not alive:
            raise RuntimeError(
                f"all {len(self.engines)} fleet replicas retired; last "
                f"error on {eng.tag or 'r0'}: {err}") from err

    def run(self, feed, *, refill_order: str = "fifo"
            ) -> Iterator[EngineItem]:
        """Drive the fleet over ``feed`` (data.feeder.FedBatch items from
        a ``put=False`` feeder — the shared admission queue). Yields one
        EngineItem per real sample as it settles, across all replicas;
        results are keyed by split position, so the ordered writer
        downstream is replica-agnostic.

        Degradation: each replica's service round runs under
        ``cfg.dispatch_watchdog_s`` (0 = off) and a try/except — a raise
        or watchdog expiry retires the replica and requeues its requests
        (:meth:`_retire`); requeued payloads are admitted BEFORE fresh
        feed items, onto whichever surviving replica wants input next."""
        if refill_order not in ("fifo", "lifo"):
            raise ValueError(f"refill_order {refill_order!r} not in "
                             f"{{'fifo', 'lifo'}}")
        for eng in self.engines:
            eng.begin_stream()
        feed_iter = iter(feed)
        exhausted = False
        wd = float(self.cfg.dispatch_watchdog_s)
        # self-healing (robust/recovery.py): with a respawn budget armed,
        # a retirement is followed by an immediate wall-backed-off
        # replacement instead of staying a permanent capacity loss
        recovery = (recovery_lib.RecoveryManager(self, self.cfg,
                                                 wall_clock=True)
                    if self.cfg.max_respawns > 0 else None)
        if recovery is not None and self.cfg.engine_spares:
            # the drain path arms its own spare pool (the serve driver
            # builds it in serve_split) — a knob that validates must act
            self.build_spares(self.cfg.engine_spares)
        # re-admission payloads from retired replicas, served head-first
        pending: "collections.deque" = collections.deque()
        alive = [eng for eng in self.engines if not eng.retired]
        while True:
            # admission + refill, replica order (deterministic: which
            # replica gets a chunk never changes the chunk's results)
            for eng in list(alive):
                try:
                    if self.faults is not None:
                        self.faults.check("fleet.replica")
                    while eng.wants_input():
                        if not pending:
                            if exhausted:
                                break
                            try:
                                item = next(feed_iter)
                            except StopIteration:
                                exhausted = True
                                break
                            # normalize EVERY item to a requeue-able
                            # payload first (positions pinned): if this
                            # replica dies mid-prefill, the chunk being
                            # admitted survives at the head of pending —
                            # fleet feeds run put=False, so re-shipping
                            # at admission was the contract already
                            pending.append(self._as_payload(item))
                        payload = pending[0]   # PEEK: a failed admit
                        #                        leaves it queued for the
                        #                        next surviving replica
                        run_with_watchdog(
                            lambda p=payload: eng.admit(p, 0), wd,
                            label=f"prefill[{eng.tag}]")
                        pending.popleft()
                    run_with_watchdog(lambda: eng.refill(refill_order), wd,
                                      label=f"refill[{eng.tag}]")
                except Exception as e:
                    self._retire(eng, alive, pending, e, recovery)
            live = [eng for eng in alive if eng.in_flight()]
            if not live:
                if exhausted and not pending:
                    return
                continue  # nothing in flight yet: pull more input
            # dispatch EVERY live replica's step before any harvest
            # readback: replica compute overlaps across chips while the
            # host walks the fleet
            for eng in live:
                try:
                    run_with_watchdog(eng.step_dispatch, wd,
                                      label=f"step[{eng.tag}]")
                except Exception as e:
                    self._retire(eng, alive, pending, e, recovery)
            for eng in live:
                if eng.retired:
                    continue
                try:
                    items = run_with_watchdog(eng.harvest, wd,
                                              label=f"harvest[{eng.tag}]")
                except Exception as e:
                    self._retire(eng, alive, pending, e, recovery)
                    continue
                yield from items
