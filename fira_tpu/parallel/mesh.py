"""Device mesh + sharding layout for multi-chip training.

The reference's only accelerator parallelism is single-process
``nn.DataParallel`` scatter/gather over local GPUs
(/root/reference/run_model.py:392-394) — no process groups, no collectives.
The TPU-native replacement is SPMD over a ``jax.sharding.Mesh`` with two
axes:

- ``data``: batch sharding; XLA inserts the gradient ``psum`` over ICI that
  DataParallel's backward gather performed on the host.
- ``model``: Megatron-style tensor parallelism for the d_model-sized
  matmuls — column-parallel first projections (q/k/v, FFN fc1), row-parallel
  second projections (out_proj, fc2, out_fc) — so each pair costs exactly
  one all-reduce, inserted by XLA from the shardings alone.

Everything is laid out with `jax.jit` + `NamedSharding`; there is no
hand-written communication. Loss normalization happens inside the jitted
program over the *global* batch, matching the reference's post-gather
normalization (run_model.py:104-105).
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Sharding-invariant RNG is part of the mesh contract: with the default
# (non-partitionable) threefry lowering, a sharded program's random draws
# (dropout masks) depend on the mesh factorization — bisected on the
# tier-1 dp4xtp2 mesh-vs-single-device loss check, where dropout drift
# reached 3.0e-3 while the partitionable lowering agrees to 6.6e-8 (pure
# f32 reassociation). Every sharded entrypoint imports this module, so
# the flag flips before any mesh exists.
jax.config.update("jax_threefry_partitionable", True)

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence] = None,
              axis_names: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS)) -> Mesh:
    """Build a 2-axis mesh, (data, model) by default — the reference's DP
    regime with all devices on the data axis. ``n_model > 1`` turns on
    tensor parallelism for fira-large-scale runs; other second axes (e.g.
    ring.SEQ_AXIS) reuse the same grid construction via ``axis_names``."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_model:
            raise ValueError(f"{len(devices)} devices not divisible by n_model={n_model}")
        n_data = len(devices) // n_model
    if len(devices) < n_data * n_model:
        raise ValueError(
            f"need {n_data * n_model} devices, have {len(devices)}")
    grid = np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(grid, axis_names)


# (regex over the "/"-joined param path) -> PartitionSpec. First match wins;
# default replicated. Column-parallel layers shard their output feature dim
# (and bias); row-parallel layers shard the contraction dim, XLA closes each
# pair with one psum over MODEL_AXIS.
_PARAM_RULES: Tuple[Tuple[str, P], ...] = (
    # embeddings: shard the feature dim (vocab sizes are odd; d is 2^k)
    (r"embedding$", P(None, MODEL_AXIS)),
    # column-parallel kernels
    (r"(q_proj|k_proj|v_proj|fc1|src_proj|tgt_proj)/kernel$", P(None, MODEL_AXIS)),
    (r"(q_proj|k_proj|v_proj|fc1)/bias$", P(MODEL_AXIS)),
    # row-parallel kernels (bias replicated: applied after the psum)
    (r"(out_proj|fc2)/kernel$", P(MODEL_AXIS, None)),
    # vocab head: contract over sharded d_model -> psum, output replicated
    (r"out_fc/kernel$", P(MODEL_AXIS, None)),
)


def param_spec(path: str) -> P:
    for pattern, spec in _PARAM_RULES:
        if re.search(pattern, path):
            return spec
    return P()


def params_shardings(params, mesh: Mesh):
    """PartitionSpec pytree for a params pytree (rules over joined paths)."""

    def spec_for(key_path, _leaf):
        path = "/".join(str(getattr(k, "key", k)) for k in key_path)
        return NamedSharding(mesh, param_spec(path))

    return jax.tree_util.tree_map_with_path(spec_for, params)


def batch_shardings(batch, mesh: Mesh):
    """Shard every batch array along its leading (batch) dim."""
    return jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(DATA_AXIS)), batch
    )


def stacked_batch_shardings(stacked_batch, mesh: Mesh):
    """Shardings for a K-stacked batch (train.step.stack_batches): axis 0 is
    the scan/step axis (replicated), axis 1 is the batch dim (data axis)."""
    return jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(None, DATA_AXIS)), stacked_batch
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def feed_shardings(mesh: Optional[Mesh]):
    """Feeder ``sharding=`` callable for the grouped/bucketed train stream.

    Mixed-geometry streams pick their sharding by SHAPE, not by bucket
    identity: a K-stacked group (2-D ``valid``) shards axis 1 on the data
    axis with the scan/step axis replicated, a per-step batch shards axis
    0 — so ONE callable covers every member of the (geometry x K) program
    family, and each K-group ships as a single worker-side sharded
    ``device_put``. ``mesh=None`` returns None (the feeder's single-chip
    default placement), so drivers can pass this unconditionally."""
    if mesh is None:
        return None

    def shardings(batch):
        if batch["valid"].ndim == 2:  # K-stacked group (fused/accum)
            return stacked_batch_shardings(batch, mesh)
        return batch_shardings(batch, mesh)

    return shardings


def divisibility_errors(cfg, n_data: int) -> List[str]:
    """Parse-time mesh admission check: every dispatched train batch
    shards its batch axis over the ``data`` mesh axis, so each bucket's
    batch size must divide by ``n_data`` — otherwise the run dies mid-epoch
    in an XLA reshape/sharding error long after startup. Returns one named
    message per offending bucket (all buckets dispatch at ``cfg.batch_size``
    today, but the check prices each declared geometry so a future
    per-bucket batch size cannot silently regress the guarantee). The
    engine fleet's twin (engine_slots vs replica count) lives with the
    fleet (parallel/fleet.py)."""
    errs: List[str] = []
    if n_data <= 1:
        return errs
    from fira_tpu.data.buckets import bucket_table, geom_tag

    for geom in bucket_table(cfg):
        if cfg.batch_size % n_data:
            errs.append(
                f"bucket {geom_tag(geom)}: batch_size {cfg.batch_size} is "
                f"not divisible by the mesh's data axis (n_data={n_data}); "
                f"every dispatched batch shards rows over that axis")
    return errs


def shard_batch(batch, mesh: Mesh):
    """Place a host batch onto the mesh, split along the data axis."""
    return jax.device_put(batch, batch_shardings(batch, mesh))


def shard_params(params, mesh: Mesh):
    return jax.device_put(params, params_shardings(params, mesh))
