"""Online raw-diff ingest: diff-in, message-out serving (docs/INGEST.md).

``difftext`` is the text front end (unified-diff parse/reconstruct +
Java lexing); ``service`` is the per-request pipeline (FSM -> AST
extraction -> frozen-vocab encode -> single-row wire payload) and the
``serve_diffs`` / ``one_shot_message`` drivers; ``cache`` is the ingest
fast path (whole-diff result cache, hunk-level AST memoization, the
parse-stage process executor — docs/INGEST.md "Fast path").
"""

from fira_tpu.ingest.cache import (  # noqa: F401
    HunkMemo,
    IngestCache,
    IngestExecutor,
    LexMemo,
    text_digest,
)
from fira_tpu.ingest.difftext import (  # noqa: F401
    DiffParseError,
    DiffRequest,
    parse_request,
    read_diff_trace,
    reconstruct_diff,
    reconstruct_request,
    write_diff_trace,
)
from fira_tpu.ingest.service import (  # noqa: F401
    IngestError,
    build_fast_path,
    ingest_errors,
    ingest_record,
    ingest_request,
    one_shot_message,
    serve_diffs,
)
