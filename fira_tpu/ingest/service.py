"""Online per-request ingest pipeline: raw diff -> wire payload -> served
message (docs/INGEST.md).

Each request runs the WHOLE preprocessing stack the corpus went through
offline, per request, inside the existing async Feeder worker pool:

    raw diff text
      -> difftext.parse_request          (lex:      file/hunk structure +
                                          Java lexing, mark streams)
      -> fsm.split_hunks + extract_commit (parse:    hunk FSM, AST parse/
                                          diff, graph extraction — native
                                          astdiff, loaded once per process)
      -> process_record + make_batch     (assemble: frozen-vocab encode,
                                          copy labels, COO adjacency, the
                                          exact single-row wire payload the
                                          corpus path ships)

EQUIVALENCE CONTRACT: a corpus commit's reconstructed diff
(difftext.reconstruct_request) pushed through :func:`ingest_request`
yields a wire payload BYTE-IDENTICAL to ``make_batch`` over the frozen
corpus row — and therefore byte-identical served output — provided the
corpus' graph streams came from the same extraction
(data.synthetic.write_extracted_corpus_dir builds exactly such corpora;
tests/test_ingest.py and the check.sh ingest smoke pin it end to end).

DEGRADATION CONTRACT, in order of severity:
- unknown word tokens encode to <unkm> and unknown AST/change labels to
  <pad> (counted per request, never a crash — the corpus path's frozen
  vocabs cover the corpus by construction; arbitrary diffs don't);
- an extraction failure degrades the request to a code-tokens-only graph
  (the pipeline's per-commit degradation, recorded per request);
- an over-budget diff is deterministically TRUNCATED to the config
  geometry (``cfg.ingest_truncate = "clip"``, recorded per request) or
  rejected with a recorded error (``"shed"``) — never a mid-loop
  admissibility backstop in ``make_batch``;
- malformed diff text (difftext.DiffParseError) rides the feeder's
  per-task error channel into the serving loop's poison-request
  quarantine: recorded shed + empty output line, never a dead loop. The
  ``ingest.parse`` fault site (robust/faults.py) injects exactly this
  class of failure deterministically.

Payloads are digest-stamped WORKER-side (decode/prefix_cache.py) when
``cfg.prefix_cache`` is armed, so byte-identical repeated diffs hit the
cross-request prefix cache and in-flight dedup unchanged.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from fira_tpu.config import FiraConfig
from fira_tpu.data.schema import CommitRecord
from fira_tpu.data.vocab import PAD_ID, UNK_TOKEN, Vocab, normalize_token
from fira_tpu.ingest.cache import (EXEC_MODES, HunkMemo, IngestCache,
                                   IngestExecutor, LexMemo, text_digest)
from fira_tpu.ingest.difftext import DiffRequest, parse_request
from fira_tpu.preprocess.fsm import NB, NL, split_hunks
from fira_tpu.preprocess.pipeline import split_sub_tokens

TRUNCATE_MODES = ("clip", "shed")


class IngestError(ValueError):
    """A request the ingest pipeline rejects by POLICY (over-budget under
    ``ingest_truncate = "shed"``, empty after truncation): quarantined
    like a parse failure — recorded shed, never a crash."""


# --------------------------------------------------------------------------
# parse-time knob validation (CLI exit 2 — the ingest twin of
# serve.server.serve_errors / decode.paging.paging_errors)
# --------------------------------------------------------------------------

def ingest_errors(cfg: FiraConfig, *, input_mode: str = "graphs",
                  diff_trace: Optional[str] = None,
                  command: str = "serve") -> List[str]:
    """Named-knob ingest admission check (docs/INGEST.md knob table)."""
    errs: List[str] = []
    if cfg.ingest_workers < 0:
        errs.append(
            f"ingest_workers {cfg.ingest_workers} must be >= 0 assembly "
            f"workers (0 = reuse feeder_workers for ingest request tasks)")
    if cfg.ingest_truncate not in TRUNCATE_MODES:
        errs.append(
            f"ingest_truncate {cfg.ingest_truncate!r} must be one of "
            f"{'/'.join(TRUNCATE_MODES)}: 'clip' deterministically "
            f"truncates an over-budget diff to the config geometry "
            f"(recorded per request), 'shed' rejects it with a recorded "
            f"error")
    if cfg.ingest_cache_entries < 0:
        errs.append(
            f"ingest_cache_entries {cfg.ingest_cache_entries} must be "
            f">= 0 cached whole-diff payloads (0 = unbounded entry "
            f"count; the LRU of the ingest result cache)")
    if cfg.ingest_cache_bytes < 0:
        errs.append(
            f"ingest_cache_bytes {cfg.ingest_cache_bytes} must be >= 0 "
            f"(0 = unbounded; otherwise the whole-diff result cache "
            f"evicts LRU-first until its payload bytes fit)")
    if cfg.ingest_exec not in EXEC_MODES:
        errs.append(
            f"ingest_exec {cfg.ingest_exec!r} must be one of "
            f"{'/'.join(EXEC_MODES)}: 'thread' runs the AST parse stage "
            f"inline on the feeder workers, 'process' ships it to a "
            f"spawned process pool (the GIL-bound stage's scaling mode)")
    if command != "serve":
        return errs
    if input_mode not in ("graphs", "diffs"):
        errs.append(f"--input {input_mode!r} must be 'graphs' (corpus "
                    f"split requests) or 'diffs' (raw-diff requests)")
    if input_mode == "diffs":
        if not diff_trace:
            errs.append(
                "--input diffs needs --diff-trace PATH: a file of "
                "'#! request'-separated unified diffs, or a directory of "
                ".diff files (docs/INGEST.md)")
        elif not os.path.exists(diff_trace):
            errs.append(f"--diff-trace {diff_trace}: path does not exist")
        else:
            # actually load the trace at parse time: an empty file, an
            # unreadable one, or a directory with no .diff files must be
            # exit 2 here — not a raw traceback after the checkpoint
            # loads (request texts are small; reading twice is cheap)
            from fira_tpu.ingest.difftext import read_diff_trace

            try:
                read_diff_trace(diff_trace)
            except (OSError, ValueError) as e:
                errs.append(f"--diff-trace {diff_trace}: {e}")
    elif diff_trace:
        errs.append("--diff-trace only applies with --input diffs "
                    "(--input graphs serves the corpus test split)")
    return errs


# --------------------------------------------------------------------------
# lenient frozen-vocab encoding (OOV -> UNK / PAD, never a crash)
# --------------------------------------------------------------------------

class _LenientVocab(Vocab):
    """View over a frozen vocab whose conversion NEVER raises: unknown
    tokens fall back to <unkm> when the vocab has one (the word vocab),
    else to <pad> (the ast/change vocab, which the corpus covers by
    construction but an arbitrary diff's AST need not). Fallbacks are
    counted — the per-request OOV record. Identical ids to the strict
    vocab whenever every token is known, which is what keeps the
    round-trip contract byte-exact."""

    def __init__(self, base: Vocab):
        self.token_to_id = base.token_to_id
        self.id_to_token = base.id_to_token
        self.unk_fallbacks = 0   # unknown -> <unkm> (the word vocab)
        self.pad_fallbacks = 0   # unknown -> <pad>  (the ast/change vocab)

    def convert_tokens_to_ids(self, tokens) -> List[int]:
        out = []
        for t in tokens:
            t = normalize_token(t)
            if t in self.token_to_id:
                out.append(self.token_to_id[t])
            elif UNK_TOKEN in self.token_to_id:
                self.unk_fallbacks += 1
                out.append(self.token_to_id[UNK_TOKEN])
            else:
                self.pad_fallbacks += 1
                out.append(PAD_ID)
        return out


# --------------------------------------------------------------------------
# per-request record construction (FSM + extraction + truncation policy)
# --------------------------------------------------------------------------

def _truncate_tokens(tokens: List[str], marks: List[int], budget: int
                     ) -> Tuple[List[str], List[int], int]:
    """Clip the streams to ``budget`` tokens at a chunk-safe boundary: a
    cut landing inside an open ``<nb>`` block backs off to before the
    ``<nb>`` (a half-open header block would fail the FSM)."""
    cut = budget
    for j in range(cut - 1, -1, -1):
        if tokens[j] == NL:
            break
        if tokens[j] == NB:
            cut = j
            break
    return tokens[:cut], marks[:cut], len(tokens) - cut


def _clip_sub_tokens(tokens: List[str], atts: List[List[str]],
                     budget: int) -> Tuple[List[List[str]], int]:
    """Drop whole tokens' sub-token lists (every occurrence — the dedup
    invariant requires a repeated token to keep ONE att list) so the
    deduplicated sub-token node count fits ``budget``."""
    kept: set = set()
    used = 0
    dropped: Dict[str, int] = {}   # unique token -> its sub-token count
    for tok, att in zip(tokens, atts):
        if not att or tok in kept or tok in dropped:
            continue
        if used + len(att) > budget:
            dropped[tok] = len(att)
        else:
            kept.add(tok)
            used += len(att)
    if not dropped:
        return atts, 0
    out = [[] if (tok in dropped and att) else att
           for tok, att in zip(tokens, atts)]
    # count dropped NODES (the dedup'd unit the budget is in), not
    # occurrences — a token repeated k times still owned one node set
    return out, sum(dropped.values())


def ingest_record(req: DiffRequest, cfg: FiraConfig, *,
                  truncate: Optional[str] = None,
                  commit_index: Optional[int] = None,
                  memo: Optional[HunkMemo] = None
                  ) -> Tuple[CommitRecord, Dict]:
    """Parsed request -> :class:`CommitRecord` + per-request info dict
    (``truncated``: what the deterministic clip dropped, or None;
    ``degraded``: the extraction error the request degraded on, or
    None). Mirrors the offline pipeline exactly for requests that FIT
    the config geometry — the round-trip contract's precondition.

    ``memo``: optional hunk-level AST memo (``ingest.cache.HunkMemo``)
    — per-chunk extraction reuses cached results across near-identical
    requests, bit-exact by purity (the rebase/merge still runs here)."""
    from fira_tpu.preprocess import extract

    truncate = truncate or cfg.ingest_truncate
    if truncate not in TRUNCATE_MODES:
        raise ValueError(f"truncate {truncate!r} not in {TRUNCATE_MODES}")
    info: Dict = {"truncated": None, "degraded": None}

    def record_trunc(key: str, n: int) -> None:
        if n:
            info["truncated"] = dict(info["truncated"] or {}, **{key: n})

    tokens, marks = list(req.tokens), list(req.marks)
    budget = cfg.sou_len - 2  # <start>/<eos> take two positions
    if len(tokens) > budget:
        if truncate == "shed":
            raise IngestError(
                f"diff has {len(tokens)} tokens > sou budget {budget} "
                f"(ingest_truncate=shed)")
        tokens, marks, dropped = _truncate_tokens(tokens, marks, budget)
        if not tokens:
            raise IngestError(
                "diff empty after truncation to the sou budget (a single "
                "header block larger than sou_len)")
        record_trunc("diff_tokens_dropped", dropped)

    atts = [split_sub_tokens(t) for t in tokens]
    atts, sub_dropped = _clip_sub_tokens(tokens, atts, cfg.sub_token_len)
    if sub_dropped:
        if truncate == "shed":
            raise IngestError(
                f"diff needs {sub_dropped} sub-token nodes beyond "
                f"sub_token_len {cfg.sub_token_len} (ingest_truncate=shed)")
        record_trunc("sub_tokens_dropped", sub_dropped)

    try:
        chunks, types = split_hunks(tokens, marks)
        g = extract.extract_commit(chunks, types, tokens,
                                   commit_index=commit_index, memo=memo)
        ast, change = list(g.ast), list(g.change)
        edge_ast = list(g.edge_ast)
        edge_ast_code = list(g.edge_ast_code)
        edge_change_ast = list(g.edge_change_ast)
        edge_change_code = list(g.edge_change_code)
    except Exception as exc:
        # the pipeline's per-commit degradation (preprocess/pipeline.py):
        # the request keeps its code tokens, the graph goes empty
        info["degraded"] = f"{type(exc).__name__}: {exc}"
        ast, change = [], []
        edge_ast, edge_ast_code = [], []
        edge_change_ast, edge_change_code = [], []

    node_budget = cfg.ast_change_len
    if len(ast) + len(change) > node_budget:
        if truncate == "shed":
            raise IngestError(
                f"diff has {len(ast)} AST + {len(change)} change nodes > "
                f"ast_change_len {node_budget} (ingest_truncate=shed)")
        keep_ast = min(len(ast), node_budget)
        keep_change = node_budget - keep_ast
        record_trunc("ast_nodes_dropped", len(ast) - keep_ast)
        record_trunc("change_nodes_dropped", len(change) - keep_change)
        ast, change = ast[:keep_ast], change[:keep_change]
        edge_ast = [(a, b) for a, b in edge_ast
                    if a < keep_ast and b < keep_ast]
        edge_ast_code = [(a, j) for a, j in edge_ast_code if a < keep_ast]
        edge_change_ast = [(c, a) for c, a in edge_change_ast
                           if c < keep_change and a < keep_ast]
        edge_change_code = [(c, j) for c, j in edge_change_code
                            if c < keep_change]

    record = CommitRecord(
        diff_tokens=tokens, diff_marks=marks, diff_atts=atts,
        msg_tokens=list(req.msg_tokens), var_map=dict(req.var_map),
        ast_labels=ast, change_labels=change,
        edge_ast=edge_ast, edge_ast_code=edge_ast_code,
        edge_change_ast=edge_change_ast,
        edge_change_code=edge_change_code)
    return record, info


# --------------------------------------------------------------------------
# record -> wire payload
# --------------------------------------------------------------------------

def _clip_edges(ex, cfg: FiraConfig) -> Tuple[object, int]:
    """Fit an example's ragged COO under ``cfg.max_edges``: drop TRAILING
    family edges (self-loops — the last ``graph_len`` entries, which the
    bucketed ``make_batch`` drop logic depends on — stay whole)."""
    n = int(ex.senders.shape[0])  # firacheck: allow[HOST-SYNC] Example arrays are host numpy (data/dataset.process_record output); shape arithmetic is pure host planning
    if n <= cfg.max_edges:
        return ex, 0
    fam = n - cfg.graph_len
    keep_fam = cfg.max_edges - cfg.graph_len
    sel = np.r_[0:keep_fam, fam:n]
    return dataclasses.replace(
        ex, senders=ex.senders[sel], receivers=ex.receivers[sel],
        values=ex.values[sel], kinds=ex.kinds[sel]), fam - keep_fam


def ingest_request(text: str, word_vocab: Vocab, ast_change_vocab: Vocab,
                   cfg: FiraConfig, *, table=None,
                   truncate: Optional[str] = None,
                   batch_size: int = 1,
                   lex=None,
                   executor: Optional[IngestExecutor] = None) -> Dict:
    """One raw request -> its single-row wire payload (the exact
    ``make_batch(batch_size=1)`` dict the corpus serve path assembles),
    plus the host-only metadata the serving loop reads:

    - ``_bucket``   smallest admissible decode bucket by the request's
                    MEASURED extents (0 when unbucketed);
    - ``_var``      the request's anonymization map (output
                    de-anonymization), one entry per row;
    - ``_ingest``   lifecycle stamps: per-stage seconds
                    (``lex_s``/``parse_s``/``assemble_s``), token count,
                    the truncation record, the degradation reason, and
                    the OOV fallback counts (``oov_words``: diff/msg
                    tokens encoded to <unkm>; ``oov_ast``: AST/change
                    labels encoded to <pad>).

    ``batch_size``: rows of the assembled batch (request row 0, the rest
    pad) — 1 for the serving loop's single-row payloads, the beam batch
    width for the one-shot ``cli message`` path.

    ``lex``/``executor``: the ingest fast-path hooks (ingest/cache.py,
    docs/INGEST.md "Fast path") — the persistent lexer memo for the lex
    stage and the parse-stage executor (thread-inline with the hunk
    memo, or the spawned process pool). None (default) runs the
    pristine pipeline; outputs are bit-exact either way.
    """
    from fira_tpu.data.batching import make_batch
    from fira_tpu.data.dataset import ProcessedSplit, process_record

    t0 = time.perf_counter()
    req = parse_request(text, lex=lex)
    t1 = time.perf_counter()
    memo_hits = memo_misses = 0
    if executor is not None:
        record, info, memo_hits, memo_misses = executor.parse(
            req, cfg, truncate or cfg.ingest_truncate)
    else:
        record, info = ingest_record(req, cfg, truncate=truncate)
    t2 = time.perf_counter()

    words = _LenientVocab(word_vocab)
    asts = _LenientVocab(ast_change_vocab)
    ex = process_record(record, words, asts, cfg)
    ex, edges_dropped = _clip_edges(ex, cfg)
    if edges_dropped:
        if (truncate or cfg.ingest_truncate) == "shed":
            raise IngestError(
                f"diff has {edges_dropped} edges beyond max_edges "
                f"{cfg.max_edges} (ingest_truncate=shed)")
        info["truncated"] = dict(info["truncated"] or {},
                                 edges_dropped=edges_dropped)
    split1 = ProcessedSplit.from_examples([ex])
    if table is not None:
        from fira_tpu.data import buckets as buckets_lib

        ext = buckets_lib.sample_extents(split1, cfg)
        if cfg.decode_tar_buckets and not record.msg_tokens:
            # tar-bucketed assignment goes by reference-message extent,
            # which is the generation BUDGET cap on the engine — a
            # referenceless real-traffic diff has no such proxy, so it
            # must reserve the FULL tar budget or its generated message
            # would be silently clipped at a small bucket's tar
            ext = dataclasses.replace(
                ext, msg=np.full_like(ext.msg, cfg.tar_len))
        bucket = int(buckets_lib.assign_buckets(
            ext, table, use_msg=cfg.decode_tar_buckets)[0])
        geom = table[bucket]
    else:
        bucket, geom = 0, None
    host = make_batch(split1, np.asarray([0]), cfg,  # firacheck: allow[HOST-SYNC] np.asarray of a host int list builds the make_batch index chunk; no device value exists here
                      batch_size=batch_size, geom=geom)
    t3 = time.perf_counter()

    host["_bucket"] = bucket
    host["_var"] = [req.var_map or None] + [None] * (batch_size - 1)
    host["_ingest"] = {
        "lex_s": round(t1 - t0, 9),
        "parse_s": round(t2 - t1, 9),
        "assemble_s": round(t3 - t2, 9),
        "n_tokens": len(record.diff_tokens),
        "truncated": info["truncated"],
        "degraded": info["degraded"],
        "oov_words": words.unk_fallbacks,
        "oov_ast": asts.pad_fallbacks,
    }
    if executor is not None:
        # the PARTIAL-hit meter (docs/INGEST.md "Fast path"): hunk-memo
        # reuse inside a whole-diff MISS — accounted separately from the
        # whole-diff `cached` flag the result cache replays
        host["_ingest"]["memo_hits"] = memo_hits
        host["_ingest"]["memo_misses"] = memo_misses
    return host


def build_fast_path(cfg: FiraConfig, *, faults=None, context=None):
    """The ingest fast-path objects for one serve run, per the knobs:
    ``(cache, lex, executor)`` — the whole-diff result cache + lexer
    memo (None with ``ingest_cache`` off), and the execution mode (the
    spawned process pool under ``ingest_exec=process``; the
    thread-inline executor carrying the hunk memo when the fast path is
    armed; None when everything is off — the pristine legacy path).

    ``context``: ``(word_vocab, ast_change_vocab, cfg, table)`` — when
    given, the process pool does WHOLE-request offload: each cache miss
    ships raw text out and an assembled payload back, so the parent's
    per-request GIL time is pickling only and ``ingest_workers`` scales
    across cores (the serve path always passes it). The caller owns
    ``executor.close()`` (serve_diffs wraps it in a finally)."""
    cache = lex = memo = None
    if cfg.ingest_cache:
        cache = IngestCache(cfg.ingest_cache_entries,
                            max_bytes=cfg.ingest_cache_bytes,
                            faults=faults)
        memo = HunkMemo()
        lex = LexMemo()
    if cfg.ingest_exec == "process":
        executor = IngestExecutor(
            "process", workers=cfg.ingest_workers or cfg.feeder_workers,
            context=context)
    elif memo is not None:
        executor = IngestExecutor("thread", memo=memo)
    else:
        executor = None
    return cache, lex, executor


def ingest_request_tasks(requests: Sequence[str], cfg: FiraConfig,
                         word_vocab: Vocab, ast_change_vocab: Vocab,
                         table=None, faults=None, cache=None, lex=None,
                         executor: Optional[IngestExecutor] = None):
    """One ingest task per request, request order — the Feeder runs them
    on its worker pool exactly like serve._request_tasks runs corpus
    assembly: payloads are ready ahead of their arrivals, a failing
    request rides the per-task error channel into the quarantine, and
    digests are stamped worker-side when the prefix cache is armed. The
    ``ingest.parse`` fault site fires here (raise/hang before the parse,
    corrupt on the assembled payload — each retry a fresh keyed draw).

    ``cache``/``lex``/``executor``: the fast-path hooks from
    :func:`build_fast_path`. With the cache armed the raw text is
    content-addressed BEFORE any lexing: a byte-identical repeat skips
    the whole pipeline and replays the stored payload (``_ingest``
    stamps with ``cached: True``); the ``ingest.cache`` fault site fires
    inside the lookup (raise => miss, corrupt => checksum-detected drop
    => re-ingest). The cache stores the CLEAN computation — the
    ``ingest.parse`` corrupt scramble and the prefix-cache digest stamp
    are applied per emission, after the lookup, so fault blast radii and
    dedup identities are byte-for-byte what the cache-off path
    produces."""
    from fira_tpu.data.feeder import task_note

    stamp = None
    if cfg.prefix_cache:
        # tier-namespaced like every other stamping site: the digest
        # commits to the serving precision so artifacts cached under one
        # tier can never seat a slot under another (decode/quant.py)
        import functools

        from fira_tpu.decode import quant
        from fira_tpu.decode.prefix_cache import stamp_digests
        stamp = functools.partial(stamp_digests,
                                  namespace=quant.tier_namespace(cfg))

    for i, text in enumerate(requests):
        def task(text=text, i=i, attempts={"n": 0}):
            if faults is not None:
                # advance the attempt BEFORE the check so a fired raise
                # still moves the key forward — every retry is a fresh
                # deterministic draw (the feeder.assemble contract)
                key = (i, attempts["n"])
                attempts["n"] += 1
                faults.check("ingest.parse", key=key)
            host = None
            digest = None
            if cache is not None:
                digest = text_digest(text)
                host, _outcome = cache.take(digest, fault_key=i)
            if host is None:
                # a miss makes this task the digest's in-flight leader:
                # concurrent duplicates are parked inside cache.take
                # until put (success) or abandon (the quarantine path —
                # a failing request must not wedge its duplicates)
                try:
                    if executor is not None and executor.offloads_requests:
                        # whole-request process offload: text out,
                        # assembled payload back — the parent thread
                        # parks GIL-free
                        host = executor.ingest(text)
                    else:
                        host = ingest_request(text, word_vocab,
                                              ast_change_vocab, cfg,
                                              table=table, lex=lex,
                                              executor=executor)
                except BaseException:
                    if cache is not None:
                        cache.abandon(digest)
                    raise
                if cache is not None:
                    cache.put(digest, host)
            if faults is not None:
                host = faults.corrupt("ingest.parse", i, host)
            return stamp(host) if stamp is not None else host
        task.note = task_note([i], site="ingest request")
        yield task


def _template_split(word_vocab: Vocab, ast_change_vocab: Vocab,
                    cfg: FiraConfig):
    """A one-row ProcessedSplit at the config geometry (an empty commit)
    — the shape/dtype source for all-pad warmup/template batches when no
    corpus split backs the request stream."""
    from fira_tpu.data.dataset import ProcessedSplit, process_record

    rec = CommitRecord([], [], [], [], {}, [], [], [], [], [], [])
    ex = process_record(rec, _LenientVocab(word_vocab),
                        _LenientVocab(ast_change_vocab), cfg)
    return ProcessedSplit.from_examples([ex])


# --------------------------------------------------------------------------
# the diff-serving driver (the raw-diff twin of serve.server.serve_split)
# --------------------------------------------------------------------------

def serve_diffs(model, params, word_vocab: Vocab, ast_change_vocab: Vocab,
                cfg: FiraConfig, *,
                requests: Sequence[str],
                arrival_times,
                out_dir: str = "OUTPUT",
                ablation: Optional[str] = None,
                guard=None,
                engine_slots: Optional[int] = None,
                refill_order: str = "fifo",
                clock: str = "wall",
                step_cost_s: float = 1.0,
                prefill_cost_s: float = 1.0,
                engine=None,
                faults=None,
                metrics_path: Optional[str] = None,
                fast_path=None) -> Dict:
    """Serve raw-diff ``requests`` (request ``i`` arrives at
    ``arrival_times[i]``) end to end through the ServeLoop: same
    admission/deadline/shed/retirement/dedup machinery, same
    position-keyed ordered writer, same metrics artifact — the request
    payloads just come from :func:`ingest_request` on the feeder workers
    instead of corpus ``make_batch``. Requests that fail to parse (or
    are rejected by the truncation policy) are recorded-shed with an
    empty output line; every completed request's lifecycle record
    carries its ingest stamps."""
    from fira_tpu.data import buckets as buckets_lib
    from fira_tpu.data.feeder import Feeder
    from fira_tpu.decode.runner import output_name
    from fira_tpu.decode.stream import OrderedStreamWriter
    from fira_tpu.decode.text import (cook_prediction, deanonymize,
                                      reference_words)
    from fira_tpu.eval.dev_bleu import nltk_sentence_bleu
    from fira_tpu.robust import faults as faults_lib
    from fira_tpu.serve.server import (ServeLoop, build_engines,
                                       finalize_serve_result, make_clock,
                                       metrics_snapshotter,
                                       prepare_templates,
                                       run_loop_guarded, serve_errors)

    if faults is None:
        faults = faults_lib.injector_from(cfg)
    times = np.asarray(arrival_times, dtype=np.float64)
    n_req = len(times)
    if n_req != len(requests):
        raise ValueError(f"{len(requests)} requests for {n_req} arrivals")
    errs = serve_errors(cfg, trace=True) + ingest_errors(cfg)
    if errs:
        raise ValueError("; ".join(errs))
    clk = make_clock(clock, step_cost_s=step_cost_s,
                     prefill_cost_s=prefill_cost_s)

    table = buckets_lib.decode_table(cfg) if cfg.buckets else None
    tmpl_split = _template_split(word_vocab, ast_change_vocab, cfg)
    owner, engines, built = build_engines(model, params, cfg,
                                          engine=engine,
                                          engine_slots=engine_slots,
                                          guard=guard, faults=faults)
    templates = prepare_templates(owner, tmpl_split, cfg, table,
                                  guard=guard, prewarm=built)

    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, output_name(ablation))
    bleu_by_pos: Dict[int, float] = {}
    snapshot = metrics_snapshotter(metrics_path, owner, faults)

    def emit(pos, host, row, tokens, probs):
        # the sample_emitter tail with the request's OWN anonymization
        # map (the packed batch's _var column) instead of a corpus-
        # indexed var_maps table — identical cooking, so reconstructed
        # corpus requests serve byte-identical output
        best = int(np.argmax(probs))
        ids = tokens[best].tolist()
        hyp = cook_prediction(ids[1:], host["diff"][row],
                              host["sub_token"][row], word_vocab, cfg,
                              resolve=False)
        ref = reference_words(host["msg"][row], word_vocab)
        bleu_by_pos[pos] = nltk_sentence_bleu([ref], hyp)
        vm = host.get("_var")
        var_map = vm[row] if vm is not None else None
        writer.add(pos, " ".join(deanonymize(hyp, var_map)) + "\n")

    # the ingest fast path (docs/INGEST.md "Fast path"): whole-diff
    # result cache + lexer memo + execution mode, one set per serve
    # run; the executor owns pool processes, so its close rides the
    # same finally as the feeder threads. The pipeline DEPTH scales
    # with the worker count: single-row ingest payloads are tens of KB,
    # and a depth that caps in-flight tasks at feeder_depth=4 would
    # idle a wide pool the moment four payloads are ready — the workers
    # must be able to run AHEAD of arrivals (that is the whole point of
    # pre-assembly) for fan-out to show up as stall reduction.
    workers = cfg.ingest_workers or cfg.feeder_workers
    depth = max(cfg.feeder_depth, 4 * max(1, workers))
    if fast_path is not None:
        # caller-owned reuse across runs (the engine= discipline): the
        # caller keeps the pool warm and decides cache clearing/close
        cache, lex, executor = fast_path
        own_executor = None
    else:
        cache, lex, executor = build_fast_path(
            cfg, faults=faults,
            context=(word_vocab, ast_change_vocab, cfg, table))
        own_executor = executor
    try:
        with OrderedStreamWriter(out_path, expected=n_req) as writer, \
                Feeder(ingest_request_tasks(requests, cfg, word_vocab,
                                            ast_change_vocab, table,
                                            faults=faults, cache=cache,
                                            lex=lex, executor=executor),
                       num_workers=workers,
                       depth=depth, put=False,
                       on_error="record",
                       retries=max(0, cfg.robust_retries),
                       faults=faults) as feed:
            loop = ServeLoop(
                engines, cfg, arrival_times=times, feed=feed, table=table,
                assignment=None, templates=templates, clock=clk, emit=emit,
                shed=lambda rec: writer.add(rec.position, "\n"),
                refill_order=refill_order, faults=faults, snapshot=snapshot)
            loop.stats.ingest_pipeline = (workers, depth)
            if cache is not None:
                # the run-level cache meter lands in the serve summary's
                # ingest block (entries/bytes/hits/evictions/integrity)
                loop.stats.ingest_cache = cache.summary
            stats = run_loop_guarded(loop, snapshot)
    finally:
        if own_executor is not None:
            own_executor.close()
    # same teardown oracle as serve.server.serve_split: armed, a leaked
    # block/thread/pool raises here naming its acquire site (success
    # path only — a serve error must not be masked by its leak fallout)
    from fira_tpu.analysis.sanitizer import leak_guard

    lg = leak_guard()
    if lg is not None:
        lg.assert_clean("serve_diffs teardown")
    return finalize_serve_result(stats, owner, faults, out_path=out_path,
                                 bleu_by_pos=bleu_by_pos,
                                 metrics_path=metrics_path)


# --------------------------------------------------------------------------
# one-shot: cli message <diff-file>
# --------------------------------------------------------------------------

def one_shot_message(model, params, word_vocab: Vocab,
                     ast_change_vocab: Vocab, cfg: FiraConfig,
                     text: str) -> str:
    """One diff in, one commit message out (``cli message``): ingest the
    request through the SAME pipeline the serving loop uses (truncation
    policy included — a diff `cli serve --input diffs` would shed under
    ``ingest_truncate=shed`` is rejected here too), run the batched beam
    on the payload, cook and de-anonymize the argmax beam. No engine, no
    serving loop — the smallest possible diff->message path."""
    from fira_tpu.decode.beam import make_beam_search
    from fira_tpu.decode.text import cook_prediction, deanonymize

    host = ingest_request(text, word_vocab, ast_change_vocab, cfg,
                          batch_size=cfg.test_batch_size)
    beam = make_beam_search(model, cfg)
    wire = {k: v for k, v in host.items() if not k.startswith("_")}
    tokens, probs = beam(params, wire)
    tokens = np.asarray(tokens)
    probs = np.asarray(probs)
    best = int(np.argmax(probs[0]))
    hyp = cook_prediction(tokens[0][best].tolist()[1:], host["diff"][0],
                          host["sub_token"][0], word_vocab, cfg,
                          resolve=False)
    return " ".join(deanonymize(hyp, host["_var"][0]))
