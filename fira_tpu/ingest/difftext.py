"""Diff-text front end: raw unified git diff <-> (difftoken, diffmark).

The corpus pipeline starts from pre-tokenized ``difftoken.json`` /
``diffmark.json`` streams (the crawl stage's output); a real user sends a
RAW unified diff. This module is the bridge, in both directions:

- :func:`parse_request` — unified-diff text -> the aligned
  ``(difftoken, diffmark)`` streams ``preprocess/fsm.split_hunks``
  consumes. File headers (``diff --git`` / ``---`` / ``+++`` / mode
  lines) are metadata and skipped; each ``@@ -a,b +c,d @@ section``
  hunk header becomes a ``<nb> ... <nl>`` block (the reference's header
  sentinels — git's section text IS the enclosing-declaration header
  FIRA keeps there), and each body line's content is lexed with the
  native Java lexer (``astdiff_binding.tokenize`` — the javalang
  stand-in the rest of preprocessing already uses) under mark 2
  (context, ``' '``), 1 (delete, ``'-'``), or 3 (add, ``'+'``).
  Optional ``#!`` metadata lines carry a reference message
  (``#! msg: fix npe``) and a variable-anonymization map
  (``#! var: {"getUserName": "STRING3"}``) — present on reconstructed
  corpus requests, absent on real traffic.
- :func:`reconstruct_diff` / :func:`reconstruct_request` — the inverse:
  a corpus commit's token/mark streams rendered back into a canonical
  unified diff (one body line per same-mark token run, tokens space-
  joined). ``parse_request(reconstruct_request(record))`` reproduces the
  record's streams exactly (pinned by tests/test_ingest.py), which is
  what makes the ingest round-trip equivalence contract (docs/INGEST.md)
  testable end-to-end: reconstructed diff -> ingest -> byte-identical
  wire payload vs the frozen corpus path.

Line boundaries deliberately do NOT round-trip — only the (token, mark)
streams do. The FSM merges consecutive same-mark tokens into one run
regardless of the lines they arrived on, so splitting a run across body
lines is a no-op downstream.

Trace I/O: :func:`read_diff_trace` / :func:`write_diff_trace` handle the
``cli serve --input diffs`` request sources — a single file of
``#! request``-separated diffs, or a directory of ``*.diff`` files
served in sorted name order.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
from typing import Dict, List, Sequence, Tuple

from fira_tpu.preprocess import astdiff_binding as astdiff
from fira_tpu.preprocess.fsm import NB, NL


class DiffParseError(ValueError):
    """Malformed diff text — the ``ingest.parse`` failure the serving
    loop's poison-request quarantine sheds with a recorded reason
    (docs/INGEST.md), never a crash or a dead loop."""


# one unified-diff hunk header; group(1) is git's trailing section text
# (the enclosing declaration — FIRA's <nb> header block content)
_HUNK_RE = re.compile(r"^@@\s+-\d+(?:,\d+)?\s+\+\d+(?:,\d+)?\s+@@(.*)$")

# file-level metadata lines: request framing, not diff content. Only
# honored OUTSIDE a hunk (after `diff --git` / before the first `@@`) —
# inside a hunk a line starting with "--- " is a deletion whose content
# begins with "--" (git disambiguates by position, so must we).
_FILE_HEADER_PREFIXES = (
    "diff --git", "index ", "--- ", "+++ ", "new file mode",
    "deleted file mode", "old mode", "new mode", "similarity index",
    "dissimilarity index", "rename from", "rename to", "copy from",
    "copy to", "Binary files",
)
# skippable anywhere: git emits this marker INSIDE hunks, and its
# leading backslash can never collide with a body-line marker
_ANYWHERE_SKIP_PREFIXES = ("\\ No newline",)

_MARK_BY_CHAR = {" ": 2, "-": 1, "+": 3}
_CHAR_BY_MARK = {2: " ", 1: "-", 3: "+"}


@dataclasses.dataclass
class DiffRequest:
    """One parsed raw-diff request: the aligned token/mark streams plus
    the optional ``#!`` metadata (empty for real traffic — the message
    is what the model generates, and anonymization maps only exist for
    corpus-reconstructed requests)."""

    tokens: List[str]
    marks: List[int]
    msg_tokens: List[str]
    var_map: Dict[str, str]


def _lex(text: str, where: str, lex=None) -> List[str]:
    if not text.strip():
        return []
    toks = (lex or astdiff.tokenize)(text)
    if toks is None:
        raise DiffParseError(f"{where}: unlexable content {text!r}")
    return toks


def parse_request(text: str, *, lex=None) -> DiffRequest:
    """Raw request text -> :class:`DiffRequest`. Raises
    :class:`DiffParseError` (with the offending line number) on anything
    that is not a unified diff: a body line before any ``@@`` hunk
    header, an unknown marker character, malformed ``#!`` metadata, or a
    request with no diff content at all.

    ``lex``: optional text -> tokens callable replacing the native
    lexer — the ingest fast path passes ``ingest.cache.LexMemo`` here
    (persistent per-process lexer state: repeated body lines lex once),
    with identical output to the bare lexer by construction."""
    tokens: List[str] = []
    marks: List[int] = []
    msg_tokens: List[str] = []
    var_map: Dict[str, str] = {}
    in_hunk = False
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip("\r")
        if line.startswith("#!"):
            meta = line[2:].strip()
            if meta.startswith("msg:"):
                msg_tokens = meta[len("msg:"):].split()
            elif meta.startswith("var:"):
                try:
                    var_map = json.loads(meta[len("var:"):])
                except json.JSONDecodeError as e:
                    raise DiffParseError(
                        f"line {ln}: '#! var:' payload is not JSON: {e}"
                    ) from None
                if not isinstance(var_map, dict) or not all(
                        isinstance(k, str) and isinstance(v, str)
                        for k, v in var_map.items()):
                    raise DiffParseError(
                        f"line {ln}: '#! var:' payload must be a "
                        f"{{original: placeholder}} string map")
            elif meta.startswith("request"):
                continue  # trace separator riding inside a request text
            else:
                raise DiffParseError(
                    f"line {ln}: unknown '#!' metadata {line!r} (known: "
                    f"'#! msg: ...', '#! var: {{...}}', '#! request')")
            continue
        if not line.strip():
            continue
        if any(line.startswith(p) for p in _ANYWHERE_SKIP_PREFIXES):
            continue
        if line.startswith("diff --git"):
            in_hunk = False  # a new file section: headers follow
            continue
        if not in_hunk and any(line.startswith(p)
                               for p in _FILE_HEADER_PREFIXES):
            continue
        m = _HUNK_RE.match(line)
        if m:
            in_hunk = True
            section = m.group(1).strip()
            if section:
                toks = _lex(section, f"line {ln}", lex)
                if toks:
                    tokens += [NB] + toks + [NL]
                    marks += [2] * (len(toks) + 2)
            continue
        c = line[0]
        if c not in _MARK_BY_CHAR:
            raise DiffParseError(
                f"line {ln}: {line!r} is neither a diff body line "
                f"(' '/'-'/'+'), a file header, nor an @@ hunk header")
        if not in_hunk:
            raise DiffParseError(
                f"line {ln}: diff body line before any @@ hunk header")
        toks = _lex(line[1:], f"line {ln}", lex)
        tokens += toks
        marks += [_MARK_BY_CHAR[c]] * len(toks)
    if not tokens:
        raise DiffParseError("no diff content (no tokens in any hunk)")
    return DiffRequest(tokens=tokens, marks=marks, msg_tokens=msg_tokens,
                       var_map=var_map)


# --------------------------------------------------------------------------
# reconstruction (corpus streams -> canonical diff text)
# --------------------------------------------------------------------------

def reconstruct_diff(tokens: Sequence[str], marks: Sequence[int]) -> str:
    """Render corpus ``(difftoken, diffmark)`` streams as a canonical
    unified diff whose :func:`parse_request` output reproduces the
    streams exactly. ``<nb> ... <nl>`` blocks become hunk headers with
    the block's tokens as section text; each maximal same-mark token run
    becomes one space-joined body line. Raises ValueError on streams it
    cannot represent (an empty ``<nb>`` block, a stray ``<nl>``) — a
    corpus-quality problem, not a request-path one."""
    if len(tokens) != len(marks):
        raise ValueError(f"token/mark length mismatch: "
                         f"{len(tokens)} vs {len(marks)}")
    lines = ["diff --git a/commit.java b/commit.java",
             "--- a/commit.java", "+++ b/commit.java"]
    run: List[str] = []
    run_mark = None
    saw_hunk = False

    def flush() -> None:
        if run:
            # a SPACE separates the marker from the content: a run whose
            # first token is "--"/"++" would otherwise render as
            # "--- ..."/"+++ ..." and be skipped as a file header on
            # re-parse (lexing is whitespace-insensitive, so the extra
            # space round-trips exactly)
            lines.append(_CHAR_BY_MARK[run_mark] + " " + " ".join(run))

    toks = list(tokens)
    i, n = 0, len(toks)
    while i < n:
        t = toks[i]
        if t == NB:
            flush()
            run, run_mark = [], None
            # ONE forward walk to the closing <nl>, collecting the inner
            # tokens and checking marks in the same scan — index() plus
            # two re-slices walked every header block three times, and
            # header blocks are one per hunk on many-hunk diffs
            inner: List[str] = []
            bad_mark = marks[i] != 2
            j = i + 1
            while j < n and toks[j] != NL:
                inner.append(toks[j])
                bad_mark = bad_mark or marks[j] != 2
                j += 1
            if j >= n:
                raise ValueError(f"<nb> at {i} without closing <nl>")
            if bad_mark or marks[j] != 2:
                raise ValueError(f"non-context mark inside <nb> block at {i}")
            if not inner:
                raise ValueError(
                    f"empty <nb> block at {i}: an empty header block has "
                    f"no diff-text representation")
            lines.append(f"@@ -1,1 +1,1 @@ {' '.join(inner)}")
            saw_hunk = True
            i = j + 1
            continue
        if t == NL:
            raise ValueError(f"stray <nl> at {i} outside a <nb> block")
        if not saw_hunk:
            # a stream not opening with a header block still needs a hunk
            # delimiter; a bare header contributes no tokens on re-parse
            lines.append("@@ -1,1 +1,1 @@")
            saw_hunk = True
        m = marks[i]
        if m not in _CHAR_BY_MARK:
            raise ValueError(f"mark {m!r} at {i} outside {{1,2,3}}")
        if m != run_mark:
            flush()
            run, run_mark = [], m
        run.append(t)
        i += 1
    flush()
    return "\n".join(lines) + "\n"


def reconstruct_request(record) -> str:
    """One corpus commit (:class:`data.schema.CommitRecord`) as a full
    request text: ``#!`` metadata (reference message + anonymization
    map, when present) followed by the reconstructed diff — the
    round-trip input of the ingest equivalence contract."""
    head: List[str] = []
    if record.msg_tokens:
        head.append("#! msg: " + " ".join(record.msg_tokens))
    if record.var_map:
        head.append("#! var: " + json.dumps(record.var_map, sort_keys=True))
    body = reconstruct_diff(record.diff_tokens, record.diff_marks)
    return "\n".join(head + [body]) if head else body


# --------------------------------------------------------------------------
# diff-trace I/O (cli serve --input diffs)
# --------------------------------------------------------------------------

_REQUEST_SEP = "#! request"


def write_diff_trace(path: str, requests: Sequence[str]) -> str:
    """Write a file-of-diffs trace: each request prefixed by a
    ``#! request <i>`` separator line."""
    with open(path, "w") as f:
        for i, req in enumerate(requests):
            f.write(f"{_REQUEST_SEP} {i}\n")
            f.write(req if req.endswith("\n") else req + "\n")
    return path


def read_diff_trace(path: str) -> List[str]:
    """Load the request texts of a diff trace: a directory of ``*.diff``
    files (sorted name order = request order), or a single file —
    split on ``#! request`` separator lines when present, else one
    request. Raises ValueError on an empty source (path EXISTENCE is
    checked earlier, at parse time — ingest.service.ingest_errors)."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.diff")))
        if not files:
            raise ValueError(f"diff-trace directory {path} holds no "
                             f".diff files")
        out = []
        for fp in files:
            with open(fp) as f:
                out.append(f.read())
        return out
    with open(path) as f:
        text = f.read()
    if _REQUEST_SEP not in text:
        if not text.strip():
            raise ValueError(f"diff trace {path} is empty")
        return [text]
    requests: List[str] = []
    buf: List[str] = []
    for line in text.splitlines(keepends=True):
        if line.startswith(_REQUEST_SEP):
            if "".join(buf).strip():
                # content before the first separator is request 0 —
                # never silently dropped
                requests.append("".join(buf))
            buf = []
            continue
        buf.append(line)
    if "".join(buf).strip():
        requests.append("".join(buf))
    if not requests:
        raise ValueError(f"diff trace {path} holds no requests")
    return requests
