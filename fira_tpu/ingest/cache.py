"""Ingest fast path: whole-diff result cache + hunk-level AST memoization
+ the parse-stage process executor (docs/INGEST.md "Fast path").

INGEST_BENCH_r01 measured online ingest at ~4 ms/request (24% lex / 60%
AST parse+diff / 19% assemble) — the CPU-tiny serving bottleneck
(ingest-stall fraction 0.39-0.47). The PR-10 insight (content-address
repeated work, share the result) applies one layer earlier than the
prefill cache: that cache's digest is computed on the ASSEMBLED payload,
so a repeated diff still paid the whole lex->AST->assemble pipeline
before hitting it. This module moves the content addressing to request
INTAKE, plus memoizes the dominant AST stage at sub-request granularity,
plus gives the GIL-bound parse stage a real process-pool escape:

- :class:`IngestCache` — the whole-diff result cache. Requests are
  content-addressed by a KEYED blake2b digest of the raw diff text bytes
  (:func:`text_digest` — the ``robust/faults`` keyed-digest idiom, never
  process-salted ``hash()``), in front of lex/parse. A byte-identical
  repeat skips the entire ingest pipeline and seats from a capacity/
  byte-bounded LRU of assembled wire payloads; its ``_ingest`` stamps
  are replayed from the original computation with a ``cached`` flag.
  The PR-10 prefill cache/dedup then ALSO fires on the same payload
  digest (``_digests`` is re-stamped per emission) — two cache layers,
  one repeat. While a fault injector arms the ``ingest.cache`` site,
  every entry carries a content checksum verified at lookup: a raise is
  absorbed as a MISS (full re-ingest, bytes unchanged) and a
  corrupt-injected read is DETECTED and dropped (re-ingest, never a
  wrong answer) — unarmed, entries are trusted process memory, exactly
  the ``decode/prefix_cache`` integrity discipline.
- :class:`HunkMemo` — hunk-level AST memoization. The per-chunk
  extraction (``preprocess.extract.update_chunk_edges`` /
  ``normal_chunk_edges``) is a pure function of the typed chunk tokens
  (the ingest path runs index-free), so near-identical diffs — CI
  re-runs and bot traffic where one file changed out of many — reuse
  parsed/diffed sub-results across requests while ``extract_commit``'s
  rebase/merge re-runs deterministically. Keys are keyed digests of
  (chunk type, tokens); hit accounting is separate from whole-diff hits
  (``memo_hits``/``memo_misses`` per request, the PARTIAL-hit meter).
  :class:`LexMemo` is the same idea for the native lexer: one bounded
  text->tokens map, so repeated body lines (context lines are near-
  universal repeats) lex once per process — persistent lexer state
  shared by every ingest worker.
- :class:`IngestExecutor` — the parse-stage execution mode behind
  ``cfg.ingest_exec``. "thread" runs the stage inline on the feeder
  worker thread (the native astdiff calls already release the GIL;
  the Python around them doesn't). "process" ships the stage to a
  SPAWNED process pool sized by the ingest worker count: the submitting
  worker thread parks on the future (GIL released) while other workers
  keep lexing/assembling — stage pipelining across requests, so a slow
  AST parse never head-of-line-blocks the next request's lex. Each pool
  process keeps its own process-local :class:`HunkMemo` (spawned
  workers share no memory); outputs are bit-exact either way because
  the stage is a pure function of its inputs.

Equivalence contract (tests/test_ingest.py + the check.sh ingest-cache
smoke): served output bytes are identical with ``cfg.ingest_cache`` on
vs off vs the frozen-corpus path, at zero post-warmup retraces — every
mechanism here is pure host work in front of already-declared program
geometries.

This module deliberately imports no JAX: it is the spawn-entry module
for the process pool, and a pool worker must not drag a second copy of
the device runtime up just to parse Java.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

_DIGEST_KEY = b"fira-ingest-cache-v1"

EXEC_MODES = ("thread", "process")

# hunk-memo capacity in cached chunks (a chunk is a few hundred bytes of
# tokens + its ChunkGraph): sized so a realistic working set of repeated
# context/update hunks stays resident without an explicit knob
HUNK_MEMO_ENTRIES = 4096
# lexer-memo capacity in distinct line texts
LEX_MEMO_ENTRIES = 8192


def _arm_thread_guard(owner, lock, structures):
    """Lock-discipline sanitizer hook (analysis.sanitizer
    .guard_structures; docs/ANALYSIS.md "Runtime sanitizer"): when a
    ThreadGuard is armed, a mutation of the wrapped structures without
    the owning lock raises at the mutating line; unarmed, the inputs
    come back untouched. The import is LAZY and the sanitizer pulls no
    JAX at module level, so this module stays a safe spawn entry for
    the process pool children (which construct their own memos)."""
    from fira_tpu.analysis.sanitizer import guard_structures

    return guard_structures(owner, lock, structures)


def text_digest(text: str) -> str:
    """Content address of one raw request: keyed blake2b over the diff
    text bytes — computed at intake, BEFORE any lexing."""
    h = hashlib.blake2b(key=_DIGEST_KEY, digest_size=16)
    h.update(text.encode("utf-8"))
    return h.hexdigest()


def _payload_checksum(host: Dict) -> str:
    """Keyed digest of a cached payload's WIRE content (name, dtype,
    shape, bytes per array): what the ``ingest.cache`` corrupt leg must
    be caught against. Host-only "_" keys are excluded — they are
    replayed metadata, not served content."""
    h = hashlib.blake2b(key=_DIGEST_KEY, digest_size=16)
    for name in sorted(k for k in host if not k.startswith("_")):
        a = np.ascontiguousarray(np.asarray(host[name]))  # firacheck: allow[HOST-SYNC] ingest payloads are host numpy by construction (assembled worker-side, put=False); no device value exists here
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def payload_nbytes(host: Dict) -> int:
    return sum(int(np.asarray(v).nbytes)
               for k, v in host.items() if not k.startswith("_"))


@dataclasses.dataclass
class _Entry:
    host: Dict            # assembled payload (+ _bucket/_var/_ingest stamps)
    checksum: Optional[str]  # wire-content digest — maintained only while
    #                          the ingest.cache fault site is armed (its
    #                          corrupt injection is the one writer between
    #                          put and take; unarmed, hashing every hit
    #                          would tax the workers the cache relieves)
    nbytes: int


class IngestCache:
    """Capacity/byte-bounded LRU of assembled wire payloads, content-
    addressed by raw-diff text digest. Shared across the feeder WORKER
    threads (unlike the scheduler-owned prefix cache), so takes/puts are
    lock-protected; the lock never covers an ingest computation. An
    in-flight digest COALESCES concurrent takers onto its leader's
    computation (see :meth:`take`), so a repeated diff re-ingests zero
    times post-warmup under any thread schedule.

    ``entries`` 0 = unbounded entry count; ``max_bytes`` 0 = unbounded
    host bytes — both bounds honored together when set, and an
    over-budget entry alone still lives (the cache degrades to capacity
    one, never refuses to serve).
    """

    def __init__(self, entries: int = 512, *, max_bytes: int = 0,
                 faults=None):
        if int(entries) < 0:
            raise ValueError(
                f"ingest cache entries must be >= 0 (0 = unbounded), "
                f"got {entries}")
        if int(max_bytes) < 0:
            raise ValueError(
                f"ingest cache byte budget must be >= 0 (0 = unbounded), "
                f"got {max_bytes}")
        self.capacity = int(entries)
        self.max_bytes = int(max_bytes)
        self._lru: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._faults = faults
        self._nbytes = 0
        self._lookups = 0
        # in-flight leadership (the PR-10 dedup idiom one layer up):
        # digest -> Event set when the leader publishes or abandons.
        # A concurrent taker of an in-flight digest PARKS instead of
        # recomputing, so a repeated diff never re-ingests even when
        # its first occurrence is still mid-pipeline on another worker
        self._pending: Dict[str, threading.Event] = {}
        self.hits = 0
        self.misses = 0
        self.coalesced = 0
        self.fault_misses = 0
        self.integrity_drops = 0
        self.evictions = 0
        # lock-discipline sanitizer (--sanitize / tests): the LRU and the
        # in-flight leadership map are mutated from every feeder worker —
        # armed, a mutation outside `with self._lock` raises at the line
        self._lock, (self._lru, self._pending) = _arm_thread_guard(
            self, self._lock, [(self._lru, "_lru"),
                               (self._pending, "_pending")])

    def _integrity(self) -> bool:
        return self._faults is not None and self._faults.armed(
            "ingest.cache")

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def take(self, digest: str, *, fault_key=None,
             wait_s: float = 15.0) -> Tuple[Optional[Dict], str]:
        """(payload, outcome) — outcome one of ``hit`` / ``miss`` /
        ``fault_miss`` (injected lookup raise, absorbed: a cache fault
        re-ingests, never sheds) / ``integrity_drop`` (checksum caught a
        corrupt-injected read: entry evicted, caller re-ingests). A hit
        returns a SHALLOW copy whose ``_ingest`` stamps are replayed
        with ``cached: True`` — the arrays themselves are shared
        read-only (the serve loop copies rows into packed batches, it
        never writes a payload in place).

        A ``miss`` makes the caller the digest's in-flight LEADER: it
        MUST follow with :meth:`put` (success) or :meth:`abandon`
        (failed compute) so parked followers wake. A taker of a digest
        that is already in flight waits for the leader instead of
        re-ingesting (``coalesced`` metered, then the normal hit path);
        a leader that outlives ``wait_s`` promotes the waiter to
        CO-LEADER — duplicate compute, bit-identical result, never a
        deadlock.

        ``fault_key``: the armed ``ingest.cache`` site's event key —
        callers pass a schedule-independent request identity (the task
        generator passes the request position) so chaos runs replay
        exactly, the ``robust/faults`` contract; the global lookup
        counter is only the fallback for keyless unit-level use."""
        parked = False
        while True:
            with self._lock:
                entry = self._lru.get(digest)
                if entry is None:
                    ev = self._pending.get(digest)
                    if ev is None:
                        self._pending[digest] = threading.Event()
                        self.misses += 1
                        return None, "miss"
                else:
                    self._lookups += 1
                    key = (fault_key if fault_key is not None
                           else self._lookups)
            if entry is None:
                published = ev.wait(wait_s)
                with self._lock:
                    if published:
                        # counted as coalesced only if the re-lookup
                        # actually yields the entry — an abandon() wake
                        # re-leads as a fresh miss, not reuse
                        parked = True
                    elif self._pending.get(digest) is ev:
                        # leader presumed wedged: co-lead (its eventual
                        # put pops the same event, so stragglers wake)
                        self.misses += 1
                        return None, "miss"
                continue
            break
        if parked:
            with self._lock:
                self.coalesced += 1
        host = entry.host
        if self._integrity():
            try:
                self._faults.check("ingest.cache", key=key)
            except Exception:
                with self._lock:
                    self.fault_misses += 1
                return None, "fault_miss"
            host = self._faults.corrupt("ingest.cache", key, host)
            if (entry.checksum is not None
                    and _payload_checksum(host) != entry.checksum):
                with self._lock:
                    if self._lru.get(digest) is entry:
                        del self._lru[digest]
                        self._nbytes -= entry.nbytes
                    self.integrity_drops += 1
                return None, "integrity_drop"
        with self._lock:
            if digest in self._lru:
                self._lru.move_to_end(digest)
            self.hits += 1
        out = dict(host)
        # replay the original computation's stage stamps with the
        # `cached` flag; memo counters are ZEROED — they meter hunk
        # reuse inside whole-diff misses, and no memo work ran on this
        # hit (summing replayed counters would re-count the cold
        # computation once per repeat)
        stamps = dict(host.get("_ingest") or {}, cached=True)
        if "memo_hits" in stamps:
            stamps["memo_hits"] = stamps["memo_misses"] = 0
        out["_ingest"] = stamps
        return out, "hit"

    def put(self, digest: str, host: Dict) -> int:
        """Insert/refresh one assembled payload; returns LRU entries
        evicted to make room. The stored dict is a shallow copy taken
        BEFORE any fault-site corruption or digest stamping downstream
        of the cache, so a replay is always the clean computation.
        Publishing pops the digest's in-flight registration and wakes
        every parked follower (their re-lookup is the normal hit)."""
        entry = _Entry(host=dict(host),
                       checksum=(_payload_checksum(host)
                                 if self._integrity() else None),
                       nbytes=payload_nbytes(host))
        evicted = 0
        with self._lock:
            old = self._lru.get(digest)
            if old is not None:
                self._nbytes -= old.nbytes
            self._lru[digest] = entry
            self._lru.move_to_end(digest)
            self._nbytes += entry.nbytes
            while (self.capacity and len(self._lru) > self.capacity) or (
                    self.max_bytes and self._nbytes > self.max_bytes
                    and len(self._lru) > 1):
                _d, e = self._lru.popitem(last=False)
                self._nbytes -= e.nbytes
                evicted += 1
            self.evictions += evicted
            ev = self._pending.pop(digest, None)
        if ev is not None:
            ev.set()
        return evicted

    def abandon(self, digest: str) -> None:
        """Leader's failure path: wake parked followers WITHOUT an
        entry — the first to re-look-up claims leadership and
        re-ingests (a failing request never wedges its duplicates)."""
        with self._lock:
            ev = self._pending.pop(digest, None)
        if ev is not None:
            ev.set()

    def clear(self) -> None:
        """Reset to a fresh cache: entries AND meters — the bench's
        warm-then-measure discipline clears between the untimed warm
        pass and the timed mix, and recorded counters must describe the
        timed mix only."""
        with self._lock:
            self._lru.clear()
            self._nbytes = 0
            self._lookups = 0
            self.hits = self.misses = self.coalesced = 0
            self.fault_misses = self.integrity_drops = self.evictions = 0

    def summary(self) -> Dict[str, int]:
        with self._lock:
            total = self.hits + self.misses + self.fault_misses \
                + self.integrity_drops
            return {
                "entries": len(self._lru),
                "nbytes": self._nbytes,
                "hits": self.hits,
                "misses": self.misses,
                "coalesced": self.coalesced,
                "fault_misses": self.fault_misses,
                "integrity_drops": self.integrity_drops,
                "evictions": self.evictions,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
            }


class LexMemo:
    """Persistent lexer state: one bounded text -> token-tuple map over
    the native lexer, shared by every ingest worker in the process.
    Context lines repeat across hunks, requests, and CI re-runs; each
    distinct line lexes exactly once per process."""

    def __init__(self, entries: int = LEX_MEMO_ENTRIES):
        self.capacity = max(1, int(entries))
        self._lru: "collections.OrderedDict[str, Optional[tuple]]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self._lock, (self._lru,) = _arm_thread_guard(
            self, self._lock, [(self._lru, "_lru")])

    def __call__(self, text: str):
        with self._lock:
            if text in self._lru:
                self._lru.move_to_end(text)
                self.hits += 1
                cached = self._lru[text]
                return None if cached is None else list(cached)
        from fira_tpu.preprocess import astdiff_binding as astdiff

        toks = astdiff.tokenize(text)
        with self._lock:
            self.misses += 1
            self._lru[text] = None if toks is None else tuple(toks)
            while len(self._lru) > self.capacity:
                self._lru.popitem(last=False)
        return toks


class HunkMemo:
    """Hunk-level AST memoization: per-chunk extraction results keyed by
    a keyed digest of (chunk type, tokens). The extraction is a pure
    function of the typed chunk content on the index-free ingest path,
    and ``extract_commit`` only READS the cached ChunkGraph while
    rebasing into commit-global coordinates — the merge re-runs
    deterministically per request, the parse/diff does not.

    Compute runs OUTSIDE the lock (a native parse must not serialize
    the worker pool); a duplicate-compute race inserts equal values, so
    whichever lands last is the same value.
    """

    def __init__(self, entries: int = HUNK_MEMO_ENTRIES):
        self.capacity = max(1, int(entries))
        self._lru: "collections.OrderedDict[str, object]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self._lock, (self._lru,) = _arm_thread_guard(
            self, self._lock, [(self._lru, "_lru")])

    @staticmethod
    def _key(chunk, typ: int) -> str:
        h = hashlib.blake2b(key=_DIGEST_KEY, digest_size=16)
        h.update(str(typ).encode())
        if typ == 100:
            old, new = chunk
            h.update("\x00".join(old).encode())
            h.update(b"\x01")
            h.update("\x00".join(new).encode())
        else:
            h.update("\x00".join(chunk).encode())
        return h.hexdigest()

    def chunk_graph(self, chunk, typ: int, commit_index=None):
        """The memoized twin of the per-chunk extraction dispatch in
        ``preprocess.extract.extract_commit``. ``commit_index`` joins
        the key when set (the corpus-replication hack makes extraction
        index-dependent; the ingest path always passes None)."""
        return self.get_or_compute(chunk, typ, commit_index)[0]

    def get_or_compute(self, chunk, typ: int, commit_index=None):
        """(graph, hit) — the hit flag is per CALL, so a per-request
        tally (:class:`MemoTally`) stays exact when concurrent requests
        share this memo (global counter deltas would cross-count the
        other request's activity)."""
        key = self._key(chunk, typ)
        if commit_index is not None:
            key = f"{key}:{commit_index}"
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)
                self.hits += 1
                return self._lru[key], True
        from fira_tpu.preprocess import extract

        if typ == 100:
            g = extract.update_chunk_edges(chunk[0], chunk[1],
                                           commit_index=commit_index)
        else:
            g = extract.normal_chunk_edges(list(chunk),
                                           commit_index=commit_index)
        with self._lock:
            self.misses += 1
            self._lru[key] = g
            while len(self._lru) > self.capacity:
                self._lru.popitem(last=False)
        return g, False


class MemoTally:
    """Per-request view of a shared :class:`HunkMemo`: delegates
    ``chunk_graph`` (the interface ``extract.extract_commit(memo=)``
    reads) and counts THIS request's hits/misses locally — the
    request-scoped meter the ``_ingest`` stamps record."""

    __slots__ = ("_memo", "hits", "misses")

    def __init__(self, memo: HunkMemo):
        self._memo = memo
        self.hits = 0
        self.misses = 0

    def chunk_graph(self, chunk, typ: int, commit_index=None):
        g, hit = self._memo.get_or_compute(chunk, typ, commit_index)
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        return g


# --------------------------------------------------------------------------
# the parse-stage executor (cfg.ingest_exec)
# --------------------------------------------------------------------------

# process-local state of a spawned pool worker (set by the pool
# initializer; spawned processes share no memory with the parent, so
# each keeps its own hunk/lex memo — reporting hit DELTAS back with
# every result — and, for whole-request offload, its own copy of the
# frozen vocabs + config + bucket table shipped ONCE at spawn)
_PROC_MEMO: Optional[HunkMemo] = None
_PROC_LEX: Optional[LexMemo] = None
_PROC_CONTEXT: Optional[tuple] = None   # (word_vocab, ast_change_vocab,
#                                          cfg, table)
_PROC_EXEC: Optional["IngestExecutor"] = None  # child-local thread-mode
#                                          executor carrying _PROC_MEMO


def _proc_init(context=None) -> None:
    global _PROC_MEMO, _PROC_LEX, _PROC_CONTEXT, _PROC_EXEC
    # memos arm only when the fast path's cache knob is on (context
    # carries the cfg): with ingest_cache off, process mode must stay
    # the pristine comparator — fan-out without memoization. Stage-only
    # mode (no context) keeps its memo: the executor exists to carry it.
    arm = context is None or context[2].ingest_cache
    _PROC_MEMO = HunkMemo() if arm else None
    _PROC_LEX = LexMemo() if arm else None
    _PROC_CONTEXT = context
    _PROC_EXEC = (IngestExecutor("thread", memo=_PROC_MEMO)
                  if _PROC_MEMO is not None else None)


def _parse_with_memo(req, cfg, truncate, memo: Optional[HunkMemo]):
    """The ONE parse-stage body both exec modes run: FSM + AST
    extraction + truncation policy, with this request's memo reuse
    counted through a request-scoped :class:`MemoTally` (exact under
    concurrent requests sharing one memo). Returns (record, info,
    memo_hits, memo_misses)."""
    from fira_tpu.ingest.service import ingest_record

    tally = MemoTally(memo) if memo is not None else None
    record, info = ingest_record(req, cfg, truncate=truncate, memo=tally)
    return (record, info,
            tally.hits if tally is not None else 0,
            tally.misses if tally is not None else 0)


def _proc_parse(req, cfg, truncate):
    """Pool-worker entry, parse stage only: FSM + AST extraction +
    truncation policy on one parsed request. Returns (record, info,
    memo_hits, memo_misses); policy rejections (IngestError) propagate
    to the submitting worker exactly like the inline path."""
    return _parse_with_memo(req, cfg, truncate, _PROC_MEMO)


def _proc_ingest(text: str):
    """Pool-worker entry, WHOLE-request offload: raw diff text ->
    assembled single-row wire payload, entirely in the child (lex with
    the child's persistent LexMemo, AST stage with its HunkMemo,
    assemble against the spawn-shipped vocabs/config/table). The parent
    worker thread only pickles a string out and numpy arrays back —
    near-zero parent GIL time per request, which is what lets
    ``ingest_workers`` actually scale past one core. DiffParseError /
    IngestError propagate to the submitting worker unchanged."""
    from fira_tpu.ingest.service import ingest_request

    wv, acv, cfg, table = _PROC_CONTEXT
    return ingest_request(text, wv, acv, cfg, table=table, lex=_PROC_LEX,
                          executor=_PROC_EXEC)


class IngestExecutor:
    """Runs the ingest pipeline's heavy stages per ``cfg.ingest_exec``:
    inline on the calling worker thread ("thread"), or on a spawned
    process pool ("process") whose size follows the ingest worker
    count. With ``context=(word_vocab, ast_change_vocab, cfg, table)``
    the process pool does WHOLE-request offload (:meth:`ingest` — the
    serve path), shipping the frozen context once at spawn; without it
    only the parse stage ships (:meth:`parse`). Close() joins the pool;
    the context manager calls it."""

    def __init__(self, mode: str = "thread", *, workers: int = 2,
                 memo: Optional[HunkMemo] = None, context=None):
        if mode not in EXEC_MODES:
            raise ValueError(f"ingest_exec {mode!r} not in {EXEC_MODES}")
        self.mode = mode
        self._memo = memo
        self._pool = None
        self._has_context = context is not None
        # resource-lifecycle sanitizer: armed, the process pool is
        # ledgered at construction and retired at close(), so a serve
        # path that drops the executor without shutdown is named at
        # teardown (analysis.sanitizer.LeakGuard; static twin: RES-LEAK)
        from fira_tpu.analysis.sanitizer import leak_guard

        self._leaks = leak_guard()
        if mode == "process":
            import concurrent.futures
            import multiprocessing

            # spawn, not fork: the parent runs live feeder/engine threads
            # and a forked child inheriting their lock state can deadlock;
            # a spawned worker imports only the host-side ingest modules
            # (this module pulls no JAX)
            ctx = multiprocessing.get_context("spawn")
            self._pool = concurrent.futures.ProcessPoolExecutor(
                max_workers=max(1, int(workers)), mp_context=ctx,
                initializer=_proc_init, initargs=(context,))
            if self._leaks is not None:
                self._leaks.note_acquire(
                    "pool", f"IngestExecutor@{id(self):x}",
                    what=f"process pool ({max(1, int(workers))} workers)")

    @property
    def offloads_requests(self) -> bool:
        """True when :meth:`ingest` ships whole requests to the pool —
        the serve path's process mode."""
        return self._pool is not None and self._has_context

    def ingest(self, text: str):
        """Whole-request offload: raw diff text -> assembled payload in
        a pool worker. Only valid when constructed with ``context``."""
        if not self.offloads_requests:
            raise RuntimeError(
                "IngestExecutor.ingest needs process mode with context=")
        # .result() parks this worker thread with the GIL released;
        # sibling workers keep shipping/serving other requests
        return self._pool.submit(_proc_ingest, text).result()

    def parse(self, req, cfg, truncate):
        """(record, info, memo_hits, memo_misses) for one parsed
        request — the bit-exact stage contract both modes meet."""
        if self._pool is not None:
            return self._pool.submit(_proc_parse, req, cfg,
                                     truncate).result()
        return _parse_with_memo(req, cfg, truncate, self._memo)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
            if self._leaks is not None:
                self._leaks.note_release("pool",
                                         f"IngestExecutor@{id(self):x}")

    def __enter__(self) -> "IngestExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
