"""FIRA-TPU: a TPU-native framework for fine-grained graph-based commit
message generation.

A ground-up JAX/Flax/XLA rebuild with the capabilities of the FIRA
reference codebase (ICSE 2022, DJjjjhao/FIRA-ICSE): diff-graph encoding
with a GCN stack, a Transformer decoder with a dual copy mechanism, beam
search decoding, the full preprocessing pipeline (hunk FSM, Java AST
parse + tree diff), and the evaluation metric suite — redesigned for TPU
hardware (SPMD over device meshes, fixed-shape jitted programs, MXU-sized
matmuls, COO edge lists instead of host-side dense adjacencies).

Package map (component numbers refer to SURVEY.md §2):
  fira_tpu.config       — typed config system, named configs (C1)
  fira_tpu.data         — vocab, corpus schema, graph assembly, batching (C2)
  fira_tpu.model        — Flax encoder/decoder/copy head (C3-C6)
  fira_tpu.train        — jitted train step, mesh parallelism, checkpoints (C1, C20)
  fira_tpu.decode       — greedy dev decode + jitted beam search (C7)
  fira_tpu.eval         — B-Norm BLEU, Penalty-BLEU, ROUGE-L, METEOR (C14-C16)
  fira_tpu.preprocess   — hunk FSM, Java lexer, shard pipeline, astdiff (C8-C13)
  fira_tpu.parallel     — mesh/sharding helpers (C20-C21 TPU equivalents)
"""

__version__ = "0.1.0"
