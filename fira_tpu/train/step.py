"""Jitted train / dev steps.

One ``jax.jit`` program per step kind, compiled once over fixed shapes and
sharded over the (data, model) mesh via NamedShardings — the TPU equivalent
of the reference's per-batch DataParallel scatter/forward/gather/backward
(/root/reference/run_model.py:102-109). Buffers are donated so the optimizer
update happens in place in HBM.

Loss semantics match the reference exactly: the model returns
(nll_sum, token_count) and the step normalizes sum/count over the GLOBAL
batch (run_model.py:104-105 normalizes after DataParallel's gather — same
thing).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fira_tpu.config import FiraConfig
from fira_tpu.model.model import FiraModel
from fira_tpu.parallel import mesh as pmesh
from fira_tpu.train.state import TrainState, make_optimizer, prng_impl_name


def loss_fn(model: FiraModel, params, batch, dropout_rng) -> jnp.ndarray:
    nll_sum, count = model.apply(
        {"params": params}, batch, deterministic=False,
        rngs={"dropout": dropout_rng},
    )
    return nll_sum / jnp.maximum(count, 1)


def make_train_step(model: FiraModel, cfg: FiraConfig
                    ) -> Callable[[TrainState, Dict[str, Any]],
                                  Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    optimizer = make_optimizer(cfg)

    rng_impl = prng_impl_name(cfg.rng_impl)

    def train_step(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        # state.rng is raw key data (checkpoint-friendly); re-wrap with the
        # configured generator (threefry default / TPU-fast rbg)
        key = jax.random.wrap_key_data(state.rng, impl=rng_impl)
        step_rng, next_key = jax.random.split(key)
        next_rng = jax.random.key_data(next_key)
        loss, grads = jax.value_and_grad(
            partial(loss_fn, model)
        )(state.params, batch, step_rng)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = jax.tree_util.tree_map(
            lambda p, u: (p + u).astype(p.dtype), state.params, updates
        )
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state,
            rng=next_rng,
        )
        return new_state, {"loss": loss}

    return train_step


def make_multi_step(model: FiraModel, cfg: FiraConfig
                    ) -> Callable[[TrainState, Dict[str, Any]],
                                  Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """K train steps per dispatch: ``lax.scan`` over batches stacked on a
    leading axis — the TPU device-loop pattern.

    One host->device dispatch then runs K full steps on-chip, which bounds
    per-step host/dispatch overhead at 1/K and makes timing trustworthy on
    backends where ``block_until_ready`` acks before remote execution
    finishes (the bench rig's tunnel does exactly that —
    scripts/tpu_sync_check.py; the scan path confirmed the honest per-step
    time, 110 vs 107 ms, i.e. this workload is compute- not
    dispatch-bound). The reference's loop pays per-batch Python +
    DataParallel scatter/gather overhead every step (run_model.py:94-109);
    here the scan body is the SAME train_step the per-step path compiles,
    so semantics are identical (tests pin loss equality step-for-step).

    Returns ``(final_state, {"loss": (K,) losses})``; dev-gate cadence and
    checkpointing happen at scan-group boundaries in the caller.
    """
    step = make_train_step(model, cfg)

    def multi_step(state: TrainState, stacked_batch) -> Tuple[TrainState, Dict]:
        def body(s, b):
            s2, metrics = step(s, b)
            return s2, metrics["loss"]

        final, losses = jax.lax.scan(body, state, stacked_batch)
        return final, {"loss": losses}

    return multi_step


def stack_batches(batches) -> Dict[str, Any]:
    """Stack host batches along a new leading axis for make_multi_step /
    make_accum_step. The batches must share one geometry — under buckets
    the grouped scheduler guarantees bucket-homogeneous groups, and its
    ``data.grouping.stack_group`` owns the accum-tail variant that pads
    short groups with all-invalid micro-batches."""
    import numpy as np

    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def make_accum_step(model: FiraModel, cfg: FiraConfig
                    ) -> Callable[[TrainState, Dict[str, Any]],
                                  Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """ONE optimizer step from A accumulated micro-batches (leading axis A).

    Reproduces the reference's multi-GPU global-batch dynamics on a single
    chip: DataParallel splits batch 680 over 4 GPUs and normalizes the
    gathered (nll_sum, token_count) over the GLOBAL batch
    (run_model.py:102-105). Counts carry no gradient, so
    d[(Σ nll_i)/(Σ cnt_i)]/dθ = (Σ d nll_i)/(Σ cnt_i): accumulate raw
    nll-gradients and counts over a lax.scan, divide once, then update —
    bit-equal (up to f32 reassociation) to stepping one A·B batch, which
    the tests pin in deterministic mode.

    Each micro-batch draws its own dropout key (folded from the state key),
    mirroring the distinct per-GPU streams of the reference.
    """
    optimizer = make_optimizer(cfg)
    rng_impl = prng_impl_name(cfg.rng_impl)

    def raw_nll(params, batch, rng):
        nll_sum, count = model.apply(
            {"params": params}, batch, deterministic=False,
            rngs={"dropout": rng},
        )
        return nll_sum, count

    def accum_step(state: TrainState, stacked_batch) -> Tuple[TrainState, Dict]:
        key = jax.random.wrap_key_data(state.rng, impl=rng_impl)
        step_key, next_key = jax.random.split(key)
        next_rng = jax.random.key_data(next_key)

        zero_g = jax.tree_util.tree_map(jnp.zeros_like, state.params)

        def body(carry, mb):
            g_acc, nll_acc, cnt_acc, i = carry
            sub = jax.random.fold_in(step_key, i)
            (nll, cnt), g = jax.value_and_grad(raw_nll, has_aux=True)(
                state.params, mb, sub)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            return (g_acc, nll_acc + nll, cnt_acc + cnt, i + 1), None

        (g_sum, nll_sum, cnt_sum, _), _ = jax.lax.scan(
            body, (zero_g, jnp.zeros(()), jnp.zeros(()), 0), stacked_batch)

        denom = jnp.maximum(cnt_sum, 1)
        grads = jax.tree_util.tree_map(lambda g: g / denom, g_sum)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = jax.tree_util.tree_map(
            lambda p, u: (p + u).astype(p.dtype), state.params, updates)
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state,
            rng=next_rng,
        )
        return new_state, {"loss": nll_sum / denom}

    return accum_step


def make_dev_step(model: FiraModel) -> Callable:
    """Teacher-forced greedy ids (Model.py:86 'dev' stage)."""

    def dev_step(params, batch) -> jnp.ndarray:
        return model.apply({"params": params}, batch,
                           method=FiraModel.dev_predict)

    return dev_step


def state_shardings(state: TrainState, mesh: Mesh) -> TrainState:
    """NamedSharding pytree for a TrainState: params (and their Adam
    moments) by the TP rules, scalars/PRNG replicated."""
    import optax

    params_sh = pmesh.params_shardings(state.params, mesh)

    # Adam moments (mu/nu) live with their params — same mesh layout — so the
    # optimizer update is fully local; counts/scalars are replicated.
    def opt_component_shardings(o):
        if isinstance(o, optax.ScaleByAdamState):
            return optax.ScaleByAdamState(
                count=pmesh.replicated(mesh), mu=params_sh, nu=params_sh
            )
        return jax.tree_util.tree_map(lambda _: pmesh.replicated(mesh), o)

    return TrainState(
        step=pmesh.replicated(mesh),
        params=params_sh,
        opt_state=tuple(opt_component_shardings(o) for o in state.opt_state),
        rng=pmesh.replicated(mesh),
    )


def jit_train_step(model: FiraModel, cfg: FiraConfig, mesh: Optional[Mesh],
                   state: TrainState, sample_batch) -> Callable:
    """Compile the train step; with a mesh, pin params/opt-state/batch
    shardings so XLA lays out DP gradient psums + TP all-reduces over ICI."""
    step = make_train_step(model, cfg)
    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))

    state_sh = state_shardings(state, mesh)
    batch_sh = pmesh.batch_shardings(sample_batch, mesh)
    return jax.jit(
        step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, pmesh.replicated(mesh)),
        donate_argnums=(0,),
    )


def jit_multi_step(model: FiraModel, cfg: FiraConfig, mesh: Optional[Mesh],
                   state: TrainState, stacked_sample) -> Callable:
    """Compile the K-step device loop; with a mesh, batches shard along
    their SECOND axis (leading axis is the scan/step axis).

    Per-BucketGeom specialization falls out of jit's shape cache: the ONE
    returned callable compiles one program per stacked input shape, i.e.
    one per (geometry, K) family member — NamedShardings constrain layout,
    not shape, so the mesh path needs no per-geometry re-wrapping. The
    train loop pre-warms every member on a throwaway state
    (train/loop.py), so the epoch loop never compiles."""
    return _jit_stacked(make_multi_step(model, cfg), mesh, state,
                        stacked_sample)


def jit_accum_step(model: FiraModel, cfg: FiraConfig, mesh: Optional[Mesh],
                   state: TrainState, stacked_sample) -> Callable:
    """Compile the A-micro-batch accumulation step (same stacked layout as
    the device loop: leading axis = micro-batch, second axis = batch/data;
    same per-(geometry, A) shape-cache specialization as jit_multi_step).
    Bucketed accum tails keep the stacked shape — the scheduler pads short
    groups with all-invalid micro-batches (data/grouping.py) — so A is the
    only leading dim ever compiled."""
    return _jit_stacked(make_accum_step(model, cfg), mesh, state,
                        stacked_sample)


def _jit_stacked(fn: Callable, mesh: Optional[Mesh], state: TrainState,
                 stacked_sample) -> Callable:
    if mesh is None:
        return jax.jit(fn, donate_argnums=(0,))
    state_sh = state_shardings(state, mesh)
    stacked_sh = pmesh.stacked_batch_shardings(stacked_sample, mesh)
    return jax.jit(
        fn,
        in_shardings=(state_sh, stacked_sh),
        out_shardings=(state_sh, pmesh.replicated(mesh)),
        donate_argnums=(0,),
    )
