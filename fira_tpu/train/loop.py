"""Training driver: epochs, dev gating, checkpointing, throughput metering.

Rebuilds the reference's train/dev orchestration
(/root/reference/run_model.py:83-184) TPU-first: a SMALL FIXED FAMILY of
compiled programs runs for the whole session — per-step/grouped train
steps x bucket geometries x dev (data/grouping.py, data/buckets.py), all
pre-warmed at startup when bucketed; batches stream through fixed shapes;
throughput is reported as commits/sec/chip (the repo's metric of record,
BASELINE.md).

Reference semantics kept:
- dev-gate cadence ``epoch >= dev_start_epoch and batch_idx % dev_every == 0``
  (run_model.py:89);
- gating metric is NLTK method2 sentence BLEU on teacher-forced greedy
  output (run_model.py:171), NOT the reported B-Norm number;
- best checkpoint saved on strict improvement (run_model.py:94-96), plus an
  append-only train_process log line per gate decision (run_model.py:92).

Added beyond the reference: full train-state checkpointing with resume
(optimizer moments + PRNG + gating bookkeeping survive preemption).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, List, Optional

import jax
import numpy as np

from fira_tpu.analysis.sanitizer import program_label as sanitizer_label
from fira_tpu.config import FiraConfig
from fira_tpu.data import buckets as buckets_lib
from fira_tpu.data import grouping
from fira_tpu.data.batching import epoch_index_chunks, make_batch
from fira_tpu.data.dataset import FiraDataset
from fira_tpu.data.feeder import Feeder, assembly_tasks
from fira_tpu.decode.text import cook_prediction, deanonymize, reference_words
from fira_tpu.eval.dev_bleu import nltk_sentence_bleu
from fira_tpu.model.model import FiraModel
from fira_tpu.parallel import mesh as pmesh
from fira_tpu.robust.watchdog import WatchdogTimeout, run_with_watchdog
from fira_tpu.train import step as step_lib
from fira_tpu.train.state import CheckpointManager, TrainState, init_state
from fira_tpu.utils import profiling


@dataclasses.dataclass
class TrainLog:
    """Per-gate and per-interval console/file logging (run_model.py:92,114)."""

    out_dir: str

    def __post_init__(self):
        os.makedirs(self.out_dir, exist_ok=True)

    def gate(self, epoch: int, batch: int, bleu: float, better: bool) -> None:
        line = (f"epoch: {epoch} batch: {batch} dev bleu: {bleu} "
                f"is better: {better}\n")
        with open(os.path.join(self.out_dir, "train_process"), "a") as f:
            f.write(line)

    def dev_output(self, text: str) -> None:
        with open(os.path.join(self.out_dir, "dev_output"), "w") as f:
            f.write(text)

    def console(self, msg: str) -> None:
        print(msg, flush=True)


def _eval_tasks(data, cfg: FiraConfig, plan=None):
    """Assembly tasks for the dev pass: the single-geometry sequential
    chunks when buckets are off (the byte-identical legacy stream), the
    bucketed sort-by-length plan when on. Dev packs with the DECODE bucket
    table — tar_len pinned full, admissibility on (nodes, edges) only:
    the reference's gating metric scores teacher-forced predictions at
    EVERY tar position (even pad-conditioned ones, run_model.py:118-184),
    so truncating tar would change the metric; with tar full the per-line
    dev output is bit-identical to the unbucketed pass (pinned by
    tests/test_buckets.py). ``plan``: a precomputed packed plan for the
    split — the shuffle=False plan never changes, so train() computes it
    once instead of re-deriving extents/assignment at every dev gate."""
    if cfg.buckets:
        if plan is None:
            # tar stays PINNED FULL here even under cfg.decode_tar_buckets
            # (an engine-only generation knob): the teacher-forced gating
            # metric scores every tar position, and use_msg=False packing
            # would otherwise seat long-message samples in short-tar
            # buckets and trip make_batch's admissibility backstop mid-run
            dev_cfg = cfg.replace(decode_tar_buckets=False)
            plan = buckets_lib.packed_plan(data, cfg,
                                           batch_size=cfg.test_batch_size,
                                           table=buckets_lib.decode_table(
                                               dev_cfg),
                                           use_msg=False)
        return buckets_lib.bucketed_assembly_tasks(
            data, plan, cfg, batch_size=cfg.test_batch_size)
    chunks = epoch_index_chunks(len(data), cfg, batch_size=cfg.test_batch_size)
    return assembly_tasks(data, chunks, cfg, batch_size=cfg.test_batch_size)


def run_dev(dev_step, params, dataset: FiraDataset, cfg: FiraConfig,
            var_maps: Optional[List[Dict[str, str]]] = None,
            split: str = "valid", guard=None,
            eval_plan=None, cancel=None) -> tuple[float, str]:
    """Greedy teacher-forced validation (run_model.py:118-184). Returns
    (mean sentence BLEU over the split, dev_output text — always in split
    order, even when the bucket packer reordered the batch stream).

    ``cancel``: zero-arg callable polled per eval batch — the dispatch
    watchdog's cooperative kill switch (docs/FAULTS.md): a gate the
    watchdog abandoned must STOP dispatching eval programs and stepping
    the shared compile guard instead of racing the resumed training
    loop; raising here closes the eval feeder via the context manager."""
    data = dataset.splits[split]
    vocab = dataset.word_vocab
    indices = dataset.split_indices[split]
    total_bleu = 0.0
    out_lines: List[tuple] = []  # (split position, line)
    cursor = 0
    with Feeder(_eval_tasks(data, cfg, plan=eval_plan),
                num_workers=cfg.feeder_workers,
                depth=cfg.feeder_depth) as feed:
        for item in feed:
            if cancel is not None and cancel():
                raise WatchdogTimeout(
                    "dev gate abandoned by the dispatch watchdog")
            batch = item.host  # numpy fields for host-side text cooking
            # firacheck: allow[HOST-SYNC] dev gate IS a designated sync boundary: teacher-forced ids must reach the host for BLEU scoring (README Design notes)
            ids = np.asarray(jax.device_get(dev_step(params, item.device)))
            valid = batch["valid"]  # host-side numpy batch field, no device trip
            positions = batch.get("_positions")  # bucketed stream only
            if guard is not None:
                guard.step(sanitizer_label("dev_step", batch.get("_tag")))
            for i in range(ids.shape[0]):
                if not valid[i]:
                    continue
                pos = cursor if positions is None else int(positions[i])  # firacheck: allow[HOST-SYNC] _positions is a host-only numpy field (feeder strips it from the wire); no device value exists here
                hyp = cook_prediction(
                    ids[i].tolist(), batch["diff"][i], batch["sub_token"][i],
                    vocab, cfg,
                )
                ref = reference_words(batch["msg"][i], vocab)
                b = nltk_sentence_bleu([ref], hyp)
                total_bleu += b
                var_map = (var_maps[indices[pos]]
                           if var_maps is not None else None)
                out_lines.append(
                    (pos, " ".join(deanonymize(hyp, var_map)) + f",{b}"))
                cursor += 1
    out_lines.sort(key=lambda r: r[0])
    return (total_bleu / max(len(data), 1),
            "\n".join(line for _, line in out_lines) + "\n")


def _materialize(x) -> None:
    """Honest device sync: copy computed data to host. block_until_ready is
    NOT a sync on some remote PJRT backends — it acks before execution
    finishes (scripts/tpu_sync_check.py), which would close throughput-meter
    intervals early and inflate commits/sec up to 20x."""
    # firacheck: allow[HOST-SYNC] THE designated sync helper: every hot-loop sync funnels through here so the boundaries stay enumerable (called only at meter/log/epoch edges)
    np.asarray(jax.device_get(x))


@dataclasses.dataclass
class TrainResult:
    state: TrainState
    best_bleu: float
    epochs_run: int
    commits_per_sec_per_chip: float
    # share of measured train wall clock the host spent blocked on the
    # input feed (profiling.Meter; docs/PIPELINE.md) — the denominator the
    # next perf round divides host-pipeline work against
    feed_stall_frac: float = 0.0
    # aggregated data/feeder.Feeder stats over the run: batches,
    # feed_stall_s, queue_depth_mean/min, num_workers, depth
    feeder: Dict[str, float] = dataclasses.field(default_factory=dict)
    # loud-but-nonfatal run conditions (also printed to the console):
    # fused_steps not dividing dev_every_batches (gate-staleness footgun,
    # config.py), profiling annotations spanning K-step grouped dispatches —
    # anything a reader of this run's numbers must know to read them right
    warnings: List[str] = dataclasses.field(default_factory=list)


def train(dataset: FiraDataset, cfg: Optional[FiraConfig] = None, *,
          mesh=None,
          out_dir: str = "OUTPUT",
          ckpt_dir: Optional[str] = None,
          epochs: Optional[int] = None,
          var_maps: Optional[List[Dict[str, str]]] = None,
          resume: bool = True,
          profile_dir: Optional[str] = None,
          profile_steps: int = 10,
          guard=None,
          dtype=None) -> TrainResult:
    """Full training run. ``mesh=None`` => single-chip jit; otherwise the
    (data, model) mesh from parallel.mesh with XLA-inserted collectives.

    ``guard``: an armed analysis.sanitizer.CompileGuard — each dispatch
    site labels its program and a post-warmup step that triggers a new XLA
    compilation raises RetraceError. The CLI arms process-wide via
    ``--sanitize`` (sanitizer.arm); library callers wrap the call in
    ``with sanitizer.sanitize() as guard:`` so global config is restored.
    """
    import jax.numpy as jnp

    cfg = cfg or dataset.cfg  # dataset.cfg has vocab sizes filled in
    if mesh is not None:
        # fail BEFORE any compile: a batch axis that doesn't divide the
        # data mesh axis otherwise dies mid-epoch in an XLA sharding error
        # (the CLI runs the same check at parse time and exits 2)
        errs = pmesh.divisibility_errors(cfg,
                                         mesh.shape[pmesh.DATA_AXIS])
        if errs:
            raise ValueError("mesh divisibility: " + "; ".join(errs))
    log = TrainLog(out_dir)
    model = FiraModel(cfg, dtype=dtype or jnp.dtype(cfg.compute_dtype))

    train_split = dataset.splits["train"]
    sample = make_batch(train_split, np.arange(min(cfg.batch_size,
                                                   len(train_split))),
                        cfg, batch_size=cfg.batch_size)
    state = init_state(model, cfg, sample)
    if mesh is not None:
        state = state.replace(
            params=pmesh.shard_params(state.params, mesh))
    train_step = step_lib.jit_train_step(model, cfg, mesh, state, sample)
    dev_step = jax.jit(step_lib.make_dev_step(model))

    ckpt = CheckpointManager(ckpt_dir or os.path.join(out_dir, "ckpt"))
    best_bleu, start_epoch = 0.0, 0
    if resume and ckpt.has(CheckpointManager.LATEST):
        state, meta = ckpt.restore_latest(state, expect_rng_impl=cfg.rng_impl)
        best_bleu, start_epoch = meta["best_bleu"], meta["epoch"]
        log.console(f"resumed at epoch {start_epoch}, best dev bleu {best_bleu:.4f}")

    n_epochs = epochs if epochs is not None else cfg.epochs
    n_chips = 1 if mesh is None else mesh.devices.size
    # The host only syncs with the device at logging/dev boundaries — steps
    # stay asynchronously dispatched in between (the per-step .item() sync is
    # one of the reference's throughput sins to avoid). Meter(warmup=1) drops
    # the interval containing the compile step.
    meter = profiling.Meter(warmup=1)
    pending_commits = 0
    pending_stall = 0.0
    meter.start()

    def sync_tick():
        """Record the interval since the last sync, attributing the commits
        dispatched in it and the feed-stall time they carried; an empty
        interval just restarts the clock."""
        nonlocal pending_commits, pending_stall
        if pending_commits:
            meter.tick(pending_commits, stall_s=pending_stall)
        else:
            # an empty interval is discarded wholesale — drop its stall too
            # (e.g. the epoch's pipeline-fill stall at a start-of-epoch dev
            # gate), or it would be mis-attributed to the NEXT interval and
            # overstate feed_stall_frac
            meter.start()
        pending_commits = 0
        pending_stall = 0.0

    # jax.profiler trace of a steady-state step window (skips the compile
    # step); viewable in TensorBoard / xprof.
    profile_window = (range(2, 2 + profile_steps) if profile_dir else range(0))
    profiling_active = False
    profile_done = False
    global_step = 0

    # Double-buffered device feed: batch i+1 transfers while step i runs.
    # With a mesh, batches land pre-sharded along the data axis — the
    # shared shape-dispatched callable (parallel.mesh.feed_shardings)
    # picks stacked vs per-batch shardings per item, so mixed-geometry
    # bucketed streams and K-groups both ship correctly sharded from the
    # feeder's workers.
    batch_sharding = pmesh.feed_shardings(mesh)

    # Grouped device programs — mutually exclusive:
    #   fused_steps K   > 1: K-groups run as K steps in ONE lax.scan dispatch
    #   accum_steps A   > 1: A-groups accumulate into ONE optimizer step
    #                        normalized over the global (sum, count) — the
    #                        reference's DataParallel batch-680 dynamics
    # The epoch tail (< group size) uses the per-step program under fused
    # and pads to the stacked shape with all-invalid micro-batches under
    # accum. Both COMPOSE with cfg.buckets: the grouped scheduler
    # (data/grouping.py) packs bucket-homogeneous groups over the same
    # epoch permutation, so each dispatch is one member of the
    # (geometry x entrypoint x group-size) program family.
    fused = max(1, int(cfg.fused_steps))
    accum = max(1, int(cfg.accum_steps))
    if fused > 1 and accum > 1:
        raise ValueError("fused_steps and accum_steps are mutually "
                         "exclusive (one scans steps, one accumulates "
                         "gradients); set at most one > 1")
    warnings: List[str] = []
    if fused > 1 and cfg.dev_every_batches % fused:
        # the gate-staleness footgun documented at cfg.fused_steps: gates
        # due inside a K-group collapse to one, fired BEFORE the group with
        # up-to-K-1-steps-stale params — loud here, recorded in the result
        w = (f"fused_steps={fused} does not divide dev_every_batches="
             f"{cfg.dev_every_batches}: dev gates due inside a fused group "
             f"collapse to one gate fired before the group (params up to "
             f"{fused - 1} steps stale); pick K dividing the cadence "
             f"(config.py fused_steps note)")
        log.console(f"WARNING: {w}")
        warnings.append(w)
    if (fused > 1 or accum > 1) and profile_dir:
        # the REAL grouped program is profiled (not a per-step downgrade —
        # profiled numbers must be production-path numbers); each trace
        # annotation then spans one whole K-step dispatch
        w = (f"profiling the grouped program: each step annotation spans "
             f"one {'fused' if fused > 1 else 'accum'} dispatch of "
             f"{fused if fused > 1 else accum} stacked batches")
        log.console(w)
        warnings.append(w)
    group_size = fused if fused > 1 else accum
    grouped_step = None
    if group_size > 1:
        stacked_sample = step_lib.stack_batches([sample] * group_size)
        maker = (step_lib.jit_multi_step if fused > 1
                 else step_lib.jit_accum_step)
        grouped_step = maker(model, cfg, mesh, state, stacked_sample)

    # --- bucketed geometry family (data/buckets.py; docs/BUCKETING.md) ---
    # Table + per-sample assignment computed ONCE for the train split; the
    # whole (geometry x entrypoint x group-size) program family is
    # pre-warmed here — each member compiles against a throwaway state copy
    # and an all-pad batch (zero training effect), so the epoch loop never
    # compiles again. The guard then learns the closed family: every label
    # gets its one warmup dispatch, and any label outside the declared set
    # raises. Under fused the per-step program is warmed too (epoch tails
    # dispatch it); under accum it never runs (tails pad to the stacked
    # shape), so only the grouped member is warmed per geometry.
    bucket_table = bucket_assignment = dev_plan = None
    if cfg.buckets:
        bucket_table = buckets_lib.bucket_table(cfg)
        bucket_assignment = buckets_lib.assign_buckets(
            buckets_lib.sample_extents(train_split, cfg), bucket_table)
        warm_per_step = group_size == 1 or fused > 1
        # dev packs with the decode table (tar pinned full — the gating
        # metric scores every tar position, see _eval_tasks, so the
        # engine-only cfg.decode_tar_buckets knob is forced off here);
        # the dev plan is shuffle=False and never changes, so compute it
        # ONCE here instead of re-deriving extents/assignment at every
        # dev gate
        dev_geoms = buckets_lib.decode_table(
            cfg.replace(decode_tar_buckets=False))
        dev_plan = buckets_lib.packed_plan(
            dataset.splits["valid"], cfg, batch_size=cfg.test_batch_size,
            table=dev_geoms, use_msg=False)
        labels = [sanitizer_label("dev_step", buckets_lib.geom_tag(g))
                  for g in dev_geoms]
        for g in bucket_table:
            tag = buckets_lib.geom_tag(g)
            if warm_per_step:
                labels.append(sanitizer_label("train_step", tag))
            if group_size > 1:
                labels.append(sanitizer_label("grouped_step", tag,
                                              group_size))
        if guard is not None:
            guard.declare(labels)
        # donation-safe throwaway copy: the real state (and its PRNG) is
        # untouched by warmup; host round-trip avoids compiling a copy op
        host_state = jax.device_get(state)
        warm_state = (jax.device_put(host_state,
                                     step_lib.state_shardings(state, mesh))
                      if mesh is not None else jax.device_put(host_state))
        for g in bucket_table:
            tag = buckets_lib.geom_tag(g)
            wb = buckets_lib.warmup_batch(train_split, cfg, g,
                                          cfg.batch_size)
            if warm_per_step:
                warm_state, wm = train_step(warm_state, wb)
                if guard is not None:
                    guard.step(sanitizer_label("train_step", tag))
            if group_size > 1:
                swb = grouping.stack_group([wb] * group_size)
                warm_state, wm = grouped_step(warm_state, swb)
                if guard is not None:
                    guard.step(sanitizer_label("grouped_step", tag,
                                               group_size))
        for g in dev_geoms:
            wb = buckets_lib.warmup_batch(train_split, cfg, g,
                                          cfg.test_batch_size)
            dev_step(state.params, wb)
            if guard is not None:
                guard.step(sanitizer_label("dev_step",
                                           buckets_lib.geom_tag(g)))
        _materialize(wm["loss"])  # startup warmup boundary, pre-metering
        del warm_state, host_state
        log.console(
            f"buckets: pre-warmed "
            f"{len(bucket_table) * (1 if warm_per_step else 0)} train + "
            f"{len(bucket_table) * (1 if group_size > 1 else 0)} grouped"
            f"{f'(g{group_size})' if group_size > 1 else ''} + "
            f"{len(dev_geoms)} dev programs "
            f"({', '.join(buckets_lib.geom_tag(g) for g in bucket_table)})")
        meter.start()  # warmup/compile time is not train time

    def epoch_tasks(epoch: int):
        """Zero-arg assembly tasks in the exact deterministic (seed, epoch)
        batch order — ONE scheduler for every mode (data/grouping.py):
        per-step mode reproduces the legacy chunking/packing byte-for-byte,
        grouped mode packs bucket-homogeneous K-stacks over the SAME
        permutation (fused tails per-step, accum tails padded with
        all-invalid micro-batches). Each task builds ONE dispatch item, so
        independent items assemble in parallel on the feeder's workers."""
        plan = grouping.grouped_plan(
            train_split, cfg, batch_size=cfg.batch_size,
            group_size=group_size, accum=accum > 1, shuffle=True,
            seed=cfg.seed, epoch=epoch, table=bucket_table,
            assignment=bucket_assignment)
        return grouping.grouped_assembly_tasks(
            train_split, plan, cfg, batch_size=cfg.batch_size,
            bucketed=bucket_table is not None)

    # Aggregated feeder stats across epochs (each epoch gets a fresh
    # pipeline; sums/mins fold here for TrainResult)
    feed_totals = {"batches": 0.0, "feed_stall_s": 0.0,
                   "queue_depth_sum": 0.0, "queue_depth_min": float("inf")}

    for epoch in range(start_epoch, n_epochs):
        last_metrics = None
        idx = 0  # batch index of the current item's first step
        epoch_feed = Feeder(epoch_tasks(epoch),
                            num_workers=cfg.feeder_workers,
                            depth=cfg.feeder_depth, sharding=batch_sharding)
        try:
            for item in epoch_feed:
                batch, n_valid = item.device, item.n_valid
                pending_stall += item.stall_s
                stacked = item.host["valid"].ndim == 2
                # cadence counts REAL batches: the accum tail is padded with
                # all-zero micro-batches, so the stacked leading dim overstates
                # it — n_valid (host-side, no sync) recovers the real count
                # exactly because only a group's last real batch can be partial
                k = -(-n_valid // cfg.batch_size) if stacked else 1
                # does [idx, idx+k) contain a multiple of the cadence?
                gate_due = (-idx) % cfg.dev_every_batches < k
                log_due = (-idx) % 10 < k
                if epoch >= cfg.dev_start_epoch and gate_due:
                    if last_metrics is not None:
                        _materialize(last_metrics["loss"])
                    sync_tick()
                    meter.pause()  # dev time is not train time
                    # dispatch watchdog (docs/FAULTS.md): a dev gate that
                    # wedges (hung eval dispatch, stuck eval feeder) is
                    # ABANDONED after cfg.dispatch_watchdog_s and skipped
                    # with a recorded warning — training continues
                    # degraded instead of the whole run hanging on its
                    # own evaluation. 0 (default) = off, call inline.
                    gate_cancel = threading.Event()
                    try:
                        cur_bleu, dev_text = run_with_watchdog(
                            lambda: run_dev(dev_step, state.params,
                                            dataset, cfg, var_maps,
                                            guard=guard,
                                            eval_plan=dev_plan,
                                            cancel=gate_cancel.is_set),
                            float(cfg.dispatch_watchdog_s),  # firacheck: allow[HOST-SYNC] config scalar, not a device value; the gate is already a designated sync boundary
                            label=f"dev_gate[e{epoch}b{idx}]",
                            cancel_event=gate_cancel)
                    except WatchdogTimeout as e:
                        w = (f"dev gate at epoch {epoch} batch {idx} "
                             f"skipped: {e}; training continues without "
                             f"this gate's checkpoint decision")
                        log.console(f"WARNING: {w}")
                        warnings.append(w)
                    else:
                        better = cur_bleu > best_bleu
                        log.gate(epoch, idx, cur_bleu, better)
                        if better:
                            best_bleu = cur_bleu
                            ckpt.save_best(state.params)
                            log.dev_output(dev_text)
                    meter.start()

                if (profile_window and not profiling_active
                        and not profile_done
                        and global_step >= profile_window[0]):
                    # the REAL program is profiled — grouped dispatches and
                    # all — so profiled numbers are production-path numbers;
                    # a K-group's annotation spans its whole scan dispatch
                    jax.profiler.start_trace(profile_dir)
                    profiling_active = True
                dispatch = grouped_step if stacked else train_step
                if profiling_active:
                    with profiling.step_annotation(global_step):
                        state, metrics = dispatch(state, batch)
                else:
                    state, metrics = dispatch(state, batch)
                if guard is not None:
                    # compile-once contract: a post-warmup dispatch of any
                    # program that recompiles raises RetraceError here; a
                    # bucketed item carries its geometry tag and a stacked
                    # item its group size, giving each (geom, K) member of
                    # the pre-warmed family its own label
                    tag = item.host.get("_tag")
                    guard.step(sanitizer_label(
                        "grouped_step" if stacked else "train_step", tag,
                        group_size if stacked else 1))
                # a fused group is k steps; an accumulation group is ONE step
                global_step += 1 if (stacked and accum > 1) else k
                if profiling_active and global_step > profile_window[-1]:
                    _materialize(metrics["loss"])
                    jax.profiler.stop_trace()
                    profiling_active = False
                    profile_done = True
                    log.console(f"profile trace written to {profile_dir}")
                last_metrics = metrics
                pending_commits += n_valid
                if log_due:
                    # blocks; a stacked dispatch reports its last step's loss
                    # firacheck: allow[HOST-SYNC] the 10-batch console-log cadence is a designated sync boundary (README Design notes); steps in between stay async-dispatched
                    loss = float(np.asarray(
                        jax.device_get(metrics["loss"])).ravel()[-1])  # firacheck: allow[HOST-SYNC] same log boundary — the expression's device_get continues onto this line
                    sync_tick()
                    log.console(f"epoch: {epoch} batch: {idx} loss: {loss:.4f}")
                idx += k
        finally:
            # clean pipeline shutdown on ANY exit (error, interrupt, normal
            # exhaustion): no worker threads survive the epoch
            s = epoch_feed.stats()
            feed_totals["batches"] += s["batches"]
            feed_totals["feed_stall_s"] += s["feed_stall_s"]
            feed_totals["queue_depth_sum"] += s["queue_depth_sum"]
            feed_totals["queue_depth_min"] = min(
                feed_totals["queue_depth_min"], s["queue_depth_min"])
            epoch_feed.close()
        if last_metrics is not None:
            _materialize(last_metrics["loss"])
        sync_tick()
        ckpt.save_latest(state, best_bleu=best_bleu, epoch=epoch + 1,
                         rng_impl=cfg.rng_impl)

    if profiling_active:  # run ended inside the profile window
        jax.profiler.stop_trace()
        log.console(f"profile trace written to {profile_dir}")
    elif profile_dir and not profile_window:
        log.console("profile trace NOT written: profile_steps=0")
    elif profile_dir and not profile_done:
        log.console(f"profile trace NOT written: run ended after "
                    f"{global_step} steps, before the profile window "
                    f"(starts at step {profile_window[0]})")

    msum = meter.summary()
    cps = msum["items_per_sec"] / n_chips
    n_fed = feed_totals["batches"]
    feeder_stats = {
        "batches": n_fed,
        "feed_stall_s": round(feed_totals["feed_stall_s"], 4),
        "queue_depth_mean": round(
            feed_totals["queue_depth_sum"] / n_fed, 2) if n_fed else 0.0,
        "queue_depth_min": (feed_totals["queue_depth_min"]
                            if n_fed else 0.0),
        "num_workers": float(cfg.feeder_workers),
        "depth": float(cfg.feeder_depth),
    }
    if n_fed:
        log.console(
            f"throughput: {cps:.2f} commits/sec/chip | feed_stall_frac "
            f"{msum['feed_stall_frac']:.3f} "
            f"({msum['feed_stall_ms_per_step']:.1f} ms/step) | feeder "
            f"queue depth mean {feeder_stats['queue_depth_mean']:.1f} "
            f"min {feeder_stats['queue_depth_min']:.0f} "
            f"(workers {cfg.feeder_workers}, depth {cfg.feeder_depth})")
    # epochs ACTUALLY executed this call (a resumed run skips start_epoch of
    # them; a checkpoint already past the target runs zero) — callers
    # validating resume legs depend on the distinction
    return TrainResult(state=state, best_bleu=best_bleu,
                       epochs_run=max(0, n_epochs - start_epoch),
                       commits_per_sec_per_chip=cps,
                       feed_stall_frac=msum["feed_stall_frac"],
                       feeder=feeder_stats, warnings=warnings)
