"""Train state + checkpointing.

The reference checkpoints only the best-on-dev ``model.state_dict()``
(/root/reference/run_model.py:94-96) — no optimizer state, no resume. Here
the full train state (step, params, Adam moments, dev-gating bookkeeping,
PRNG key) round-trips through orbax, so a preempted TPU run resumes exactly;
the best-on-dev params are additionally kept as their own checkpoint, like
the reference's ``best_model.pt``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from fira_tpu.config import FiraConfig
from fira_tpu.model.model import FiraModel


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    rng: jax.Array


def prng_impl_name(cfg_value: str) -> str:
    """Map the config's generator name to JAX's registered impl name."""
    return {"threefry": "threefry2x32"}.get(cfg_value, cfg_value)


def make_optimizer(cfg: FiraConfig) -> optax.GradientTransformation:
    """Adam(lr=1e-4) with torch defaults (run_model.py:396): betas (0.9,
    0.999), eps 1e-8 — identical to optax defaults."""
    return optax.adam(cfg.lr)


def init_state(model: FiraModel, cfg: FiraConfig, sample_batch: Dict[str, Any],
               seed: Optional[int] = None) -> TrainState:
    # rng_impl "rbg" swaps the dropout-stream generator for the
    # hardware-friendly RBG one (threefry is the reproducible-everywhere
    # default). Param INIT always uses threefry so initial weights are
    # identical across the knob; only the dropout stream differs. A
    # checkpoint stores the key, so resumes must keep the same impl.
    impl = prng_impl_name(cfg.rng_impl)
    s = cfg.seed if seed is None else seed
    init_rng, _ = jax.random.split(jax.random.PRNGKey(s))
    # State carries RAW key data (orbax-serializable); train_step re-wraps it
    # with cfg.rng_impl. For threefry this is bit-identical to the historical
    # split(PRNGKey(seed))[1] layout.
    state_rng = jax.random.key_data(
        jax.random.split(jax.random.key(s, impl=impl))[1])
    params = model.init(init_rng, sample_batch, deterministic=True)["params"]
    opt_state = make_optimizer(cfg).init(params)
    return TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=opt_state, rng=state_rng,
    )


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


class CheckpointManager:
    """Orbax-backed save/restore of (state, best_params, metadata)."""

    LATEST = "latest"
    BEST = "best"

    def __init__(self, ckpt_dir: str):
        import orbax.checkpoint as ocp

        self.ckpt_dir = os.path.abspath(ckpt_dir)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self._ckpt = ocp.PyTreeCheckpointer()

    def _path(self, name: str) -> str:
        return os.path.join(self.ckpt_dir, name)

    def save_latest(self, state: TrainState, *, best_bleu: float,
                    epoch: int, rng_impl: str = "threefry") -> None:
        payload = {
            "state": jax.device_get(state),
            "meta": {"best_bleu": float(best_bleu), "epoch": int(epoch),
                     "rng_impl": rng_impl},
        }
        self._ckpt.save(self._path(self.LATEST), payload, force=True)

    def save_best(self, params) -> None:
        """The reference's best_model.pt equivalent (run_model.py:96):
        params only, gated on dev BLEU by the caller."""
        self._ckpt.save(self._path(self.BEST), jax.device_get(params),
                        force=True)

    def has(self, name: str) -> bool:
        return os.path.isdir(self._path(name))

    def restore_latest(self, template_state: TrainState, *,
                       expect_rng_impl: Optional[str] = None
                       ) -> Tuple[TrainState, Dict[str, Any]]:
        state_t = jax.device_get(template_state)
        # Probe the saved tree's structure to decide the restore template:
        # checkpoints written before the rng_impl field lack meta.rng_impl,
        # and restoring them against a template that has it raises. Probing
        # (rather than try/restore/except Exception) keeps a transient I/O
        # failure from being misread as "old checkpoint" and silently
        # mislabelled threefry (advisor r3).
        meta_t = {"best_bleu": 0.0, "epoch": 0, "rng_impl": "threefry"}
        # orbax changed the metadata() return shape across versions: older
        # releases hand back the metadata tree as a plain dict, newer ones
        # wrap it in CheckpointMetadata.item_metadata.tree
        meta_obj = self._ckpt.metadata(self._path(self.LATEST))
        if hasattr(meta_obj, "item_metadata"):
            meta_obj = meta_obj.item_metadata.tree
        saved_meta_keys = (meta_obj or {}).get("meta", {})
        if "rng_impl" not in saved_meta_keys:
            del meta_t["rng_impl"]
        payload = self._ckpt.restore(
            self._path(self.LATEST),
            item={"state": state_t, "meta": meta_t},
        )
        payload["meta"].setdefault("rng_impl", "threefry")
        saved_impl = payload["meta"].get("rng_impl", "threefry")
        if expect_rng_impl is not None and saved_impl != expect_rng_impl:
            # fail HERE with the cause, not later with an opaque key-shape
            # error inside the jitted step's wrap_key_data
            raise ValueError(
                f"checkpoint was trained with rng_impl={saved_impl!r} but "
                f"this run is configured with rng_impl={expect_rng_impl!r}; "
                f"resume with the matching --rng-impl or use a fresh "
                f"checkpoint dir")
        return payload["state"], payload["meta"]

    def restore_best(self, template_params):
        return self._ckpt.restore(self._path(self.BEST),
                                  item=jax.device_get(template_params))
