"""Serving-contract lints: the repo's own merge contracts, mechanized
(docs/ANALYSIS.md "v2: contract lints").

Three registry passes over conventions every PR since 8 has maintained by
hand — each encodes a promise some other file silently depends on:

- KNOB-VALIDATE — every config knob a CLI flag writes is admitted at
  parse time: either a ``*_errors`` validator somewhere reads
  ``cfg.<knob>``, or the flag itself constrains its value (``choices``,
  a validating ``type`` callable, ``store_true``). The repo's exit-2
  contract (PR 6 review onward): a bad knob is a named parse-time
  rejection, never a mid-run traceback.
- FAULT-SITE — every site string handed to the fault injector
  (``.check("x.y")`` / ``.corrupt("x.y", ...)`` / ``.armed("x.y")``) is
  registered in ``robust.faults.SITES``, and corrupt-capable sites are
  in ``CORRUPT_SITES``: an unregistered site arms NOTHING (the spec
  parser rejects it), so a typo'd site silently un-tests its
  degradation contract.
- DRIVER-REG — every module that dispatches jitted programs
  (``jax.jit``) or drives the engine/fleet steppables (``SlotEngine`` /
  ``EngineFleet``) is a designated driver module
  (``analysis.astutil._DRIVER_FILES``) AND named in
  ``scripts/check.sh``: otherwise its dispatch loops are invisible to
  the hot-region rules and a future check.sh refactor can drop it from
  the scan (the PR 2-13 convention, now enforced).
- STATS-SCHEMA (v3) — the observability contract for ``*Stats``
  classes that own a ``summary()``: (a) every declared field is READ by
  ``summary()`` or a helper/property it reaches (a field the snapshot
  never serializes is invisible drift — the ``workers`` /
  ``pipeline_depth`` class of bug PR 13 closed by hand); (b) every
  ``self.X`` the summary closure reads is a declared field / method /
  assigned attribute of the class (the typo'd-key direction); (c) for
  the repo's real stats classes (:data:`_STATS_DOC_CLASSES`), every
  field is named somewhere under ``docs/`` — a serialized key nobody
  documented is a key consumers cannot rely on (WARNING).

The cross-file state lives in :class:`ContractRegistry`, merged by the
engine's pass 1 exactly like the donation-factory registry. When the
scan does not include ``robust/faults.py`` (a partial scan), the site
registry falls back to importing the real module, so subset scans never
false-positive on registered sites.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from fira_tpu.analysis import astutil
from fira_tpu.analysis.findings import Finding, Severity

# argparse `type=` callables that validate nothing beyond shape
_PLAIN_TYPES = {"int", "float", "str"}
_INJECTOR_HINTS = ("fault", "injector")
_STEPPABLE_NAMES = {"SlotEngine", "EngineFleet"}
# the real observability classes whose fields must also be docs-named;
# fixture *Stats classes get checks (a)/(b) but not the docs half
_STATS_DOC_CLASSES = ("EngineStats", "FleetStats", "ServeStats")


@dataclasses.dataclass
class ContractRegistry:
    """Cross-file contract state, merged over every scanned file."""

    # cfg fields read by some `*_errors` validator function
    validated_fields: Set[str] = dataclasses.field(default_factory=set)
    # fault-site registry (robust/faults.py SITES / CORRUPT_SITES)
    sites: Set[str] = dataclasses.field(default_factory=set)
    corrupt_sites: Set[str] = dataclasses.field(default_factory=set)
    sites_seen: bool = False  # a faults.py module was in the scan


def _module_tuple(tree: ast.AST, name: str) -> List[Tuple[int, str]]:
    """(line, value) per string element of a module-level ``name = (...)``
    tuple assignment."""
    out: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append((e.lineno, e.value))
    return out


def collect(path: str, tree: ast.AST, registry: ContractRegistry) -> None:
    """Pass-1 hook: fold one file's contract state into the registry."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.endswith("_errors"):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == "cfg":
                    registry.validated_fields.add(sub.attr)
    if os.path.basename(path) == "faults.py":
        sites = _module_tuple(tree, "SITES")
        corrupt = _module_tuple(tree, "CORRUPT_SITES")
        if sites:
            registry.sites_seen = True
            registry.sites.update(v for _ln, v in sites)
            registry.corrupt_sites.update(v for _ln, v in corrupt)


def finalize(registry: ContractRegistry) -> None:
    """After pass 1: a scan that did not include robust/faults.py reads
    the REAL site registry instead of flagging every site as unknown."""
    if not registry.sites_seen:
        try:
            from fira_tpu.robust import faults as faults_lib

            registry.sites.update(faults_lib.SITES)
            registry.corrupt_sites.update(faults_lib.CORRUPT_SITES)
            registry.sites_seen = True
        except Exception:
            pass  # no package available: FAULT-SITE stays disarmed


# --------------------------------------------------------------------------
# KNOB-VALIDATE
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _FlagInfo:
    choices: bool = False
    store_true: bool = False
    custom_type: bool = False

    @property
    def self_validating(self) -> bool:
        return self.choices or self.store_true or self.custom_type


def _argparse_flags(tree: ast.AST) -> Dict[str, _FlagInfo]:
    """dest -> constraint info for every ``add_argument`` call in the
    file (dest derived from the first ``--option-string`` or positional
    name, or an explicit ``dest=``)."""
    flags: Dict[str, _FlagInfo] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args):
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        dest = first.value.lstrip("-").replace("-", "_")
        info = _FlagInfo()
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = str(kw.value.value)
            elif kw.arg == "choices":
                info.choices = True
            elif kw.arg == "action" \
                    and isinstance(kw.value, ast.Constant) \
                    and kw.value.value in ("store_true", "store_false"):
                info.store_true = True
            elif kw.arg == "type":
                tname = astutil.dotted(kw.value)
                if tname is None or astutil.last_segment(tname) \
                        not in _PLAIN_TYPES:
                    info.custom_type = True
        flags[dest] = info
    return flags


def _args_attrs(node: ast.AST) -> List[str]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.value.id == "args":
            out.append(n.attr)
    return out


def check_knob_validate(path: str, tree: ast.AST, parents,
                        registry: ContractRegistry) -> List[Finding]:
    """KNOB-VALIDATE: runs in files that define ``_resolve_cfg`` (the
    CLI's flag->config funnel). Disarmed when the scan saw NO validator
    functions at all (a partial scan has nothing to compare against)."""
    if not registry.validated_fields:
        return []
    resolve = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_resolve_cfg":
            resolve = node
            break
    if resolve is None:
        return []
    flags = _argparse_flags(tree)
    findings: List[Finding] = []
    for node in ast.walk(resolve):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name)
                and t.value.id == "overrides"
                and isinstance(t.slice, ast.Constant)
                and isinstance(t.slice.value, str)):
            continue
        field = t.slice.value
        if field in registry.validated_fields:
            continue
        # which CLI flag feeds this knob: the RHS's args.<attr>, else the
        # nearest enclosing condition's (a `store_true`-gated literal)
        attrs = _args_attrs(node.value)
        if not attrs:
            for a in astutil.ancestors(node, parents):
                if a is resolve:
                    break
                if isinstance(a, ast.If):
                    attrs = _args_attrs(a.test)
                    if attrs:
                        break
        covered = any(flags.get(a, _FlagInfo()).self_validating
                      for a in attrs)
        if not covered:
            via = (f"--{attrs[0].replace('_', '-')}" if attrs
                   else "a computed value")
            findings.append(Finding(
                path, node.lineno, "KNOB-VALIDATE", Severity.ERROR,
                f"config knob '{field}' is set from the CLI ({via}) but "
                f"no *_errors validator reads cfg.{field} and the flag "
                f"carries no choices/validating type: a bad value becomes "
                f"a mid-run traceback instead of a named exit-2 rejection"))
    return findings


# --------------------------------------------------------------------------
# FAULT-SITE
# --------------------------------------------------------------------------

def _injector_receiver(func: ast.AST) -> bool:
    if not isinstance(func, ast.Attribute):
        return False
    recv = astutil.dotted(func.value)
    if not recv:
        return False
    seg = astutil.last_segment(recv).lower()
    return any(h in seg for h in _INJECTOR_HINTS)


def check_fault_site(path: str, tree: ast.AST,
                     registry: ContractRegistry) -> List[Finding]:
    """FAULT-SITE: every dotted site string handed to an injector-shaped
    receiver's check/corrupt/armed is registered; corrupt requires
    CORRUPT_SITES membership. Disarmed without a site registry."""
    if not registry.sites_seen:
        return []
    if os.path.basename(path) == "faults.py":
        return []  # the registry definition site itself
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("check", "corrupt", "armed")
                and _injector_receiver(node.func) and node.args):
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                and "." in arg.value):
            continue
        site = arg.value
        if site not in registry.sites:
            findings.append(Finding(
                path, node.lineno, "FAULT-SITE", Severity.ERROR,
                f"fault site '{site}' is not registered in "
                f"robust.faults.SITES: the spec parser rejects it, so no "
                f"chaos run can ever arm this injection point — register "
                f"it or fix the typo"))
        elif node.func.attr == "corrupt" \
                and site not in registry.corrupt_sites:
            findings.append(Finding(
                path, node.lineno, "FAULT-SITE", Severity.ERROR,
                f"fault site '{site}' is used with corrupt() but is not "
                f"in robust.faults.CORRUPT_SITES: only sites owning a "
                f"host payload may scramble one (docs/FAULTS.md) — "
                f"register it corrupt-capable or drop the call"))
    return findings


# --------------------------------------------------------------------------
# DRIVER-REG
# --------------------------------------------------------------------------

def _steppable_use(tree: ast.AST) -> Optional[int]:
    lines = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if any(a.name in _STEPPABLE_NAMES for a in node.names):
                lines.append(node.lineno)
        elif isinstance(node, ast.Attribute) \
                and node.attr in _STEPPABLE_NAMES:
            lines.append(node.lineno)
    return min(lines) if lines else None


def _jit_use(tree: ast.AST) -> Optional[int]:
    lines = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and astutil.is_jit_call(node):
            lines.append(node.lineno)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if ((isinstance(dec, ast.Call) and astutil.is_jit_call(dec))
                        or astutil.dotted(dec) in ("jax.jit", "jit")):
                    lines.append(dec.lineno)
    return min(lines) if lines else None


def _find_check_sh(path: str) -> Optional[str]:
    """scripts/check.sh located by walking up from the scanned file."""
    d = os.path.dirname(astutil.normalize_path(path))
    for _ in range(6):
        cand = os.path.join(d, "scripts", "check.sh")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def check_driver_reg(path: str, tree: ast.AST) -> List[Finding]:
    """DRIVER-REG, per-module half: a fira_tpu module that dispatches
    jitted programs or drives engine/fleet steppables must be a
    designated driver module."""
    from fira_tpu.analysis.rules_purity import _package_relative

    rel = _package_relative(astutil.normalize_path(path))
    if rel is None or not rel or rel.startswith("analysis/") \
            or os.path.basename(path) == "__init__.py":
        return []
    if astutil.is_driver_module(path):
        return []
    findings: List[Finding] = []
    line = _steppable_use(tree)
    if line is not None:
        findings.append(Finding(
            path, line, "DRIVER-REG", Severity.ERROR,
            f"module drives the engine/fleet steppables but is not in "
            f"analysis.astutil._DRIVER_FILES: its scheduling loops are "
            f"invisible to the hot-region/concurrency rules — register "
            f"it (and name it in scripts/check.sh) or waive with a "
            f"reason"))
        return findings
    line = _jit_use(tree)
    if line is not None:
        findings.append(Finding(
            path, line, "DRIVER-REG", Severity.ERROR,
            f"module constructs jitted programs (jax.jit) but is not in "
            f"analysis.astutil._DRIVER_FILES: its dispatch loops are "
            f"invisible to the hot-region/concurrency rules — register "
            f"it (and name it in scripts/check.sh) or waive with a "
            f"reason"))
    return findings


def check_driver_names(path: str, tree: ast.AST) -> List[Finding]:
    """DRIVER-REG, registry half: runs only on the file that defines
    _DRIVER_FILES (analysis/astutil.py) — every registered driver module
    must be NAMED in scripts/check.sh so a check.sh refactor can never
    silently drop one from the gate."""
    entries = _module_tuple(tree, "_DRIVER_FILES")
    if not entries:
        return []
    sh = _find_check_sh(path)
    if sh is None:
        return []  # no check.sh in this checkout: nothing to pin against
    try:
        with open(sh, encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError:
        return []
    findings: List[Finding] = []
    for line, entry in entries:
        if entry not in text:
            findings.append(Finding(
                path, line, "DRIVER-REG", Severity.ERROR,
                f"driver module '{entry}' (_DRIVER_FILES) is not named in "
                f"scripts/check.sh: the self-scan would silently lose it "
                f"if the directory arguments ever change — name it in the "
                f"check.sh invocation"))
    return findings


# --------------------------------------------------------------------------
# STATS-SCHEMA (v3)
# --------------------------------------------------------------------------

def _stats_members(cls: ast.ClassDef) -> Tuple[Dict[str, int], Set[str],
                                               Set[str], Set[str]]:
    """(fields -> line, method names, property names, self-assigned
    attrs) for one class body."""
    fields: Dict[str, int] = {}
    methods: Set[str] = set()
    props: Set[str] = set()
    assigned: Set[str] = set()
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                          ast.Name):
            ann = astutil.dotted(node.annotation) or ""
            if astutil.last_segment(ann) != "ClassVar":
                fields[node.target.id] = node.lineno
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            methods.add(node.name)
            if any(astutil.dotted(d) in ("property", "functools.cached_property",
                                         "cached_property")
                   for d in node.decorator_list):
                props.add(node.name)
    for node in ast.walk(cls):
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Store) \
                and isinstance(node.value, ast.Name) and node.value.id == "self":
            assigned.add(node.attr)
    return fields, methods, props, assigned


def _summary_closure(cls: ast.ClassDef, methods: Set[str],
                     props: Set[str]) -> Set[str]:
    """Methods/properties transitively reachable from summary(): follow
    ``self.m(...)`` calls and ``self.p`` property reads."""
    bodies = {n.name: n for n in cls.body
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    closure: Set[str] = set()
    frontier = ["summary"]
    while frontier:
        name = frontier.pop()
        if name in closure or name not in bodies:
            continue
        closure.add(name)
        for node in ast.walk(bodies[name]):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                if node.attr in methods and (
                        node.attr in props
                        or isinstance(node.ctx, ast.Load)):
                    frontier.append(node.attr)
    return closure


def _docs_text(path: str) -> Optional[str]:
    """Concatenated docs/*.md found by walking up from the scanned file
    (same discovery as _find_check_sh); None when this checkout carries
    no docs tree — the docs half of STATS-SCHEMA then stays disarmed."""
    d = os.path.dirname(astutil.normalize_path(path))
    for _ in range(6):
        cand = os.path.join(d, "docs")
        if os.path.isdir(cand):
            chunks = []
            try:
                for name in sorted(os.listdir(cand)):
                    if name.endswith(".md"):
                        with open(os.path.join(cand, name),
                                  encoding="utf-8", errors="replace") as f:
                            chunks.append(f.read())
            except OSError:
                return None
            return "\n".join(chunks)
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def check_stats_schema(path: str, tree: ast.AST) -> List[Finding]:
    """STATS-SCHEMA: see the module docstring. Purely per-file — a
    stats class and its summary() always live together."""
    import re

    findings: List[Finding] = []
    docs: Optional[str] = None
    docs_loaded = False
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name.endswith("Stats")):
            continue
        fields, methods, props, assigned = _stats_members(cls)
        if "summary" not in methods or not fields:
            continue
        closure = _summary_closure(cls, methods, props)
        reads: Set[str] = set()
        bodies = {n.name: n for n in cls.body
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for name in closure:
            for node in ast.walk(bodies[name]):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self":
                    reads.add(node.attr)
        for field, line in sorted(fields.items(), key=lambda kv: kv[1]):
            if field not in reads:
                findings.append(Finding(
                    path, line, "STATS-SCHEMA", Severity.ERROR,
                    f"{cls.name}.{field} is never serialized: summary() "
                    f"and the helpers it reaches never read "
                    f"self.{field}, so the metrics snapshot silently "
                    f"drops the field — serialize it or delete it"))
        declared = set(fields) | methods | assigned
        for name in sorted(closure):
            for node in ast.walk(bodies[name]):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr not in declared:
                    findings.append(Finding(
                        path, node.lineno, "STATS-SCHEMA", Severity.ERROR,
                        f"summary() path reads self.{node.attr} which "
                        f"{cls.name} never declares as a field, method, "
                        f"or assigned attribute — a serialized key with "
                        f"no backing state (the workers/pipeline_depth "
                        f"drift class)"))
                    declared.add(node.attr)  # one finding per name
        if cls.name in _STATS_DOC_CLASSES:
            if not docs_loaded:
                docs = _docs_text(path)
                docs_loaded = True
            if docs is not None:
                for field, line in sorted(fields.items(),
                                          key=lambda kv: kv[1]):
                    if not re.search(rf"\b{re.escape(field)}\b", docs):
                        findings.append(Finding(
                            path, line, "STATS-SCHEMA", Severity.WARNING,
                            f"{cls.name}.{field} is not named anywhere "
                            f"under docs/ — a metrics key consumers "
                            f"cannot rely on; add it to the stats table "
                            f"in docs/ANALYSIS.md"))
    return findings


def check(path: str, tree: ast.AST, source: str, parents, spans, *,
          registry: Optional[ContractRegistry] = None) -> List[Finding]:
    registry = registry if registry is not None else ContractRegistry()
    findings: List[Finding] = []
    findings += check_knob_validate(path, tree, parents, registry)
    findings += check_fault_site(path, tree, registry)
    findings += check_driver_reg(path, tree)
    findings += check_driver_names(path, tree)
    findings += check_stats_schema(path, tree)
    return findings
