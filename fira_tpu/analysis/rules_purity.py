"""PRNG-REUSE, DISCARDED-AT, GEOMETRY-DRIFT: functional-purity contracts.

PRNG-REUSE — JAX keys are use-once: feeding the same key object to two
``jax.random.*`` consumers silently correlates the draws (the dropout
masks of two layers become identical, a bug no test of either layer alone
catches). Intra-function dataflow: two consumer uses of one key name with
no intervening rebind (``split``/``fold_in``/key-data plumbing don't count
as consumers).

DISCARDED-AT — ``x.at[i].set(v)`` returns a NEW array; as a bare
expression statement it is a silent no-op (the torch-habits bug: in-place
``tensor[i] = v`` thinking).

GEOMETRY-DRIFT — the fixed geometry (210/30/25/280/160/650, config.py) is
the one-compile contract's unit of account. A re-typed literal in package
code silently diverges when a config scales; the named field must be
referenced. Scoped to ``fira_tpu/`` (minus config.py, where the numbers
are DEFINED, and this analysis package).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from fira_tpu.analysis import astutil
from fira_tpu.analysis.findings import Finding, Severity

_NONCONSUMING = {
    "split", "fold_in", "PRNGKey", "key", "key_data", "wrap_key_data",
    "clone", "key_impl", "default_prng_impl",
}
_RANDOM_PREFIXES = ("jax.random.", "jrandom.")  # NOT bare "random.":
# stdlib random.shuffle etc. would false-positive; this repo always
# qualifies jax.random fully.

_GEOMETRY = {
    210: "sou_len", 30: "tar_len", 25: "att_len", 280: "ast_change_len",
    160: "sub_token_len", 650: "graph_len",
}
_AT_METHODS = {"set", "add", "multiply", "mul", "divide", "div", "power",
               "min", "max", "apply", "get"}


def _random_consumer(call: ast.Call) -> bool:
    name = astutil.call_name(call)
    if not name:
        return False
    for prefix in _RANDOM_PREFIXES:
        if name.startswith(prefix):
            fn = name[len(prefix):]
            return "." not in fn and fn not in _NONCONSUMING
    return False


def _function_scopes(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def check_prng(path: str, tree: ast.AST, source: str, parents, spans,
               ) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _function_scopes(tree):
        # events in source order: ('store', name) rebinds; ('use', name)
        # consumes. Nested defs get their own scope pass, so skip their
        # bodies here (a closure's key discipline is its own affair).
        events: List[Tuple[int, str, str, ast.AST]] = []
        nested = {id(sub) for stmt in fn.body for sub in ast.walk(stmt)
                  if isinstance(sub, astutil.FunctionNode) and sub is not fn}

        def in_nested(node: ast.AST, owner_ids=nested) -> bool:
            for a in astutil.ancestors(node, parents):
                if id(a) in owner_ids:
                    return True
                if a is fn:
                    return False
            return False

        for stmt in fn.body:
            for node in ast.walk(stmt):
                if in_nested(node):
                    continue
                if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                             ast.Store):
                    events.append((node.lineno, "store", node.id, node))
                elif isinstance(node, ast.Call) and _random_consumer(node):
                    key = node.args[0] if node.args else None
                    if isinstance(key, ast.Name):
                        events.append((node.lineno, "use", key.id, node))
        def branch_arms(node: ast.AST):
            """(id(if_node), arm) chain — two uses that sit in DIFFERENT
            arms of a shared if/else are mutually exclusive, not reuse."""
            arms = {}
            child = node
            for a in astutil.ancestors(node, parents):
                if isinstance(a, ast.If):
                    arm = "orelse" if child in a.orelse else "body"
                    arms[id(a)] = arm
                if a is fn:
                    break
                child = a
            return arms

        def exclusive(n1: ast.AST, n2: ast.AST) -> bool:
            a1, a2 = branch_arms(n1), branch_arms(n2)
            return any(a2.get(k, v) != v for k, v in a1.items())

        events.sort(key=lambda e: e[0])
        live_use: Dict[str, Tuple[int, ast.AST]] = {}
        for lineno, kind, name, node in events:
            if kind == "store":
                live_use.pop(name, None)
            elif name in live_use and not exclusive(live_use[name][1], node):
                findings.append(Finding(
                    path, lineno, "PRNG-REUSE", Severity.ERROR,
                    f"key `{name}` already consumed by a jax.random call "
                    f"at line {live_use[name][0]} and reused here without "
                    f"split/fold_in: the two draws are perfectly "
                    f"correlated"))
            else:
                live_use[name] = (lineno, node)
    return findings


def check_discarded_at(path: str, tree: ast.AST, source: str, parents,
                       spans) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Expr) and isinstance(node.value,
                                                          ast.Call)):
            continue
        call = node.value
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr in _AT_METHODS):
            continue
        # receiver chain must contain an `.at[...]` subscript
        recv = call.func.value
        has_at = False
        probe = recv
        while True:
            if isinstance(probe, ast.Subscript):
                if (isinstance(probe.value, ast.Attribute)
                        and probe.value.attr == "at"):
                    has_at = True
                    break
                probe = probe.value
            elif isinstance(probe, (ast.Attribute, ast.Call)):
                probe = (probe.value if isinstance(probe, ast.Attribute)
                         else probe.func)
            else:
                break
        if has_at:
            findings.append(Finding(
                path, node.lineno, "DISCARDED-AT", Severity.ERROR,
                f"result of .at[...].{call.func.attr}(...) is discarded — "
                f"JAX functional updates return a new array; assign it or "
                f"delete the statement"))
    return findings


# sub-packages whose code must reference the named geometry; NOT analysis/
# (this package), config.py (where the numbers are DEFINED), or anything
# outside the package (tests/scripts assert literal geometry legitimately)
_GEOMETRY_SUBPACKAGES = {"model", "data", "decode", "train", "ops",
                         "parallel", "eval", "preprocess", "utils"}


def _package_relative(norm: str):
    """Path after the LAST 'fira_tpu' segment, or None. Segment-based so a
    repo CHECKOUT directory named fira_tpu doesn't arm the rule for its
    tests/ and scripts/ trees (substring matching did — review catch)."""
    segs = norm.split("/")
    for i in range(len(segs) - 1, -1, -1):
        if segs[i] == "fira_tpu":
            return "/".join(segs[i + 1:])
    return None


def check_geometry(path: str, tree: ast.AST, source: str, parents, spans,
                   ) -> List[Finding]:
    rel = _package_relative(astutil.normalize_path(path))
    if rel is None or rel.split("/")[0] not in _GEOMETRY_SUBPACKAGES:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant) and type(node.value) is int
                and node.value in _GEOMETRY):
            field = _GEOMETRY[node.value]
            findings.append(Finding(
                path, node.lineno, "GEOMETRY-DRIFT", Severity.ERROR,
                f"literal {node.value} shadows cfg.{field}; reference the "
                f"named geometry so scaled configs can't silently diverge "
                f"from the compiled shapes"))
    return findings
