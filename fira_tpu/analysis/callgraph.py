"""Module-set call graph for the interprocedural v3 rules
(docs/ANALYSIS.md "v3: interprocedural rules").

The v1/v2 rules are single-function pattern matchers; the two bug
families the v3 pack targets — an acquire whose release lives behind a
helper call (RES-LEAK) and a nondeterministic value that crosses a
function boundary before reaching a byte sink (DET-TAINT) — both
require following a value ACROSS calls. This module builds the index
that makes that possible without whole-program type inference:

- **def indexing**: every module-level function and every class method
  in the scanned tree set, keyed ``(path, qualname)`` where qualname is
  ``func`` or ``Class.method``.
- **call resolution** (:meth:`CallGraph.resolve`), deliberately scoped
  to the forms this repo's code actually uses and a static scan can get
  RIGHT: ``self.m(...)`` resolves within the caller's own class;
  ``f(...)`` resolves to a same-module function; ``mod.f(...)`` /
  ``alias.f(...)`` resolves through the file's imports when the target
  module is in the scan set. An unresolvable receiver (``obj.m(...)``
  on a value of unknown type) resolves to None — the rules treat those
  calls conservatively per-rule rather than guessing.
- **bounded-depth summaries**: :meth:`may_raise` answers "can a call to
  this function raise out of it?" by walking raise/assert statements,
  fault-injector ``check``/``corrupt`` sites (which raise BY CONTRACT
  when a chaos spec arms them), ``os.fsync`` (the one always-can-fail
  OS call the repo leans on), and resolved callees, to
  ``SUMMARY_DEPTH`` levels with cycle protection. The model is
  deliberately selective, not sound: treating EVERY call as
  may-raise would flag every two-statement acquire window in the tree
  and the signal would drown. What it claims, it can name — every
  may-raise verdict carries the concrete raising site.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Tuple

from fira_tpu.analysis import astutil

# how many call levels a summary follows before giving up (a bounded
# walk keeps the scan O(files), and real escape chains here are short:
# the deepest in-tree chain is 3)
SUMMARY_DEPTH = 4

_INJECTOR_HINTS = ("fault", "injector")
# externals that raise as part of their everyday contract (OSError on a
# full/dying disk); kept tiny on purpose — see module docstring
_RAISING_CALLS = {"os.fsync"}

FuncKey = Tuple[str, str]  # (normalized path, qualname)


@dataclasses.dataclass
class FunctionInfo:
    """One indexed function/method definition."""

    path: str                  # display path (as scanned)
    norm: str                  # astutil.normalize_path(path)
    qualname: str              # "func" or "Class.method"
    cls: Optional[str]         # owning class name, None for module level
    node: ast.AST              # the FunctionDef/AsyncFunctionDef
    params: Tuple[str, ...]    # positional parameter names (incl. self)

    @property
    def key(self) -> FuncKey:
        return (self.norm, self.qualname)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


def _positional_params(node: ast.AST) -> Tuple[str, ...]:
    a = node.args
    return tuple(p.arg for p in (*a.posonlyargs, *a.args))


def _module_of(norm: str) -> str:
    """Dotted module guess for an absolute path: the path after the last
    ``fira_tpu`` segment (``fira_tpu.x.y``), else the bare stem — enough
    for suffix-matching module-qualified calls against the scan set."""
    from fira_tpu.analysis.rules_purity import _package_relative

    rel = _package_relative(norm)
    stem = (rel if rel is not None else os.path.basename(norm))
    stem = stem[:-3] if stem.endswith(".py") else stem
    return ("fira_tpu." + stem.replace("/", ".")) if rel is not None \
        else stem.replace("/", ".")


def _file_imports(tree: ast.AST) -> Dict[str, str]:
    """alias -> dotted module for this file's module imports (both
    ``import a.b as m`` and ``from a import b``; ``from a import fn``
    also lands here and simply never suffix-matches a module)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


class CallGraph:
    """Def/use index + call resolution + bounded-depth raise summaries
    over one scan's parsed tree set."""

    def __init__(self) -> None:
        self.functions: Dict[FuncKey, FunctionInfo] = {}
        # module dotted name -> norm path (last definition wins; the
        # scan set has unique module paths so collisions don't matter)
        self._modules: Dict[str, str] = {}
        # per-file alias -> dotted module import map
        self._imports: Dict[str, Dict[str, str]] = {}
        # (norm, class or "") -> {method/function name -> qualname}
        self._scopes: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._raise_memo: Dict[FuncKey, Optional[str]] = {}

    # --- construction ---

    @classmethod
    def build(cls, trees: Dict[str, ast.AST]) -> "CallGraph":
        g = cls()
        for path, tree in trees.items():
            g.add_file(path, tree)
        return g

    def add_file(self, path: str, tree: ast.AST) -> None:
        norm = astutil.normalize_path(path)
        self._modules[_module_of(norm)] = norm
        self._imports[norm] = _file_imports(tree)
        for node in tree.body if hasattr(tree, "body") else []:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index(path, norm, None, node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._index(path, norm, node.name, sub)

    def _index(self, path: str, norm: str, cls_name: Optional[str],
               node: ast.AST) -> None:
        qual = f"{cls_name}.{node.name}" if cls_name else node.name
        info = FunctionInfo(path=path, norm=norm, qualname=qual,
                            cls=cls_name, node=node,
                            params=_positional_params(node))
        self.functions[info.key] = info
        self._scopes.setdefault((norm, cls_name or ""), {})[node.name] = qual

    # --- resolution ---

    def resolve(self, path: str, caller_cls: Optional[str],
                call: ast.Call) -> Optional[FunctionInfo]:
        """The FunctionInfo a call resolves to, or None (unknown
        receiver / not in the scan set). See the module docstring for
        the supported forms."""
        norm = astutil.normalize_path(path)
        func = call.func
        # self.m(...) -> method in the caller's class
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self" and caller_cls:
            qual = self._scopes.get((norm, caller_cls), {}).get(func.attr)
            return self.functions.get((norm, qual)) if qual else None
        # f(...) -> same-module function
        if isinstance(func, ast.Name):
            qual = self._scopes.get((norm, ""), {}).get(func.id)
            return self.functions.get((norm, qual)) if qual else None
        # mod.f(...) / alias.f(...) through the file's imports
        if isinstance(func, ast.Attribute):
            recv = astutil.dotted(func.value)
            if recv is None:
                return None
            mod = self._imports.get(norm, {}).get(recv.split(".")[0])
            if mod is not None:
                target = self._module_path(mod)
                if target is not None:
                    qual = self._scopes.get((target, ""), {}).get(func.attr)
                    if qual:
                        return self.functions.get((target, qual))
            return self._affinity_resolve(norm, recv, func.attr)
        return None

    def _affinity_resolve(self, norm: str, recv: str,
                          attr: str) -> Optional[FunctionInfo]:
        """Receiver-name affinity fallback for unknown-typed receivers:
        when exactly ONE same-file class whose name contains (or is
        contained by) the receiver's last segment defines ``attr``,
        resolve to that method — ``stats.summary()`` next to a single
        ``ServeStats`` class is unambiguous in practice. Anything less
        constrained stays unresolved (no guessing)."""
        seg = (astutil.last_segment(recv) or "").lstrip("_").lower()
        if not seg:
            return None
        hits: List[FuncKey] = []
        for (n, c), scope in self._scopes.items():
            if n != norm or not c or attr not in scope:
                continue
            cl = c.lower()
            if seg in cl or cl in seg:
                hits.append((n, scope[attr]))
        return self.functions.get(hits[0]) if len(hits) == 1 else None

    def _module_path(self, dotted_mod: str) -> Optional[str]:
        if dotted_mod in self._modules:
            return self._modules[dotted_mod]
        # suffix match: `from fira_tpu.robust import recovery` imports
        # module "fira_tpu.robust.recovery"
        for mod, norm in self._modules.items():
            if mod == dotted_mod or mod.endswith("." + dotted_mod):
                return norm
        return None

    # --- bounded-depth summaries ---

    def may_raise(self, info: FunctionInfo,
                  depth: int = SUMMARY_DEPTH) -> Optional[str]:
        """A human-readable description of a site inside ``info`` (or a
        callee, to ``depth`` levels) that can raise out of it, or None.
        Memoized; cycles read as in-progress -> None (a recursive chain
        adds no NEW raising site beyond what its body already shows)."""
        key = info.key
        if key in self._raise_memo:
            return self._raise_memo[key]
        self._raise_memo[key] = None  # cycle guard
        verdict = self._may_raise_walk(info, depth)
        self._raise_memo[key] = verdict
        return verdict

    def _may_raise_walk(self, info: FunctionInfo,
                        depth: int) -> Optional[str]:
        where = os.path.basename(info.path)
        for node in ast.walk(info.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not info.node:
                continue  # nested defs don't run at call time
            if isinstance(node, ast.Raise):
                return f"raise at {where}:{node.lineno}"
            if isinstance(node, ast.Assert):
                return f"assert at {where}:{node.lineno}"
            if isinstance(node, ast.Call):
                desc = self.call_may_raise(info.path, info.cls, node,
                                           depth=depth)
                if desc:
                    return desc
        return None

    def call_may_raise(self, path: str, caller_cls: Optional[str],
                       call: ast.Call,
                       depth: int = SUMMARY_DEPTH) -> Optional[str]:
        """May THIS call expression raise: injector check/corrupt sites
        (raise by contract under an armed chaos spec), the known-raising
        externals table, or a resolved callee whose own summary says so."""
        where = os.path.basename(path)
        name = astutil.call_name(call)
        if name in _RAISING_CALLS:
            return f"{name} at {where}:{call.lineno}"
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("check", "corrupt"):
            recv = astutil.dotted(call.func.value)
            seg = (astutil.last_segment(recv) or "").lower()
            if any(h in seg for h in _INJECTOR_HINTS):
                return (f"fault-injector .{call.func.attr}() at "
                        f"{where}:{call.lineno} (raises when armed)")
        if depth <= 0:
            return None
        target = self.resolve(path, caller_cls, call)
        if target is not None:
            inner = self.may_raise(target, depth - 1)
            if inner:
                return (f"{target.qualname}() at {where}:{call.lineno} "
                        f"-> {inner}")
        return None
