"""Suppression comments: ``# firacheck: allow[RULE-ID] <reason>``.

An inline comment waives the named rule(s) on its own line; a standalone
comment line waives them on the next source line (consecutive standalone
waivers stack onto the same target). The reason is MANDATORY and must name
the invariant being waived — a bare ``allow[...]`` is itself a
BAD-SUPPRESS error, so the committed baseline can't rot into cargo-cult
silencing. Multiple rules: ``allow[HOST-SYNC,RETRACE] reason``.

Suppressions are per-rule by construction: ``allow[HOST-SYNC]`` never
silences a DONATION finding on the same line (pinned by
tests/test_firacheck.py).
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Tuple

from fira_tpu.analysis.findings import RULES, Finding, Severity

_ALLOW_RE = re.compile(
    r"#\s*firacheck:\s*allow\[(?P<rules>[^\]]*)\]\s*(?P<reason>.*)$")
_MARKER_RE = re.compile(r"#\s*firacheck\b")


@dataclasses.dataclass
class Suppression:
    line: int            # line the comment sits on
    target: int          # line whose findings it waives
    rules: Tuple[str, ...]
    reason: str
    # usage is tracked PER RULE: allow[A,B] where only A ever matches must
    # still report B as stale, or the baseline stops shrinking
    used_rules: set = dataclasses.field(default_factory=set)


def _comments(source: str) -> List[Tuple[int, int, str]]:
    """(line, col, text) for every comment token; tolerant of files that
    tokenize cannot finish (returns what it saw before the error)."""
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def parse_suppressions(path: str, source: str
                       ) -> Tuple[List[Suppression], List[Finding]]:
    """Extract suppressions + BAD-SUPPRESS findings for malformed ones."""
    lines = source.splitlines()

    def next_code_line(after: int) -> int:
        for i in range(after, len(lines)):
            stripped = lines[i].strip()
            if stripped and not stripped.startswith("#"):
                return i + 1  # 1-based
        return after  # trailing comment: waives nothing real

    sups: List[Suppression] = []
    bad: List[Finding] = []
    for line, col, text in _comments(source):
        if not _MARKER_RE.search(text):
            continue
        m = _ALLOW_RE.search(text)
        if not m:
            bad.append(Finding(path, line, "BAD-SUPPRESS", Severity.ERROR,
                               f"unrecognized firacheck directive {text!r}; "
                               f"expected '# firacheck: allow[RULE-ID] "
                               f"<reason>'"))
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(",")
                      if r.strip())
        reason = m.group("reason").strip()
        unknown = [r for r in rules if r not in RULES]
        if not rules or unknown:
            bad.append(Finding(path, line, "BAD-SUPPRESS", Severity.ERROR,
                               f"unknown rule id(s) {unknown or '[]'} in "
                               f"suppression; known: {sorted(RULES)}"))
            continue
        if not reason:
            bad.append(Finding(path, line, "BAD-SUPPRESS", Severity.ERROR,
                               "suppression without a reason; name the "
                               "invariant this waiver trades away"))
            continue
        standalone = lines[line - 1].strip().startswith("#")
        target = next_code_line(line) if standalone else line
        sups.append(Suppression(line, target, rules, reason))
    return sups, bad


def apply_suppressions(findings: List[Finding], sups: List[Suppression]
                       ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, waived); marks suppressions used."""
    by_target: Dict[Tuple[int, str], List[Suppression]] = {}
    for s in sups:
        for r in s.rules:
            by_target.setdefault((s.target, r), []).append(s)
    kept, waived = [], []
    for f in findings:
        hits = by_target.get((f.line, f.rule))
        if hits:
            for s in hits:
                s.used_rules.add(f.rule)
            waived.append(f)
        else:
            kept.append(f)
    return kept, waived


def unused_suppressions(path: str, sups: List[Suppression]) -> List[Finding]:
    """A waiver (or a rule within a multi-rule waiver) that waives nothing
    is stale — surface it (warning) so the baseline shrinks when hazards
    get fixed for real."""
    out = []
    for s in sups:
        stale = [r for r in s.rules if r not in s.used_rules]
        if stale:
            out.append(Finding(
                path, s.line, "BAD-SUPPRESS", Severity.WARNING,
                f"unused suppression for {','.join(stale)} (no matching "
                f"finding on line {s.target}); delete it"))
    return out
