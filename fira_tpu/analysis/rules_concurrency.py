"""Concurrency-race rules: the bug family PRs 6-13 shipped and reviewers
caught by hand, mechanized (docs/ANALYSIS.md "v2: concurrency rules").

The serving stack is multi-threaded three ways: feeder worker threads
assemble payloads, watchdog worker threads run dispatches that may be
ABANDONED mid-flight (robust/watchdog.py), and the scheduler thread owns
the round loop. Every rule here encodes a discipline this repo already
fixed a real bug against:

- SHARED-MUT — a ``self._x`` attribute written under ``with self._lock``
  in some places but bare in others (the FaultInjector.fired class, PR 9
  review), or written bare from both a thread-entry method and a
  non-entry method (the MemoTally cross-count class, PR 13 review).
- RETIRED-RECHECK — shared scheduling/guard state mutated after a
  dispatch/readback boundary without re-checking ``self.retired``: the
  abandoned-watchdog-thread class fixed three separate times (PRs 9, 10,
  12 review rounds).
- SCHED-BLOCK — a blocking primitive (``time.sleep``, ``.wait()`` /
  ``.result()`` / ``.join()`` without a timeout, ``os.fsync``) inside a
  hot region of a driver module: the scheduler/worker hot paths must
  never block uncancellably (the PR 12 busy-spin/pause review round).
- WALL-CLOCK — ``time.time``/``perf_counter``/``monotonic`` in a module
  that schedules under the virtual clock, outside the ``*Clock`` classes:
  wall time leaking into virtual-clock replay broke determinism and a
  dimensionless stall fraction (PR 11 review, fourth pass).
- FLOAT-ORDER — float ``+=`` accumulation iterating an unordered /
  settle-ordered container in a threaded driver module: float addition
  does not reassociate, so the aggregate depends on thread interleaving
  in the last ulp (the PR 6 BLEU bug; fixed by summing in split order).

Scoping: all five run only in designated driver modules
(astutil._DRIVER_FILES) — plus, for WALL-CLOCK, only the modules that
actually schedule under ``serve.server.make_clock`` — so host-only text
cooking and checkpoint I/O never pay waiver noise.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from fira_tpu.analysis import astutil
from fira_tpu.analysis.findings import Finding, Severity

# modules whose scheduler runs under serve.server.make_clock (wall OR
# virtual): a raw wall-clock read outside the *Clock classes here either
# breaks virtual-replay determinism or divides a wall numerator by a
# virtual denominator. ingest stage stamps (ingest/service.py) are
# deliberately NOT in scope: they are worker-side wall metering,
# documented as schedule-dependent.
_VIRTUAL_CLOCK_FILES = (
    "fira_tpu/serve/server.py",
    "fira_tpu/parallel/fleet.py",
    "fira_tpu/decode/engine.py",
    "fira_tpu/robust/recovery.py",
)

# dispatch/readback boundaries a watchdog expiry can abandon a thread
# inside: device transfers/readbacks by name, and the engine's jitted
# entry points by self-attribute idiom (decode/engine.py)
_BOUNDARY_CALLS = {
    "jax.device_put", "jax.device_get", "device_put", "device_get",
    "jax.block_until_ready",
}
_BOUNDARY_SELF_ATTRS = {"_prefill", "_step", "_insert", "_take_rows"}
_BOUNDARY_ATTRS = {"copy_to_host_async", "block_until_ready"}

# container-mutating method names: a call self._x.append(...) mutates _x
_MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
    "add", "discard", "move_to_end",
}
# shared-state method calls the abandoned-thread discipline names
# explicitly: touching the (process-shared) compile guard from an
# abandoned thread races the live loop that owns it
_GUARD_SELF_CALLS = {"_guard_step"}

_BLOCKING_CALLS = {"time.sleep": "time.sleep",
                   "os.fsync": "os.fsync",
                   "sleep": "time.sleep",
                   "fsync": "os.fsync"}
_BLOCKING_ATTRS = {"wait", "result", "join"}  # flagged only with NO timeout
# lifecycle functions where blocking is the contract, not a stall:
# shutdown joins its threads, __exit__ drains, close flushes
_LIFECYCLE_FUNCS = {"close", "shutdown", "__exit__", "__del__", "stop"}

# bare names cover the `from time import time/perf_counter/monotonic`
# idiom; a bare-Name call cannot collide with `clock.time()`-style
# attribute calls, which resolve to a dotted name
_WALL_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "time", "perf_counter", "monotonic"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x``; None otherwise."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _mutated_attrs(node: ast.AST) -> List[str]:
    """EVERY self-attribute a statement-level node mutates:
    ``self.x = v`` / ``self.x += v`` / ``self.x[k] = v`` /
    ``self.a, self.b = ...`` (all tuple elements, not just the first) /
    ``self.x.append(v)``-style container calls."""
    out: List[str] = []
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                a = _self_attr(e)
                if a is None and isinstance(e, ast.Subscript):
                    a = _self_attr(e.value)
                if a:
                    out.append(a)
    elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        call = node.value
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _MUTATING_METHODS:
            recv = call.func.value
            a = _self_attr(recv)
            if a is None and isinstance(recv, ast.Subscript):
                a = _self_attr(recv.value)
            if a:
                out.append(a)
    return out


def _lockish_name(expr: ast.AST) -> Optional[str]:
    """The name of a lock-like context expression (``self._lock``,
    ``self._cond``, a bare ``lock`` variable), else None."""
    name = None
    a = _self_attr(expr)
    if a is not None:
        name = a
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Call):
        return _lockish_name(expr.func.value) \
            if isinstance(expr.func, ast.Attribute) else None
    if name is None:
        return None
    low = name.lower()
    if "lock" in low or "cond" in low or "mutex" in low:
        return name
    return None


def _under_lock(node: ast.AST, parents, stop: ast.AST) -> bool:
    for a in astutil.ancestors(node, parents):
        if a is stop:
            return False
        if isinstance(a, ast.With):
            for item in a.items:
                if _lockish_name(item.context_expr):
                    return True
    return False


def _methods(cls: ast.ClassDef) -> List[ast.AST]:
    return [n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _thread_entry_methods(cls: ast.ClassDef) -> Set[str]:
    """Methods of this class handed to a thread: ``Thread(target=self.m)``
    or ``pool.submit(self.m, ...)`` anywhere in the class body."""
    entries: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        callee = astutil.call_name(node)
        if callee and astutil.last_segment(callee) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    m = _self_attr(kw.value)
                    if m:
                        entries.add(m)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "submit" and node.args:
            m = _self_attr(node.args[0])
            if m:
                entries.add(m)
    return entries


def _reachable_methods(cls: ast.ClassDef, roots: Set[str]) -> Set[str]:
    """roots + methods they transitively call via ``self.m(...)``."""
    calls: Dict[str, Set[str]] = {}
    for m in _methods(cls):
        out: Set[str] = set()
        for node in ast.walk(m):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee:
                    out.add(callee)
        calls[m.name] = out
    reach = set(roots)
    frontier = list(roots)
    while frontier:
        m = frontier.pop()
        for callee in calls.get(m, ()):
            if callee in calls and callee not in reach:
                reach.add(callee)
                frontier.append(callee)
    return reach


@dataclasses.dataclass
class _Write:
    attr: str
    method: str
    line: int
    locked: bool


def check_shared_mut(path: str, tree: ast.AST, source: str, parents,
                     spans) -> List[Finding]:
    """SHARED-MUT: per-class write-site registry + lock inference."""
    if not astutil.is_driver_module(path):
        return []
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        writes: List[_Write] = []
        for m in _methods(cls):
            if m.name == "__init__":
                continue  # construction precedes sharing: no lock needed
            for node in ast.walk(m):
                for attr in _mutated_attrs(node):
                    writes.append(_Write(attr, m.name, node.lineno,
                                         _under_lock(node, parents, m)))
        by_attr: Dict[str, List[_Write]] = {}
        for w in writes:
            by_attr.setdefault(w.attr, []).append(w)
        entries = _thread_entry_methods(cls)
        reach = _reachable_methods(cls, entries) if entries else set()
        for attr, sites in sorted(by_attr.items()):
            locked = [w for w in sites if w.locked]
            bare = [w for w in sites if not w.locked]
            if locked and bare:
                lw = locked[0]
                for w in bare:
                    findings.append(Finding(
                        path, w.line, "SHARED-MUT", Severity.ERROR,
                        f"`self.{attr}` is written under a lock in "
                        f"{cls.name}.{lw.method} (line {lw.line}) but bare "
                        f"here in {cls.name}.{w.method}: the lock protects "
                        f"nothing unless every write site holds it"))
            elif bare and reach:
                worker = [w for w in bare if w.method in reach]
                owner = [w for w in bare if w.method not in reach]
                if worker and owner:
                    ow = owner[0]
                    for w in worker:
                        findings.append(Finding(
                            path, w.line, "SHARED-MUT", Severity.ERROR,
                            f"`self.{attr}` is mutated on a thread-entry "
                            f"path ({cls.name}.{w.method}) and from "
                            f"{cls.name}.{ow.method} (line {ow.line}) with "
                            f"no lock on either side: an unsynchronized "
                            f"cross-thread read-modify-write"))
    return findings


def _retire_capable(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if _self_attr(t) == "retired":
                    return True
    return False


def _is_boundary_call(node: ast.Call) -> bool:
    name = astutil.call_name(node)
    if name in _BOUNDARY_CALLS:
        return True
    if isinstance(node.func, ast.Attribute):
        if node.func.attr in _BOUNDARY_ATTRS:
            return True
        if node.func.attr in _BOUNDARY_SELF_ATTRS \
                and _self_attr(node.func) is not None:
            return True
    return False


def _reads_retired(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "retired" \
                and isinstance(n.ctx, ast.Load):
            return True
    return False


def check_retired_recheck(path: str, tree: ast.AST, source: str, parents,
                          spans) -> List[Finding]:
    """RETIRED-RECHECK: in a retire-capable class, shared state mutated
    after a dispatch/readback boundary with no ``self.retired`` re-check
    in between — the abandoned-watchdog-thread race (docs/FAULTS.md)."""
    if not astutil.is_driver_module(path):
        return []
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef) or not _retire_capable(cls):
            continue
        for m in _methods(cls):
            if m.name in ("__init__", "retire", "prewarm"):
                # __init__ precedes sharing; retire() is the far side of
                # the race; prewarm is the watchdog's PREcondition
                # (docs/FAULTS.md) — it runs before any watchdogged
                # dispatch exists (or on a fresh unshared replacement
                # engine during respawn), never on an abandonable thread
                continue
            events: List[Tuple[int, int, str, int]] = []  # (line, rank, kind, aux)
            for node in ast.walk(m):
                if isinstance(node, (ast.If, ast.While)) \
                        and _reads_retired(node.test):
                    # the check covers everything after its own line —
                    # including a `while not self.retired` loop's body
                    events.append((node.lineno, 1, "check", 0))
                elif isinstance(node, ast.Call) and _is_boundary_call(node):
                    events.append((node.lineno, 2, "boundary", 0))
                else:
                    # setting the flag itself is the discipline, not a
                    # hazard
                    attrs = [a for a in _mutated_attrs(node)
                             if a != "retired"]
                    guard_call = (
                        isinstance(node, ast.Expr)
                        and isinstance(node.value, ast.Call)
                        and _self_attr(node.value.func) in _GUARD_SELF_CALLS)
                    if attrs or guard_call:
                        # rank 3: a store whose RHS holds the boundary call
                        # completes AFTER the call returns — same line, the
                        # mutation is on the abandoned side of the window
                        events.append((node.lineno, 3, "mutation",
                                       1 if guard_call else 0))
            events.sort()
            pending: Optional[int] = None
            for line, _rank, kind, aux in events:
                if kind == "check":
                    pending = None
                elif kind == "boundary":
                    pending = line
                elif pending is not None:
                    what = ("the shared compile guard" if aux
                            else "shared scheduling state")
                    findings.append(Finding(
                        path, line, "RETIRED-RECHECK", Severity.ERROR,
                        f"{cls.name}.{m.name} mutates {what} after the "
                        f"dispatch/readback boundary at line {pending} "
                        f"without re-checking `self.retired`: a watchdog "
                        f"expiry abandons this thread mid-call, retire() "
                        f"hands the state to survivors, and this write "
                        f"races them (the PR 9/10/12 bug class)"))
                    pending = line  # one finding per mutation, keep arming
    return findings


def _has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or bool(call.keywords)


def _in_clock_class(node: ast.AST, parents) -> bool:
    for a in astutil.ancestors(node, parents):
        if isinstance(a, ast.ClassDef) and a.name.endswith("Clock"):
            return True
    return False


def _in_lifecycle_func(node: ast.AST, parents) -> bool:
    fn = astutil.enclosing_function(node, parents)
    return getattr(fn, "name", None) in _LIFECYCLE_FUNCS


def check_sched_block(path: str, tree: ast.AST, source: str, parents,
                      spans) -> List[Finding]:
    """SCHED-BLOCK: uncancellable blocking primitives on driver hot
    paths (outside the *Clock helpers and lifecycle shutdown funcs)."""
    if not astutil.is_driver_module(path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        region = astutil.hot_region_at(spans, node.lineno)
        if region is None:
            continue
        if _in_clock_class(node, parents) or _in_lifecycle_func(node, parents):
            continue
        name = astutil.call_name(node)
        what = None
        if name in _BLOCKING_CALLS:
            what = f"{_BLOCKING_CALLS[name]}(...)"
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _BLOCKING_ATTRS
              and not _has_timeout(node)):
            what = f".{node.func.attr}() with no timeout"
        if what:
            findings.append(Finding(
                path, node.lineno, "SCHED-BLOCK", Severity.ERROR,
                f"{what} inside hot region [{region.desc}]: the scheduler/"
                f"worker hot path blocks uncancellably — route it through "
                f"the clock/backoff helpers, give it a timeout, or waive "
                f"the boundary with a reason"))
    return findings


def check_wall_clock(path: str, tree: ast.AST, source: str, parents,
                     spans) -> List[Finding]:
    """WALL-CLOCK: raw wall-clock reads in modules that schedule under
    serve.server.make_clock, outside the *Clock classes."""
    norm = astutil.normalize_path(path)
    if not any(norm.endswith(f) for f in _VIRTUAL_CLOCK_FILES):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if name not in _WALL_CALLS:
            continue
        if _in_clock_class(node, parents):
            continue
        findings.append(Finding(
            path, node.lineno, "WALL-CLOCK", Severity.ERROR,
            f"{name}() in a module that schedules under make_clock: wall "
            f"time outside the *Clock classes leaks real time into "
            f"virtual-clock replay (or divides wall by virtual) — read "
            f"the loop's clock, or waive the metering boundary with a "
            f"reason"))
    return findings


def _unordered_iter(it: ast.AST) -> Optional[str]:
    """A description of why the iterable's order is settle/schedule
    -dependent, or None. ``sorted(...)`` wrappers are the fix and never
    match (the call name is then 'sorted')."""
    if isinstance(it, ast.Call):
        if isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("values", "items", "keys"):
            return f".{it.func.attr}() of a settle-ordered mapping"
        name = astutil.call_name(it)
        if name in ("set", "frozenset"):
            return "a set (iteration order is unspecified)"
    if isinstance(it, ast.Set):
        return "a set literal"
    return None


def check_float_order(path: str, tree: ast.AST, source: str, parents,
                      spans) -> List[Finding]:
    """FLOAT-ORDER: float accumulation over settle-ordered iteration in
    threaded driver modules (the PR 6 BLEU bug class)."""
    if not astutil.is_driver_module(path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.For):
            continue
        why = _unordered_iter(node.iter)
        if why is None:
            continue
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.AugAssign)
                    and isinstance(sub.op, ast.Add)):
                continue
            v = sub.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                continue  # integer counting is order-safe
            findings.append(Finding(
                path, sub.lineno, "FLOAT-ORDER", Severity.ERROR,
                f"float `+=` accumulation iterating {why} (loop at line "
                f"{node.lineno}): float addition does not reassociate, so "
                f"the aggregate depends on settle/thread order in the "
                f"last ulp — accumulate per key and sum in sorted order "
                f"(the PR 6 BLEU fix)"))
    return findings


def check(path: str, tree: ast.AST, source: str, parents, spans,
          ) -> List[Finding]:
    findings: List[Finding] = []
    findings += check_shared_mut(path, tree, source, parents, spans)
    findings += check_retired_recheck(path, tree, source, parents, spans)
    findings += check_sched_block(path, tree, source, parents, spans)
    findings += check_wall_clock(path, tree, source, parents, spans)
    findings += check_float_order(path, tree, source, parents, spans)
    return findings
