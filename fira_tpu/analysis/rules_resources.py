"""RES-LEAK: interprocedural resource-lifecycle analysis (firacheck v3).

The bug class (CHANGES.md PRs 9–13 review rounds): a resource is
acquired, a statement between the acquire and the release can raise,
and no ``finally``/``with`` covers the release — the exception strands
the resource. Intra-procedural linting cannot see the worst instances
because the raising statement is often an innocent-looking helper call
(``self.append(...)`` whose body fsyncs; a prefill helper with an
``assert``); v3 resolves those calls through the module-set
:mod:`callgraph` and uses its bounded-depth may-raise summaries.

Tracked resources — the repo's REAL lifecycles, nothing speculative:

==============================  =======================================
acquire                         release / handoff
==============================  =======================================
``x = *._acquire_blocks(n)``    ``*._release_blocks(x)``
``t = Thread(...); t.start()``  ``t.join(...)``
``p = ThreadPoolExecutor(..)``  ``p.shutdown(...)`` or ``with``
``f = open(...)``               ``f.close()`` or ``with``
``ev = threading.Event()``      ``ev.set()`` (follower wakeup handoff)
==============================  =======================================

Window semantics (one window per acquired binding, statements walked in
source order):

- **close** on the release call, on ``join``/``shutdown``/``close``.
- **ownership transfer** closes the window without complaint: storing
  the value into ``self.*`` or any subscript, returning/yielding it, or
  passing it as an argument to any other call (the callee or container
  owns it now — each frame is responsible for its own window).
- **``__init__`` is special**: ``self.attr = <resource>`` does NOT
  transfer — until ``__init__`` returns, no caller holds the object, so
  an exception after the store strands the resource with nobody able to
  close it (the Journal-fsync class of bug). The window is renamed to
  the attribute and runs to the end of ``__init__``; reaching the end
  closes it silently (the constructed object now owns it).
- **fire** when a statement inside an open window may raise — a
  ``raise``/``assert``, a known-raising call, or a call whose
  :meth:`~fira_tpu.analysis.callgraph.CallGraph.may_raise` summary says
  so — and neither the acquire nor the raising statement sits under a
  ``try`` whose ``finally`` (or an except handler) performs the
  release. The finding lands at the ACQUIRE line and names the
  escaping path.
- a window still open at the end of the function (never released,
  never handed off) fires as a straight leak — except ``Event``
  windows, whose release legitimately belongs to another component.

Scope: driver modules only (``astutil.is_driver_module``), same arming
as the v2 concurrency rules. Acquires not bound to a name are not
tracked (no binding, no window — document-level honesty over guessing).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from fira_tpu.analysis import astutil
from fira_tpu.analysis.callgraph import CallGraph
from fira_tpu.analysis.dataflow import iter_statements, name_loads, \
    target_names
from fira_tpu.analysis.findings import Finding, Severity

_BLOCK_ACQUIRES = {"_acquire_blocks", "acquire_blocks"}
_BLOCK_RELEASES = {"_release_blocks", "release_blocks"}
_POOL_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}

# kind -> receiver-method that closes its window
_METHOD_RELEASES = {
    "thread": "join",
    "pool": "shutdown",
    "file": "close",
    "event": "set",
}
_SIMPLE_STMTS = (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
                 ast.Return, ast.Raise, ast.Assert, ast.Delete)


@dataclasses.dataclass
class _Window:
    kind: str            # blocks | thread | pool | file | event
    what: str            # human description of the acquire
    line: int            # acquire line (where the finding lands)
    acquire_stmt: ast.stmt
    fired: bool = False


def _acquire_of(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, description) when ``call`` is a tracked acquire."""
    seg = astutil.last_segment(astutil.call_name(call) or "")
    if seg in _BLOCK_ACQUIRES:
        return "blocks", f"KV block grant from {seg}()"
    if seg in _POOL_CTORS:
        return "pool", f"{seg} worker pool"
    if seg == "open" and isinstance(call.func, ast.Name):
        return "file", "open() file handle"
    if seg == "Event":
        return "event", "threading.Event follower wakeup"
    return None


def _pending_thread(call: ast.Call) -> bool:
    return astutil.last_segment(astutil.call_name(call) or "") == "Thread"


def _receiver(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return astutil.dotted(call.func.value)
    return None


def _arg_names(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for a in list(call.args) + [k.value for k in call.keywords]:
        out.update(name_loads(a))
    return out


def _stmt_calls(stmt: ast.stmt) -> List[ast.Call]:
    """Calls evaluated BY this statement itself: a simple statement's
    whole subtree; only the header expressions of compound statements
    (their bodies are walked as their own statements)."""
    if isinstance(stmt, _SIMPLE_STMTS):
        roots: List[ast.AST] = [stmt]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.iter]
    elif isinstance(stmt, (ast.While, ast.If)):
        roots = [stmt.test]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [i.context_expr for i in stmt.items]
    else:
        return []
    out: List[ast.Call] = []
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                break
            if isinstance(node, ast.Call):
                out.append(node)
    return out


def _stmt_may_raise(stmt: ast.stmt, graph: CallGraph, path: str,
                    cls: Optional[str]) -> Optional[str]:
    if isinstance(stmt, ast.Raise):
        return f"raise at line {stmt.lineno}"
    if isinstance(stmt, ast.Assert):
        return f"assert at line {stmt.lineno}"
    for call in _stmt_calls(stmt):
        desc = graph.call_may_raise(path, cls, call)
        if desc:
            return desc
    return None


def _releases_in(nodes: List[ast.stmt], win: _Window) -> bool:
    """Does any statement in ``nodes`` perform a release for ``win``'s
    kind? (Used for try/finally + except-handler protection checks —
    name-insensitive on purpose: a finally that releases the KIND is
    accepted as covering the window.)"""
    for stmt in nodes:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            seg = astutil.last_segment(astutil.call_name(node) or "")
            if win.kind == "blocks" and seg in _BLOCK_RELEASES:
                return True
            if seg == _METHOD_RELEASES.get(win.kind):
                return True
    return False


def _protected(stmt: ast.stmt, win: _Window, parents) -> bool:
    """Is a raise inside ``stmt`` covered: some enclosing ``try`` (of
    the raising statement or of the acquire) releases the window's kind
    in its ``finally`` or an except handler."""
    for anchor in (stmt, win.acquire_stmt):
        for anc in astutil.ancestors(anchor, parents):
            if isinstance(anc, ast.Try):
                if _releases_in(anc.finalbody, win):
                    return True
                for h in anc.handlers:
                    if _releases_in(h.body, win):
                        return True
    return False


class _FunctionScan:
    def __init__(self, path: str, cls: Optional[str], fn: ast.AST,
                 graph: CallGraph, parents) -> None:
        self.path = path
        self.cls = cls
        self.fn = fn
        self.graph = graph
        self.parents = parents
        self.in_init = fn.name == "__init__"
        self.windows: Dict[str, _Window] = {}
        self.pending_threads: Set[str] = set()
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        stmts = list(iter_statements(self.fn.body))
        for stmt in stmts:
            self._close_releases(stmt)
            self._check_raises(stmt)
            self._close_transfers(stmt)
            self._open_acquires(stmt)
        for name, win in self.windows.items():
            if win.fired or win.kind == "event":
                continue
            if self.in_init and name.startswith("self."):
                continue  # constructed object owns it now
            self.findings.append(Finding(
                self.path, win.line, "RES-LEAK", Severity.ERROR,
                f"{win.what} bound to '{name}' is never released or "
                f"handed off on the fall-through path",
            ))
        return self.findings

    # -- stages --

    def _close_releases(self, stmt: ast.stmt) -> None:
        for call in _stmt_calls(stmt):
            seg = astutil.last_segment(astutil.call_name(call) or "")
            recv = _receiver(call)
            if seg in _BLOCK_RELEASES:
                args = _arg_names(call)
                for name in [n for n, w in self.windows.items()
                             if w.kind == "blocks"
                             and (n in args or not args)]:
                    del self.windows[name]
                continue
            if recv in self.windows \
                    and seg == _METHOD_RELEASES.get(self.windows[recv].kind):
                del self.windows[recv]

    def _check_raises(self, stmt: ast.stmt) -> None:
        if not self.windows:
            return
        desc = _stmt_may_raise(stmt, self.graph, self.path, self.cls)
        if not desc:
            return
        for name, win in self.windows.items():
            if win.fired or stmt is win.acquire_stmt:
                continue
            if _protected(stmt, win, self.parents):
                continue
            win.fired = True
            self.findings.append(Finding(
                self.path, win.line, "RES-LEAK", Severity.ERROR,
                f"{win.what} can leak: {desc} can raise before the "
                f"release of '{name}' with no finally/with covering it",
            ))

    def _close_transfers(self, stmt: ast.stmt) -> None:
        if not self.windows:
            return
        # store into self.* or any subscript; return/yield
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            reads = name_loads(stmt.value) if stmt.value is not None else []
            for name in [n for n in list(self.windows) if n in reads]:
                for t in targets:
                    names = target_names(t)
                    self_store = any(x.startswith("self.") for x in names)
                    if self_store and self.in_init:
                        # rename: the half-built object holds it now, but
                        # no caller can close it until __init__ returns
                        for x in names:
                            if x.startswith("self."):
                                self.windows[x] = self.windows.pop(name)
                                break
                    elif self_store or isinstance(t, ast.Subscript):
                        self.windows.pop(name, None)
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            for name in name_loads(stmt.value):
                self.windows.pop(name, None)
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     (ast.Yield,
                                                      ast.YieldFrom)):
            val = stmt.value.value
            for name in (name_loads(val) if val is not None else []):
                self.windows.pop(name, None)
        # handoff: the value passed as an argument to any call
        for call in _stmt_calls(stmt):
            seg = astutil.last_segment(astutil.call_name(call) or "")
            if seg in _BLOCK_RELEASES:
                continue  # handled as a release
            for name in _arg_names(call) & set(self.windows):
                del self.windows[name]

    def _open_acquires(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return  # context manager = protected by construction
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                or stmt.value is None or not isinstance(stmt.value, ast.Call):
            # `t.start()` promotes a pending thread binding to a window
            if isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call):
                call = stmt.value
                recv = _receiver(call)
                if recv in self.pending_threads and isinstance(
                        call.func, ast.Attribute) and call.func.attr == "start":
                    self.windows[recv] = _Window(
                        "thread", "started Thread", stmt.lineno, stmt)
            return
        call = stmt.value
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        names = [n for t in targets for n in target_names(t)]
        if not names:
            return
        if _pending_thread(call):
            self.pending_threads.update(names)
            return
        hit = _acquire_of(call)
        if hit is None:
            return
        kind, what = hit
        self.windows[names[0]] = _Window(kind, what, stmt.lineno, stmt)


def check(path: str, tree: ast.AST, source: str, parents,
          graph: CallGraph) -> List[Finding]:
    if not astutil.is_driver_module(path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = None
        for anc in astutil.ancestors(node, parents):
            if isinstance(anc, ast.ClassDef):
                cls = anc.name
                break
        findings.extend(_FunctionScan(path, cls, node, graph, parents).run())
    return findings
