"""DET-TAINT: byte-determinism taint analysis (firacheck v3).

The serving contract says output bytes are a pure function of the
request stream (docs/SERVING.md). FLOAT-ORDER (v2) catches ONE local
shape of the violation — a float ``+=`` inside an unordered loop. The
general bug is a FLOW: a value whose identity depends on
nondeterministic ORDER reaches a sink that commits bytes, and the
source and sink are frequently in different statements or different
functions. This rule runs the :class:`~fira_tpu.analysis.dataflow.
ForwardPass` taint engine over every function in a driver module, with
call-graph summaries carrying taint across function boundaries.

**Sources** (order-nondeterminism enters a value):

- iteration over ``.values()`` / ``.items()`` / ``.keys()`` / a set —
  settle/insertion order (same detector family as FLOAT-ORDER, but
  producing a taint instead of requiring the ``+=`` right there);
- ``os.listdir(...)`` — the OS returns directory entries unsorted;
- ``as_completed(...)`` — thread completion order;
- ``queue.get()``-drained batches are NOT flagged (the repo's queues
  are single-producer FIFO by design — see docs/ANALYSIS.md);
- a call to a scanned function whose RETURN value is tainted
  (bounded-depth, memoized — the interprocedural half).

``sorted(...)`` launders its whole subtree: every taint here is an
order fact, and sorted() re-establishes a deterministic order.

**Sinks** (bytes get committed):

- ``<writer>.add(...)`` — OrderedStreamWriter output lines;
- ``json.dump/dumps(...)`` — serve_metrics.json / journal payloads;
- ``<journal>.append(...)`` — the recovery journal;
- ``write_metrics_atomic(...)``;
- keyed digests — ``times_digest(...)``, ``hashlib`` constructions,
  ``<digest>.update(...)``;
- BLEU accumulation — a call whose name mentions ``bleu``;
- passing a tainted value to a scanned function that forwards that
  parameter into one of the above (the caller-side interprocedural
  check; fires at the call, naming the callee's sink).

Scope: driver modules only. Severity ERROR — a hit is a reproducible
byte-contract break, not a style nit.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from fira_tpu.analysis import astutil
from fira_tpu.analysis.callgraph import CallGraph, FunctionInfo, FuncKey
from fira_tpu.analysis.dataflow import ForwardPass
from fira_tpu.analysis.findings import Finding, Severity

_DIGEST_HINTS = ("digest", "hash", "blake", "sha", "md5")
_WRITER_HINTS = ("writer", "stream")
_JOURNAL_HINTS = ("journal",)
_SUMMARY_DEPTH = 3
_PARAM_MARK = "\x00param:"  # internal seed label for param->sink summaries


def _unordered_source(node: ast.AST) -> Optional[str]:
    """Settle-order iteration sources (the FLOAT-ORDER detector family,
    yielding a description instead of a finding). Dict-view iteration
    counts only on ``self.*`` receivers: shared instance state is what
    threads populate in settle order — a local dict built from literal
    keys in the same frame iterates deterministically."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("values", "items", "keys") \
            and not node.args:
        owner = astutil.dotted(node.func.value) or ""
        if owner.startswith("self."):
            return f"{owner}.{node.func.attr}() settle order"
        return None
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return f"{node.func.id}() iteration order"
    if isinstance(node, ast.Set):
        return "set-literal iteration order"
    return None


def _sink_of(call: ast.Call) -> Optional[str]:
    """Byte-sink description for a call, or None."""
    name = astutil.call_name(call) or ""
    seg = astutil.last_segment(name) or ""
    if seg in ("dump", "dumps") and name.startswith("json"):
        return f"json.{seg}() serialization"
    if seg == "write_metrics_atomic":
        return "write_metrics_atomic() metrics bytes"
    if seg == "times_digest" or seg in ("blake2b", "blake2s", "sha256",
                                        "sha1", "md5"):
        return f"{seg}() keyed digest"
    if "bleu" in seg.lower():
        return f"{seg}() BLEU accumulation"
    if isinstance(call.func, ast.Attribute):
        recv = (astutil.last_segment(astutil.dotted(call.func.value) or "")
                or "").lower()
        if call.func.attr == "add" and any(h in recv for h in _WRITER_HINTS):
            return "OrderedStreamWriter.add() output line"
        if call.func.attr == "append" \
                and any(h in recv for h in _JOURNAL_HINTS):
            return "journal.append() record"
        if call.func.attr == "update" \
                and any(h in recv for h in _DIGEST_HINTS):
            return f"{recv}.update() digest"
    return None


class _TaintScan:
    """One file's DET-TAINT pass, with memoized cross-function
    summaries resolved through the scan-wide call graph."""

    def __init__(self, graph: CallGraph) -> None:
        self.graph = graph
        self._returns_memo: Dict[FuncKey, Optional[str]] = {}
        self._param_sink_memo: Dict[FuncKey, Dict[str, str]] = {}

    # -- summaries --

    def returns_taint(self, info: FunctionInfo,
                      depth: int = _SUMMARY_DEPTH) -> Optional[str]:
        """Does a call to ``info`` return an order-tainted value?"""
        if info.key in self._returns_memo:
            return self._returns_memo[info.key]
        self._returns_memo[info.key] = None  # cycle guard
        found: List[str] = []

        def on_stmt(stmt: ast.stmt, env: Dict[str, str]) -> None:
            if isinstance(stmt, ast.Return) and stmt.value is not None \
                    and not found:
                label = walker.expr_label(stmt.value, env)
                if label:
                    found.append(label)

        walker = ForwardPass(self._source_fn(info, depth - 1), on_stmt)
        walker.run(info.node.body)
        verdict = found[0] if found else None
        self._returns_memo[info.key] = verdict
        return verdict

    def param_sinks(self, info: FunctionInfo) -> Dict[str, str]:
        """param name -> sink description, for parameters ``info``
        forwards into a byte sink (one summary level)."""
        if info.key in self._param_sink_memo:
            return self._param_sink_memo[info.key]
        self._param_sink_memo[info.key] = {}  # cycle guard
        params = [p for p in info.params if p != "self"]
        seed = {p: f"{_PARAM_MARK}{p}" for p in params}
        hits: Dict[str, str] = {}

        def on_stmt(stmt: ast.stmt, env: Dict[str, str]) -> None:
            for call in [n for n in ast.walk(stmt)
                         if isinstance(n, ast.Call)]:
                sink = _sink_of(call)
                if not sink:
                    continue
                for a in list(call.args) + [k.value for k in call.keywords]:
                    label = walker.expr_label(a, env)
                    if label and label.startswith(_PARAM_MARK):
                        hits.setdefault(label[len(_PARAM_MARK):], sink)

        walker = ForwardPass(lambda node: None, on_stmt)
        walker.run(info.node.body, seed_env=seed)
        self._param_sink_memo[info.key] = hits
        return hits

    # -- per-function scan --

    def _source_fn(self, info: FunctionInfo, depth: int):
        def source(node: ast.AST) -> Optional[str]:
            hit = _unordered_source(node)
            if hit:
                return hit
            if not isinstance(node, ast.Call):
                return None
            seg = astutil.last_segment(astutil.call_name(node) or "")
            if seg == "listdir":
                return "os.listdir() scan order"
            if seg == "as_completed":
                return "as_completed() thread-completion order"
            if depth > 0:
                target = self.graph.resolve(info.path, info.cls, node)
                if target is not None and target.key != info.key:
                    inner = self.returns_taint(target, depth)
                    if inner:
                        return f"{target.qualname}() -> {inner}"
            return None
        return source

    def scan_function(self, info: FunctionInfo) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()

        def on_stmt(stmt: ast.stmt, env: Dict[str, str]) -> None:
            for call in [n for n in ast.walk(stmt)
                         if isinstance(n, ast.Call)]:
                args = list(call.args) + [k.value for k in call.keywords]
                sink = _sink_of(call)
                if sink:
                    for a in args:
                        label = walker.expr_label(a, env)
                        if label:
                            self._emit(findings, seen, info.path,
                                       call.lineno, label, sink)
                            break
                    continue
                target = self.graph.resolve(info.path, info.cls, call)
                if target is None or target.key == info.key:
                    continue
                forwarded = self.param_sinks(target)
                if not forwarded:
                    continue
                params = [p for p in target.params if p != "self"]
                for i, a in enumerate(call.args):
                    if i >= len(params) or params[i] not in forwarded:
                        continue
                    label = walker.expr_label(a, env)
                    if label:
                        self._emit(
                            findings, seen, info.path, call.lineno, label,
                            f"{forwarded[params[i]]} inside "
                            f"{target.qualname}()")

        walker = ForwardPass(self._source_fn(info, _SUMMARY_DEPTH), on_stmt)
        walker.run(info.node.body)
        return findings

    @staticmethod
    def _emit(findings: List[Finding], seen: Set[Tuple[int, str]],
              path: str, line: int, label: str, sink: str) -> None:
        key = (line, sink)
        if key in seen:
            return
        seen.add(key)
        findings.append(Finding(
            path, line, "DET-TAINT", Severity.ERROR,
            f"nondeterministic value ({label}) flows into byte sink: "
            f"{sink}",
        ))


def check(path: str, tree: ast.AST, source: str, parents,
          graph: CallGraph) -> List[Finding]:
    if not astutil.is_driver_module(path):
        return []
    scan = _TaintScan(graph)
    norm = astutil.normalize_path(path)
    findings: List[Finding] = []
    for info in graph.functions.values():
        if info.norm == norm:
            findings.extend(scan.scan_function(info))
    return findings
