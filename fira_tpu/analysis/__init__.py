"""firacheck — JAX-hazard static analyzer + runtime sanitizer.

The repo's throughput wins rest on invariants that used to live only in
prose (README "Design notes", docs/PERF.md): the driver never syncs with
the device except at logging/dev boundaries, train-step buffers are
donated, every program compiles exactly once over fixed geometry, and PRNG
keys are never reused. firacheck turns those into machine-checked
contracts:

- static: ``python -m fira_tpu.analysis.cli check fira_tpu tests scripts``
  walks the AST of every file and emits ``file:line [RULE-ID] severity:
  message`` findings (nonzero exit on errors; ``--json`` for the
  machine-readable artifact, ``--rules`` for a family-scoped gate).
  v1 rules: HOST-SYNC, RETRACE, DONATION, PRNG-REUSE, DISCARDED-AT,
  GEOMETRY-DRIFT. v2 concurrency rules (the serving stack's bug family):
  SHARED-MUT, RETIRED-RECHECK, SCHED-BLOCK, WALL-CLOCK, FLOAT-ORDER.
  v2 contract lints: KNOB-VALIDATE, FAULT-SITE, DRIVER-REG — see
  docs/ANALYSIS.md for each rule's rationale and provenance.
- runtime: ``--sanitize`` on the train/test CLIs arms
  ``analysis.sanitizer`` — jax_debug_nans/jax_debug_infs plus a
  jax_log_compiles capture whose per-program compile-count guard raises if
  any step after a program's first dispatch triggers a new compilation,
  plus the ThreadGuard lock-discipline sanitizer: declared threaded
  structures (ingest cache/memos, fault accounting, the feeder channel)
  raise on any mutation without their owning lock and record
  lock-acquisition order to flag inversions.

Deliberate boundary syncs are waived in place with
``# firacheck: allow[RULE-ID] <reason naming the invariant>``; a reason is
mandatory (a bare allow is itself a finding).
"""

from fira_tpu.analysis.findings import Finding, Severity  # noqa: F401
from fira_tpu.analysis.engine import check_paths, check_source  # noqa: F401
