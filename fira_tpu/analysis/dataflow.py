"""Forward dataflow walker shared by the v3 interprocedural rules.

Two consumers with the same core need — "follow a value through the
statements of one function, in order" — and deliberately the same
simplifications:

- **Flow is syntactic**: statements are visited in source order,
  descending into compound bodies (if/for/while/with/try). Branches are
  NOT joined path-sensitively — a binding made in an ``if`` arm is
  visible after it (may-analysis: we want "can this happen on SOME
  path", which over-approximating branch joins gives us for free).
- **Loops run the transfer twice** so a fact produced at the bottom of
  a loop body reaches uses at the top (one extra pass reaches the
  fixpoint for the single-level facts tracked here — labels don't
  compose, they only spread).
- **Names only**: facts attach to local variable names and, read-only,
  to ``self.attr`` reads. Tuple targets spread the RHS fact to every
  element (over-approximate); subscript/attribute stores drop it
  (ownership transferred out of the local frame — the caller's rule
  decides what that means).

:class:`ForwardPass` is the engine; rules subclass nothing — they hand
it two callables (``source`` classifies an expression as introducing a
fact, ``on_stmt`` observes the post-transfer environment at every
statement) and read the results.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterator, List, Optional, Tuple

# bodies that nest statements; Try's handlers/orelse/finalbody handled
# explicitly in iter_statements
_BODY_FIELDS = ("body", "orelse", "finalbody")


def iter_statements(body: List[ast.stmt]) -> Iterator[ast.stmt]:
    """Pre-order statement walk in source order, descending into every
    compound-statement body (but NOT into nested function/class defs —
    those have their own frames)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for field in _BODY_FIELDS:
            sub = getattr(stmt, field, None)
            if sub:
                yield from iter_statements(sub)
        for handler in getattr(stmt, "handlers", ()) or ():
            yield from iter_statements(handler.body)


def name_loads(expr: ast.AST) -> List[str]:
    """Local names read anywhere inside ``expr`` (Load context), plus
    ``self.attr`` reads rendered as ``"self.attr"``."""
    out: List[str] = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.append(node.id)
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            out.append(f"self.{node.attr}")
    return out


def target_names(target: ast.AST) -> List[str]:
    """Bindable names in an assignment target: plain names and
    ``self.attr`` stores; tuple/list targets flattened. Subscript and
    non-self attribute stores yield nothing (fact leaves the frame)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        return [f"self.{target.attr}"]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            out.extend(target_names(el))
        return out
    if isinstance(target, ast.Starred):
        return target_names(target.value)
    return []


class ForwardPass:
    """Forward may-propagation of string-labelled facts over one
    function body.

    ``source(expr) -> Optional[str]`` names the fact an expression
    introduces (or None). Facts then spread through assignments,
    augmented assignments, for-targets, and with-items; any expression
    that READS a labelled name carries that label. ``on_stmt(stmt,
    env)`` fires for every statement on the FINAL pass with the
    environment as of just after that statement — rules do their sink
    checks there.
    """

    def __init__(self, source: Callable[[ast.AST], Optional[str]],
                 on_stmt: Optional[
                     Callable[[ast.stmt, Dict[str, str]], None]] = None
                 ) -> None:
        self._source = source
        self._on_stmt = on_stmt

    def expr_label(self, expr: Optional[ast.AST],
                   env: Dict[str, str]) -> Optional[str]:
        """The fact ``expr`` carries under ``env``: a direct source hit
        wins (most specific description), else the first labelled name
        it reads. Everything under a ``sorted(...)`` call is laundered —
        the facts tracked here are ORDER facts, and a sorted() wrapper
        re-establishes a deterministic order for its whole subtree."""
        if expr is None:
            return None
        covered = _sorted_covered(expr)
        for node in ast.walk(expr):
            if id(node) in covered:
                continue
            hit = self._source(node)
            if hit:
                return hit
        for node in ast.walk(expr):
            if id(node) in covered:
                continue
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id in env:
                return env[node.id]
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and f"self.{node.attr}" in env:
                return env[f"self.{node.attr}"]
        return None

    def run(self, body: List[ast.stmt],
            seed_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        """Two transfer passes (loop-carried facts), observer callbacks
        on the second. Returns the final environment."""
        env: Dict[str, str] = dict(seed_env or {})
        for final in (False, True):
            for stmt in iter_statements(body):
                self._transfer(stmt, env)
                if final and self._on_stmt is not None:
                    self._on_stmt(stmt, env)
        return env

    def _transfer(self, stmt: ast.stmt, env: Dict[str, str]) -> None:
        if isinstance(stmt, ast.Assign):
            label = self.expr_label(stmt.value, env)
            for t in stmt.targets:
                self._bind(t, label, env)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, self.expr_label(stmt.value, env), env)
        elif isinstance(stmt, ast.AugAssign):
            # x += tainted taints x; x += clean keeps x's current label
            label = self.expr_label(stmt.value, env)
            if label:
                self._bind(stmt.target, label, env)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            # iterating a labelled iterable labels the loop variable
            self._bind(stmt.target, self.expr_label(stmt.iter, env), env,
                       keep=True)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               self.expr_label(item.context_expr, env), env)

    def _bind(self, target: ast.AST, label: Optional[str],
              env: Dict[str, str], keep: bool = False) -> None:
        for name in target_names(target):
            if label:
                env[name] = label
            elif not keep:
                env.pop(name, None)  # rebound clean -> fact killed


def _sorted_covered(expr: ast.AST) -> set:
    """ids of every node sitting under a ``sorted(...)`` call inside
    ``expr`` (including the call itself) — the laundered region."""
    covered: set = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "sorted":
            for inner in ast.walk(node):
                covered.add(id(inner))
    return covered


def local_bindings(fn_node: ast.AST) -> Dict[str, ast.AST]:
    """One-level local name -> RHS expression map for simple
    single-target assignments in a function body (last write wins).
    Used by registry rules (STATS-SCHEMA) to see through
    ``n = len(self.records); out["offered"] = n`` indirection."""
    out: Dict[str, ast.AST] = {}
    for stmt in iter_statements(fn_node.body):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            out[stmt.targets[0].id] = stmt.value
    return out
