"""firacheck CLI.

Usage:
    python -m fira_tpu.analysis.cli check fira_tpu tests scripts
    python -m fira_tpu.analysis.cli check --no-suppress fira_tpu
    python -m fira_tpu.analysis.cli list-rules

``check`` prints one ``file:line [RULE-ID] severity: message`` per finding
and exits 1 if any ERROR survives the suppression baseline (warnings never
gate). ``--no-suppress`` shows the raw pre-waiver findings — the view a
reviewer uses to audit the committed baseline.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from fira_tpu.analysis import astutil, engine
from fira_tpu.analysis.findings import RULES, Severity


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="python -m fira_tpu.analysis.cli",
                                description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)
    chk = sub.add_parser("check", help="analyze paths; exit 1 on errors")
    chk.add_argument("paths", nargs="+",
                     help="files or directories to analyze")
    chk.add_argument("--no-suppress", action="store_true",
                     help="show raw pre-waiver findings (audit view for "
                          "the committed baseline). The exit status then "
                          "reflects the RAW findings too, so a cleanly "
                          "baselined repo may still exit 1 here")
    chk.add_argument("--quiet", action="store_true",
                     help="suppress the summary line")
    sub.add_parser("list-rules", help="print the rule registry")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "list-rules":
        for rule, doc in sorted(RULES.items()):
            print(f"{rule}: {doc}")
        return 0

    # resolve the file list once; check_paths' own iter_py_files pass over
    # already-resolved .py paths is a cheap isfile sweep, not a re-walk.
    # An argument resolving to NO files gates: a mistyped or renamed path
    # must not turn into a silently-green scan over nothing
    files = []
    empty = []
    seen = set()
    for p in args.paths:
        got = engine.iter_py_files([p])
        if not got:
            empty.append(p)
        for f in got:
            # dedupe: a file named explicitly AND reached via a directory
            # argument (e.g. check.sh pinning data/feeder.py alongside the
            # fira_tpu tree) must not double-report findings
            key = astutil.normalize_path(f)
            if key not in seen:
                seen.add(key)
                files.append(f)
    if empty:
        print(f"firacheck: no Python files under {', '.join(empty)} — "
              f"refusing to report a clean scan over nothing",
              file=sys.stderr)
        return 1
    findings = engine.check_paths(files, suppress=not args.no_suppress)
    for f in findings:
        print(f.render())
    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    n_warn = len(findings) - n_err
    if not args.quiet:
        print(f"firacheck: {n_err} error(s), {n_warn} warning(s) over "
              f"{len(files)} file(s)", file=sys.stderr)
    return 1 if n_err else 0


if __name__ == "__main__":
    raise SystemExit(main())
