"""firacheck CLI.

Usage:
    python -m fira_tpu.analysis.cli check fira_tpu tests scripts
    python -m fira_tpu.analysis.cli check --no-suppress fira_tpu
    python -m fira_tpu.analysis.cli check --json fira_tpu tests scripts
    python -m fira_tpu.analysis.cli check --rules SHARED-MUT,FAULT-SITE fira_tpu
    python -m fira_tpu.analysis.cli check --sarif out.sarif fira_tpu
    python -m fira_tpu.analysis.cli list-rules

``check`` prints one ``file:line [RULE-ID] severity: message`` per finding
and exits 1 if any ERROR survives the suppression baseline (warnings never
gate). ``--no-suppress`` shows the raw pre-waiver findings — the view a
reviewer uses to audit the committed baseline. ``--json`` emits one
machine-readable document on stdout (per-rule counts + a findings array —
the check.sh artifact format); ``--rules`` restricts reporting AND the
exit status to the named rule ids, so a scan leg can gate on one rule
family without re-litigating the whole baseline. ``--sarif PATH``
additionally writes the findings as a SARIF 2.1.0 log to PATH — the
interchange format code-review UIs ingest — without changing what goes
to stdout or the exit status.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from fira_tpu.analysis import astutil, engine
from fira_tpu.analysis.findings import RULES, Severity


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="python -m fira_tpu.analysis.cli",
                                description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)
    chk = sub.add_parser("check", help="analyze paths; exit 1 on errors")
    chk.add_argument("paths", nargs="+",
                     help="files or directories to analyze")
    chk.add_argument("--no-suppress", action="store_true",
                     help="show raw pre-waiver findings (audit view for "
                          "the committed baseline). The exit status then "
                          "reflects the RAW findings too, so a cleanly "
                          "baselined repo may still exit 1 here")
    chk.add_argument("--quiet", action="store_true",
                     help="suppress the summary line")
    chk.add_argument("--json", action="store_true",
                     help="emit one machine-readable JSON document on "
                          "stdout: {files, errors, warnings, per_rule, "
                          "findings: [{path, line, rule, severity, "
                          "message}]} — the check.sh artifact format. "
                          "Exit codes are unchanged")
    chk.add_argument("--sarif", default=None, metavar="PATH",
                     help="also write the findings as a SARIF 2.1.0 log "
                          "to PATH (stdout output and exit codes are "
                          "unchanged; composes with --rules/--json)")
    chk.add_argument("--rules", default=None, metavar="RULE[,RULE...]",
                     help="restrict reporting and exit status to these "
                          "rule ids (BAD-SUPPRESS and PARSE-ERROR always "
                          "gate — a waiver typo or a broken file must "
                          "never pass a filtered scan). Unknown ids are "
                          "a usage error (exit 2)")
    sub.add_parser("list-rules", help="print the rule registry")
    return p


# always-gating meta rules: a filtered scan that ignored a malformed
# waiver or an unparseable file would report "clean" over a scan that
# never actually ran
_META_RULES = ("BAD-SUPPRESS", "PARSE-ERROR")

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def sarif_document(findings, rule_ids) -> dict:
    """The findings as one SARIF 2.1.0 run. ``rule_ids`` is the reported
    rule universe (the --rules selection or the full registry): every id
    appears in the driver's rules array whether or not it fired, so a
    consumer can tell "rule ran clean" from "rule didn't run"."""
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "firacheck",
                "informationUri": "docs/ANALYSIS.md",
                "rules": [{"id": r,
                           "shortDescription": {"text": RULES[r]}}
                          for r in sorted(rule_ids)],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": str(f.severity),
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/")},
                    "region": {"startLine": f.line},
                }}],
            } for f in findings],
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "list-rules":
        for rule, doc in sorted(RULES.items()):
            print(f"{rule}: {doc}")
        return 0

    selected = None
    if args.rules:
        selected = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = sorted(selected - set(RULES))
        if unknown:
            print(f"firacheck: unknown rule id(s) {unknown}; known: "
                  f"{sorted(RULES)}", file=sys.stderr)
            return 2
        selected |= set(_META_RULES)

    # resolve the file list once; check_paths' own iter_py_files pass over
    # already-resolved .py paths is a cheap isfile sweep, not a re-walk.
    # An argument resolving to NO files gates: a mistyped or renamed path
    # must not turn into a silently-green scan over nothing
    files = []
    empty = []
    seen = set()
    for p in args.paths:
        got = engine.iter_py_files([p])
        if not got:
            empty.append(p)
        for f in got:
            # dedupe: a file named explicitly AND reached via a directory
            # argument (e.g. check.sh pinning data/feeder.py alongside the
            # fira_tpu tree) must not double-report findings
            key = astutil.normalize_path(f)
            if key not in seen:
                seen.add(key)
                files.append(f)
    if empty:
        print(f"firacheck: no Python files under {', '.join(empty)} — "
              f"refusing to report a clean scan over nothing",
              file=sys.stderr)
        return 1
    findings = engine.check_paths(files, suppress=not args.no_suppress)
    if selected is not None:
        findings = [f for f in findings if f.rule in selected]
    n_err = sum(1 for f in findings if f.severity is Severity.ERROR)
    n_warn = len(findings) - n_err
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(sarif_document(findings, selected or set(RULES)),
                      fh, indent=1)
            fh.write("\n")
    if args.json:
        per_rule = {r: 0 for r in sorted(selected or RULES)}
        for f in findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        json.dump({
            "files": len(files),
            "errors": n_err,
            "warnings": n_warn,
            "per_rule": per_rule,
            "findings": [{"path": f.path, "line": f.line, "rule": f.rule,
                          "severity": str(f.severity),
                          "message": f.message} for f in findings],
        }, sys.stdout, indent=1)
        print()
    else:
        for f in findings:
            print(f.render())
    if not args.quiet:
        print(f"firacheck: {n_err} error(s), {n_warn} warning(s) over "
              f"{len(files)} file(s)", file=sys.stderr)
    return 1 if n_err else 0


if __name__ == "__main__":
    raise SystemExit(main())
