"""HOST-SYNC: host/device synchronization inside hot-loop regions.

The repo's throughput story (README Design notes, BASELINE.md) depends on
the driver never syncing with the device except at logging/dev/output
boundaries: one stray ``.item()`` per step serializes dispatch with
compute and erases the async-dispatch win. This rule flags every sync
primitive inside a designated hot region (see astutil.hot_spans); the
honest boundaries carry ``# firacheck: allow[HOST-SYNC] <reason>``.

Flagged primitives:
- ``x.item()``, ``x.block_until_ready()``
- ``jax.device_get(x)``, ``jax.block_until_ready(x)``
- ``np.asarray(x)`` / ``np.array(x)`` (jnp.* is device-side and exempt)
- ``float(x)`` / ``int(x)`` / ``bool(x)`` where x is a bare
  variable/attribute/subscript — the classic regressed ``float(loss)``.
  Conversions of call results are not double-flagged: the inner call is
  either itself a sync primitive (flagged once) or host-side already.
"""

from __future__ import annotations

import ast
from typing import List

from fira_tpu.analysis import astutil
from fira_tpu.analysis.findings import Finding, Severity

_SYNC_ATTRS = {"item", "block_until_ready"}
_SYNC_CALLS = {
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
    "np.asarray": "np.asarray", "np.array": "np.array",
    "numpy.asarray": "numpy.asarray", "numpy.array": "numpy.array",
    "onp.asarray": "np.asarray", "onp.array": "np.array",
}
_CASTS = {"float", "int", "bool"}


def _cast_arg_is_value_expr(call: ast.Call) -> bool:
    if len(call.args) != 1 or call.keywords:
        return False
    arg = call.args[0]
    if not isinstance(arg, (ast.Name, ast.Attribute, ast.Subscript)):
        return False
    # an argument containing a call is not double-flagged: the inner call
    # is either itself a sync primitive (reported once) or host-side
    return not any(isinstance(n, ast.Call) for n in ast.walk(arg))


def check(path: str, tree: ast.AST, source: str, parents, spans,
          ) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        region = astutil.hot_region_at(spans, node.lineno)
        if region is None:
            continue
        name = astutil.call_name(node)
        what = None
        if name in _SYNC_CALLS:
            what = f"{_SYNC_CALLS[name]}(...)"
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _SYNC_ATTRS and not node.args):
            what = f".{node.func.attr}()"
        elif name in _CASTS and _cast_arg_is_value_expr(node):
            src = ast.unparse(node.args[0])
            what = f"{name}({src}) on a (possible) device value"
        if what:
            findings.append(Finding(
                path, node.lineno, "HOST-SYNC", Severity.ERROR,
                f"{what} inside hot region [{region.desc}]: forces a "
                f"host/device sync in the hot loop; move it to a "
                f"logging/dev boundary or waive with a reason"))
    return findings
