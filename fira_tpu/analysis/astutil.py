"""Shared AST plumbing: dotted-name resolution, parent/ancestor walks, and
hot-loop-region designation.

Hot regions are where a host sync is a throughput bug rather than a
boundary. Designation (the README "Design notes" invariant, mechanized):

1. anywhere: bodies of functions handed to ``jax.lax.scan`` /
   ``while_loop`` / ``fori_loop`` / ``cond`` (traced — a sync there is a
   trace-time error or a silent per-step host round-trip);
2. anywhere: bodies of jit/pmap-wrapped or -decorated functions;
3. designated driver files (train/loop.py, train/step.py,
   decode/runner.py, decode/beam.py): every ``for``/``while`` loop body
   (the step-dispatch loops whose cadence IS the throughput story) and
   every function nested inside a function (the step closures those
   drivers build);
4. closure: a same-module function called by name from a hot region is hot
   too (catches helpers like train/loop.py ``_materialize`` that
   encapsulate the sync).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

_SCAN_CALLS = {
    "jax.lax.scan", "lax.scan",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.cond", "lax.cond",
}
_JIT_CALLS = {"jax.jit", "jit", "jax.pmap", "pmap", "pjit", "jax.pjit"}

# The designated dispatch drivers whose for/while bodies are hot: the
# train loop, the train-step factories, the decode drivers, the async
# input pipeline (its dispatcher/worker/consumer loops run concurrently
# with every step dispatch — a sync there stalls the feed exactly like one
# in the train loop), the bucket packer (its packing/assembly loops
# run as feeder tasks on the same worker threads), and the grouped
# scheduler (data/grouping.py — its plan walk and K-stack assembly run on
# the same feeder workers, one task per dispatch). NOT every train/decode
# module — e.g. decode/text.py is host-only text cooking and
# train/state.py is checkpoint I/O (already a boundary by definition).
_DRIVER_FILES = (
    "fira_tpu/train/loop.py", "fira_tpu/train/step.py",
    "fira_tpu/decode/runner.py", "fira_tpu/decode/beam.py",
    "fira_tpu/decode/engine.py", "fira_tpu/decode/paging.py",
    "fira_tpu/decode/prefix_cache.py", "fira_tpu/decode/spec.py",
    "fira_tpu/decode/quant.py",
    "fira_tpu/data/feeder.py", "fira_tpu/data/buckets.py",
    "fira_tpu/data/grouping.py",
    "fira_tpu/parallel/fleet.py",
    "fira_tpu/serve/server.py",
    "fira_tpu/serve/disagg.py",
    "fira_tpu/ingest/difftext.py",
    "fira_tpu/ingest/service.py",
    "fira_tpu/ingest/cache.py",
    "fira_tpu/robust/faults.py",
    "fira_tpu/robust/watchdog.py",
    "fira_tpu/robust/recovery.py",
)


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def last_segment(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(node: ast.AST, parents: Dict[ast.AST, ast.AST]
              ) -> Iterator[ast.AST]:
    while node in parents:
        node = parents[node]
        yield node


def enclosing_function(node: ast.AST, parents: Dict[ast.AST, ast.AST]
                       ) -> Optional[ast.AST]:
    for a in ancestors(node, parents):
        if isinstance(a, FunctionNode):
            return a
    return None


def is_jit_call(call: ast.Call) -> bool:
    name = call_name(call)
    if name in _JIT_CALLS:
        return True
    # functools.partial(jax.jit, ...) used as a decorator/factory
    if last_segment(name) == "partial" and call.args:
        return dotted(call.args[0]) in _JIT_CALLS
    return False


def normalize_path(path: str) -> str:
    """Absolute, forward-slash form for rule SCOPING (display paths stay
    as given). Without this, a checkout-relative invocation from inside
    the package ('check train/loop.py' with cwd fira_tpu/) would silently
    disarm the path-scoped rules and report a clean scan."""
    return os.path.abspath(path).replace("\\", "/")


def is_driver_module(path: str) -> bool:
    norm = normalize_path(path)
    return any(norm.endswith(f) for f in _DRIVER_FILES)


@dataclasses.dataclass(frozen=True)
class HotSpan:
    start: int
    end: int
    desc: str

    def covers(self, line: int) -> bool:
        return self.start <= line <= self.end


def _body_span(node: ast.AST, desc: str) -> Optional[HotSpan]:
    end = getattr(node, "end_lineno", None)
    if end is None:
        return None
    return HotSpan(node.lineno, end, desc)


def _function_name(node: ast.AST) -> str:
    return getattr(node, "name", "<lambda>")


def hot_spans(tree: ast.AST, path: str,
              parents: Dict[ast.AST, ast.AST]) -> List[HotSpan]:
    spans: List[HotSpan] = []
    func_defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # last definition wins; good enough for flat modules
            func_defs[node.name] = node

    def add_function(node: ast.AST, desc: str) -> None:
        span = _body_span(node, desc)
        if span:
            spans.append(span)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _SCAN_CALLS:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        add_function(arg, f"{name} body")
                    elif isinstance(arg, ast.Name) and arg.id in func_defs:
                        add_function(func_defs[arg.id],
                                     f"{name} body `{arg.id}`")
            elif is_jit_call(node):
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        add_function(arg, "jitted lambda")
                    elif isinstance(arg, ast.Name) and arg.id in func_defs:
                        add_function(func_defs[arg.id],
                                     f"jitted function `{arg.id}`")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if ((isinstance(dec, ast.Call) and is_jit_call(dec))
                        or dotted(dec) in _JIT_CALLS):
                    add_function(node, f"jit-decorated `{node.name}`")

    if is_driver_module(path):
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While)):
                span = _body_span(node, f"driver loop (line {node.lineno})")
                if span:
                    spans.append(span)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(enclosing_function(node, parents), FunctionNode):
                    add_function(node, f"driver step closure `{node.name}`")

    # Closure: same-module functions called from hot regions become hot.
    def covered(line: int) -> Optional[HotSpan]:
        for s in spans:
            if s.covers(line):
                return s
        return None

    changed = True
    hot_names: Set[str] = set()
    while changed:
        changed = False
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = call_name(node)
            if fname in func_defs and fname not in hot_names \
                    and covered(node.lineno):
                hot_names.add(fname)
                add_function(func_defs[fname],
                             f"`{fname}` (called from hot region, line "
                             f"{node.lineno})")
                changed = True
    return spans


def hot_region_at(spans: List[HotSpan], line: int) -> Optional[HotSpan]:
    best: Optional[HotSpan] = None
    for s in spans:
        if s.covers(line) and (best is None or s.start >= best.start):
            best = s  # innermost (latest-starting) region names the message
    return best
