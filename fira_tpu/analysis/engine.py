"""firacheck engine: file walking, two-pass analysis, suppression folding.

Pass 1 collects the cross-file registries: the donating-factory registry
(functions whose return is ``jax.jit(..., donate_argnums=...)``, e.g.
train/step.py:jit_train_step) so DONATION reasons about call sites in
OTHER files by name, the contract registry (``*_errors`` validator
fields + the fault-site tables — rules_contracts.ContractRegistry) so
the v2 contract lints reason across the whole scan, and the module-set
call graph (callgraph.CallGraph over every parsed tree) so the v3
interprocedural rules resolve calls and summaries across files. Pass 2
runs every rule per file, then folds in the ``# firacheck: allow[...]``
waivers.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from fira_tpu.analysis import (astutil, rules_concurrency, rules_contracts,
                               rules_determinism, rules_purity,
                               rules_resources, rules_sync, rules_trace)
from fira_tpu.analysis import suppress as suppress_lib
from fira_tpu.analysis.callgraph import CallGraph
from fira_tpu.analysis.findings import Finding, Severity


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # `fixtures` dirs hold planted-hazard corpora (the analyzer's
                # own test bed) — hazards there are the point, so directory
                # walks skip them; naming a fixture file explicitly scans it
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d not in ("__pycache__", "fixtures"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def _parse(path: str, source: str) -> Optional[ast.AST]:
    try:
        return ast.parse(source, filename=path)
    except SyntaxError:
        return None


def check_source(path: str, source: str, *,
                 factories: Optional[Dict[str, Tuple[int, ...]]] = None,
                 contracts: Optional[
                     rules_contracts.ContractRegistry] = None,
                 suppress: bool = True,
                 tree: Optional[ast.AST] = None,
                 graph: Optional[CallGraph] = None,
                 ) -> List[Finding]:
    """Analyze one in-memory source; returns surviving findings.

    With ``suppress=False`` the raw (pre-waiver) findings come back —
    the fixture test uses this to pin that every rule fires. ``tree``
    lets check_paths reuse its registry-pass parse. ``contracts``: the
    cross-file contract registry; None builds one from this file alone
    (+ the real fault-site table — the single-file fixture path).
    ``graph``: the scan-wide call graph; None builds a single-file graph
    (same-module resolution still works — the fixture path).
    """
    tree = tree if tree is not None else _parse(path, source)
    if tree is None:
        # a syntax-broken file was analyzed by NO rule — that must gate,
        # or "clean scan" silently stops meaning anything for this file
        return [Finding(path, 1, "PARSE-ERROR", Severity.ERROR,
                        "file does not parse; none of its invariants "
                        "were checked")]
    if contracts is None:
        contracts = rules_contracts.ContractRegistry()
        rules_contracts.collect(path, tree, contracts)
        rules_contracts.finalize(contracts)
    if graph is None:
        graph = CallGraph.build({path: tree})
    parents = astutil.parent_map(tree)
    spans = astutil.hot_spans(tree, path, parents)
    findings: List[Finding] = []
    findings += rules_sync.check(path, tree, source, parents, spans)
    findings += rules_trace.check(path, tree, source, parents, spans,
                                  factories=factories or {})
    findings += rules_purity.check_prng(path, tree, source, parents, spans)
    findings += rules_purity.check_discarded_at(path, tree, source, parents,
                                                spans)
    findings += rules_purity.check_geometry(path, tree, source, parents,
                                            spans)
    findings += rules_concurrency.check(path, tree, source, parents, spans)
    findings += rules_contracts.check(path, tree, source, parents, spans,
                                      registry=contracts)
    findings += rules_resources.check(path, tree, source, parents, graph)
    findings += rules_determinism.check(path, tree, source, parents, graph)

    sups, bad = suppress_lib.parse_suppressions(path, source)
    if not suppress:
        return findings + bad
    kept, _waived = suppress_lib.apply_suppressions(findings, sups)
    kept += bad
    kept += suppress_lib.unused_suppressions(path, sups)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept


def check_paths(paths: Iterable[str], *, suppress: bool = True,
                ) -> List[Finding]:
    files = iter_py_files(paths)
    factories: Dict[str, Tuple[int, ...]] = {}
    contracts = rules_contracts.ContractRegistry()
    sources: Dict[str, str] = {}
    trees: Dict[str, ast.AST] = {}
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                sources[path] = f.read()
        except OSError as e:
            # unanalyzed must gate, same contract as the unparseable case
            findings.append(Finding(
                path, 1, "PARSE-ERROR", Severity.ERROR,
                f"file could not be read ({e.__class__.__name__}); none "
                f"of its invariants were checked"))
            continue
        tree = _parse(path, sources[path])
        if tree is not None:
            trees[path] = tree  # reused in pass 2 — parse once per file
            factories.update(rules_trace.collect_donating_factories(tree))
            rules_contracts.collect(path, tree, contracts)
    rules_contracts.finalize(contracts)
    graph = CallGraph.build(trees)  # v3 interprocedural index (pass 1)
    for path in files:
        if path in sources:
            findings += check_source(path, sources[path],
                                     factories=factories,
                                     contracts=contracts, suppress=suppress,
                                     tree=trees.get(path), graph=graph)
    return findings


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity is Severity.ERROR for f in findings)
