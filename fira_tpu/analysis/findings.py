"""Finding/severity types and the one true output format.

Every rule reports through :class:`Finding`; the CLI renders
``file:line [RULE-ID] severity: message`` so editors, grep-based
baselines, and the golden fixture test all parse one shape.
"""

from __future__ import annotations

import dataclasses
import enum


class Severity(enum.Enum):
    ERROR = "error"      # breaks a performance/correctness invariant
    WARNING = "warning"  # suspicious; heuristic or advisory

    def __str__(self) -> str:
        return self.value


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str        # as given to the checker (kept relative for stable output)
    line: int        # 1-based
    rule: str        # e.g. "HOST-SYNC"
    severity: Severity
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line} [{self.rule}] "
                f"{self.severity}: {self.message}")


# Rule registry: id -> one-line contract (docs/ANALYSIS.md holds the long
# form). Kept here so `cli.py list-rules`, the engine's suppression
# validation, and the docs can't drift apart on the id set.
RULES = {
    "HOST-SYNC": (
        "host/device sync primitive (.item()/float()/int()/bool()/"
        "np.asarray/jax.device_get/block_until_ready) inside a hot-loop "
        "region"),
    "RETRACE": (
        "jax.jit constructed inside a loop body, unhashable static "
        "arguments, or a jitted closure baking captured arrays into the "
        "trace"),
    "DONATION": (
        "a buffer passed at a donate_argnums position is read again after "
        "the donating call"),
    "PRNG-REUSE": (
        "the same PRNG key fed to two jax.random consumers without an "
        "intervening split/fold_in"),
    "DISCARDED-AT": (
        "x.at[...].set/add(...) result discarded — a silent no-op under "
        "JAX's functional updates"),
    "GEOMETRY-DRIFT": (
        "a literal shape constant shadows the named geometry in config.py "
        "(210/30/25/280/160/650 must be referenced, not re-typed)"),
    "BAD-SUPPRESS": (
        "malformed or reason-less firacheck suppression comment (every "
        "waiver must name the invariant it waives)"),
    "PARSE-ERROR": (
        "file could not be read or parsed, so NONE of its invariants were "
        "checked — a gating error, not a skip"),
    # --- v2: concurrency-race rules (rules_concurrency.py) ---
    "SHARED-MUT": (
        "a self._x attribute written under a lock in some methods but "
        "bare in others, or mutated bare from both a thread-entry method "
        "and a scheduler method — an unsynchronized cross-thread write"),
    "RETIRED-RECHECK": (
        "shared scheduling/guard state mutated after a dispatch/readback "
        "boundary without re-checking `retired` — an abandoned watchdog "
        "thread races the survivors (docs/FAULTS.md)"),
    "SCHED-BLOCK": (
        "uncancellable blocking primitive (time.sleep, .wait()/.result()/"
        ".join() without timeout, os.fsync) on a driver hot path outside "
        "the sanctioned clock/backoff/lifecycle helpers"),
    "WALL-CLOCK": (
        "raw wall-clock read (time.time/perf_counter/monotonic) in a "
        "module that schedules under make_clock, outside the *Clock "
        "classes — wall time leaking into virtual-clock replay"),
    "FLOAT-ORDER": (
        "float += accumulation iterating a settle-ordered dict/set in a "
        "threaded driver module — the aggregate depends on thread "
        "interleaving in the last ulp (sum in sorted order instead)"),
    # --- v2: serving-contract lints (rules_contracts.py) ---
    "KNOB-VALIDATE": (
        "a config knob set from a CLI flag with no *_errors parse-time "
        "validator reading it and no constraining choices/type on the "
        "flag — a bad value becomes a mid-run traceback, not exit 2"),
    "FAULT-SITE": (
        "a fault-injection site string not registered in robust.faults."
        "SITES (or corrupt() on a site outside CORRUPT_SITES) — the spec "
        "parser rejects it, so the injection point can never be armed"),
    "DRIVER-REG": (
        "a module dispatching jitted programs or driving engine/fleet "
        "steppables that is not registered in astutil._DRIVER_FILES, or "
        "a registered driver module not named in scripts/check.sh"),
    # --- v3: interprocedural rules (callgraph.py + dataflow.py) ---
    "RES-LEAK": (
        "a tracked resource (KV block grant, started Thread, executor "
        "pool, open() handle, Event wakeup) whose release a raising path "
        "can skip — no finally/with covers the window between acquire "
        "and release, traced through calls via the module call graph"),
    "DET-TAINT": (
        "a value carrying nondeterministic order (settle-order dict/set "
        "iteration, unsorted os.listdir, as_completed) flows into a "
        "byte-contract sink (OrderedStreamWriter, metrics/journal "
        "serialization, keyed digests, BLEU) — traced across calls"),
    "STATS-SCHEMA": (
        "a *Stats field the metrics summary() never serializes, a "
        "summary() read of undeclared state, or an EngineStats/"
        "FleetStats/ServeStats field not named under docs/ — the "
        "observability schema and its consumers drifting apart"),
}
