"""Finding/severity types and the one true output format.

Every rule reports through :class:`Finding`; the CLI renders
``file:line [RULE-ID] severity: message`` so editors, grep-based
baselines, and the golden fixture test all parse one shape.
"""

from __future__ import annotations

import dataclasses
import enum


class Severity(enum.Enum):
    ERROR = "error"      # breaks a performance/correctness invariant
    WARNING = "warning"  # suspicious; heuristic or advisory

    def __str__(self) -> str:
        return self.value


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str        # as given to the checker (kept relative for stable output)
    line: int        # 1-based
    rule: str        # e.g. "HOST-SYNC"
    severity: Severity
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line} [{self.rule}] "
                f"{self.severity}: {self.message}")


# Rule registry: id -> one-line contract (docs/ANALYSIS.md holds the long
# form). Kept here so `cli.py list-rules`, the engine's suppression
# validation, and the docs can't drift apart on the id set.
RULES = {
    "HOST-SYNC": (
        "host/device sync primitive (.item()/float()/int()/bool()/"
        "np.asarray/jax.device_get/block_until_ready) inside a hot-loop "
        "region"),
    "RETRACE": (
        "jax.jit constructed inside a loop body, unhashable static "
        "arguments, or a jitted closure baking captured arrays into the "
        "trace"),
    "DONATION": (
        "a buffer passed at a donate_argnums position is read again after "
        "the donating call"),
    "PRNG-REUSE": (
        "the same PRNG key fed to two jax.random consumers without an "
        "intervening split/fold_in"),
    "DISCARDED-AT": (
        "x.at[...].set/add(...) result discarded — a silent no-op under "
        "JAX's functional updates"),
    "GEOMETRY-DRIFT": (
        "a literal shape constant shadows the named geometry in config.py "
        "(210/30/25/280/160/650 must be referenced, not re-typed)"),
    "BAD-SUPPRESS": (
        "malformed or reason-less firacheck suppression comment (every "
        "waiver must name the invariant it waives)"),
    "PARSE-ERROR": (
        "file could not be read or parsed, so NONE of its invariants were "
        "checked — a gating error, not a skip"),
}
