"""RETRACE and DONATION: compile-once and buffer-donation contracts.

RETRACE — the one-compile fixed-geometry invariant (README Design notes;
the runtime twin is the sanitizer's compile-count guard):
- ``jax.jit(...)`` constructed inside a loop body recompiles (or at best
  re-looks-up) every iteration;
- a list/dict/set passed at a ``static_argnums``/``static_argnames``
  position is unhashable → TypeError at best, cache-miss-per-call if
  wrapped;
- a jitted closure capturing an array built in an enclosing function bakes
  it into the jaxpr as a constant: rebuilt closures retrace, and the
  constant bloats the program (warning — sometimes intentional).

DONATION — donated buffers die at the call (train/step.py donates the
TrainState so the optimizer update happens in place in HBM): reading a
variable after passing it at a donated position returns garbage or raises.
The pass also understands this repo's factory idiom: a function whose
return is ``jax.jit(..., donate_argnums=...)`` makes every
``x = factory(...)`` result a donating callable, cross-module by name.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from fira_tpu.analysis import astutil
from fira_tpu.analysis.findings import Finding, Severity

_ARRAY_PREFIXES = ("jnp.", "np.", "numpy.", "jax.numpy.", "jax.random.",
                   "jax.device_put")
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, ast.Tuple):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.append(e.value)
                    else:
                        return None
                return tuple(out)
            return None
    return None


def _static_spec(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = (v.value,)
            elif isinstance(v, ast.Tuple):
                nums = tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
        elif kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names = tuple(e.value for e in v.elts
                              if isinstance(e, ast.Constant)
                              and isinstance(e.value, str))
    return nums, names


def collect_donating_factories(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """Functions whose return value is jax.jit(..., donate_argnums=...) —
    the engine merges these across all scanned files into one registry."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Return)
                    and isinstance(sub.value, ast.Call)
                    and astutil.is_jit_call(sub.value)):
                pos = _donate_positions(sub.value)
                if pos:
                    out[node.name] = tuple(sorted(set(out.get(node.name, ())
                                                      + pos)))
    return out


def _store_names(target: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            names.add(n.id)
    return names


def _resolve_value(value: ast.AST, factories: Dict[str, Tuple[int, ...]],
                   local_factories: Dict[str, Tuple[int, ...]],
                   ) -> Optional[Tuple[str, Tuple[int, ...]]]:
    """Classify an assignment RHS: ('donating', pos) for a donating
    callable, ('factory', pos) for a reference to a donating factory."""
    if isinstance(value, ast.Call):
        if astutil.is_jit_call(value):
            pos = _donate_positions(value)
            return ("donating", pos) if pos else None
        seg = astutil.last_segment(astutil.call_name(value))
        if seg in local_factories:
            return ("donating", local_factories[seg])
        if seg in factories:
            return ("donating", factories[seg])
        return None
    seg = astutil.last_segment(astutil.dotted(value))
    if seg in local_factories:
        return ("factory", local_factories[seg])
    if seg in factories:
        return ("factory", factories[seg])
    if isinstance(value, ast.IfExp):
        a = _resolve_value(value.body, factories, local_factories)
        b = _resolve_value(value.orelse, factories, local_factories)
        if a and b and a[0] == b[0]:
            return (a[0], tuple(sorted(set(a[1]) | set(b[1]))))
    return None


def _enclosing_loop_same_frame(node: ast.AST, parents) -> Optional[ast.AST]:
    for a in astutil.ancestors(node, parents):
        if isinstance(a, astutil.FunctionNode):
            return None
        if isinstance(a, (ast.For, ast.While)):
            return a
    return None


def _check_donation_calls(path: str, tree: ast.AST, parents,
                          donating: Dict[str, Tuple[int, ...]],
                          findings: List[Finding]) -> None:
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in donating):
            continue
        positions = donating[node.func.id]
        stmt = node
        for a in astutil.ancestors(node, parents):
            stmt = a
            if isinstance(a, ast.stmt):
                break
        targets: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                targets |= _store_names(t)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets |= _store_names(stmt.target)
        scope = astutil.enclosing_function(node, parents) or tree
        for pos in positions:
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            if not isinstance(arg, ast.Name):
                continue
            var = arg.id
            if var in targets:
                continue  # rebound by the donating call itself
            loop = _enclosing_loop_same_frame(node, parents)
            if loop is not None:
                findings.append(Finding(
                    path, node.lineno, "DONATION", Severity.ERROR,
                    f"`{var}` is donated to `{node.func.id}` (argument "
                    f"{pos}) inside a loop without being rebound by the "
                    f"call — the next iteration passes an "
                    f"already-donated buffer"))
                continue
            first_store = None
            first_read = None
            for n in ast.walk(scope):
                if isinstance(n, ast.Name) and n.id == var \
                        and n.lineno > node.lineno:
                    if isinstance(n.ctx, ast.Store):
                        if first_store is None or n.lineno < first_store:
                            first_store = n.lineno
                    elif first_read is None or n.lineno < first_read:
                        first_read = n.lineno
            if first_read is not None and (first_store is None
                                           or first_read <= first_store):
                findings.append(Finding(
                    path, node.lineno, "DONATION", Severity.ERROR,
                    f"`{var}` is donated to `{node.func.id}` (argument "
                    f"{pos}) but read again at line {first_read}; donated "
                    f"buffers are invalidated by the call"))


def _free_names(fn: ast.AST) -> Set[str]:
    bound: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        bound.add(a.arg)
    loads: Set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
                else:
                    loads.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(n.name)
    return loads - bound


def _check_closure_capture(path: str, fn: ast.AST, parents,
                           findings: List[Finding], label: str) -> None:
    free = _free_names(fn)
    if not free:
        return
    enclosing = astutil.enclosing_function(fn, parents)
    while enclosing is not None:
        body = (enclosing.body if isinstance(enclosing.body, list)
                else [enclosing.body])
        for stmt in body:
            if not isinstance(stmt, ast.Assign):
                continue
            names = set()
            for t in stmt.targets:
                names |= _store_names(t)
            hit = names & free
            if not hit or not isinstance(stmt.value, ast.Call):
                continue
            vname = astutil.call_name(stmt.value)
            if vname and vname.startswith(_ARRAY_PREFIXES):
                var = sorted(hit)[0]
                findings.append(Finding(
                    path, fn.lineno, "RETRACE", Severity.WARNING,
                    f"{label} captures array `{var}` (built at line "
                    f"{stmt.lineno}) as a closure constant; it is baked "
                    f"into the jaxpr — pass it as an argument so the "
                    f"compiled program is reused"))
        enclosing = astutil.enclosing_function(enclosing, parents)


def check(path: str, tree: ast.AST, source: str, parents, spans, *,
          factories: Dict[str, Tuple[int, ...]],
          ) -> List[Finding]:
    findings: List[Finding] = []
    func_defs: Dict[str, ast.AST] = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    local_factories = collect_donating_factories(tree)
    donating: Dict[str, Tuple[int, ...]] = {}
    jit_static: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {}

    for node in ast.walk(tree):
        # --- RETRACE (a): jit constructed inside a loop body ---
        if isinstance(node, ast.Call) and astutil.is_jit_call(node):
            loop = _enclosing_loop_same_frame(node, parents)
            if loop is not None:
                findings.append(Finding(
                    path, node.lineno, "RETRACE", Severity.ERROR,
                    f"jax.jit constructed inside the loop at line "
                    f"{loop.lineno}: every iteration builds a fresh jitted "
                    f"callable (retrace/cache-miss per step); hoist it out "
                    f"of the loop"))
            # RETRACE (c): jitted lambda / local def capturing arrays
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    _check_closure_capture(path, arg, parents, findings,
                                           "jitted lambda")
                elif isinstance(arg, ast.Name) and arg.id in func_defs:
                    _check_closure_capture(
                        path, func_defs[arg.id], parents, findings,
                        f"jitted function `{arg.id}`")

        # --- collect donating/static callables from assignments ---
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            resolved = _resolve_value(node.value, factories, local_factories)
            if resolved:
                kind, pos = resolved
                if kind == "donating":
                    donating[tname] = pos
                else:
                    local_factories[tname] = pos
            if isinstance(node.value, ast.Call) \
                    and astutil.is_jit_call(node.value):
                nums, names = _static_spec(node.value)
                if nums or names:
                    jit_static[tname] = (nums, names)

    # --- RETRACE (b): unhashable values at static positions ---
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jit_static):
            continue
        nums, names = jit_static[node.func.id]
        for pos in nums:
            if pos < len(node.args) and isinstance(node.args[pos],
                                                   _UNHASHABLE):
                findings.append(Finding(
                    path, node.lineno, "RETRACE", Severity.ERROR,
                    f"unhashable {type(node.args[pos]).__name__} passed at "
                    f"static_argnums position {pos} of "
                    f"`{node.func.id}`: static arguments are hashed for "
                    f"the jit cache"))
        for kw in node.keywords:
            if kw.arg in names and isinstance(kw.value, _UNHASHABLE):
                findings.append(Finding(
                    path, node.lineno, "RETRACE", Severity.ERROR,
                    f"unhashable {type(kw.value).__name__} passed at "
                    f"static_argnames key '{kw.arg}' of `{node.func.id}`"))

    # jit-decorated local defs also get the closure-capture check
    for fname, fn in func_defs.items():
        for dec in fn.decorator_list:
            if ((isinstance(dec, ast.Call) and astutil.is_jit_call(dec))
                    or astutil.dotted(dec) in ("jax.jit", "jit")):
                _check_closure_capture(path, fn, parents, findings,
                                       f"jit-decorated `{fname}`")

    _check_donation_calls(path, tree, parents, donating, findings)
    return findings
