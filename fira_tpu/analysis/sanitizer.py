"""Runtime sanitizer: the dynamic twin of firacheck's static rules.

``--sanitize`` on the train/test CLIs arms three checks for the whole run:

- ``jax_debug_nans`` / ``jax_debug_infs``: every jitted program is
  re-checked for non-finite outputs (JAX re-runs op-by-op on a hit, so the
  raise points at the culprit primitive). Costs a sync per dispatch —
  this is a debugging mode, not a training mode.
- compile capture: ``jax_log_compiles`` routes one "Compiling <name>..."
  log record per XLA compilation through :class:`CompileWatcher`;
- :class:`CompileGuard`: the one-compile fixed-geometry contract
  (README Design notes; static twin: RETRACE). Call ``guard.step(label)``
  after each dispatch of a program; a label's FIRST step may compile
  (warmup), any compilation attributed to a later step of a known label
  raises :class:`RetraceError` with the captured program names.

The guard is deliberately per-label, not global: a fused-steps run
legitimately compiles the grouped program at step 1 and the per-step
program at the epoch tail; each label gets exactly one warmup dispatch.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
from typing import Dict, Iterator, Optional

_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",  # "Compiling <fn> with global shapes..."
    "jax._src.dispatch",           # "Finished XLA compilation of <fn>..."
)
_COMPILE_PREFIXES = ("Compiling ",)


class RetraceError(RuntimeError):
    """A post-warmup step triggered a fresh XLA compilation."""


def program_label(kind: str, tag: Optional[str] = None, group: int = 1) -> str:
    """Canonical label for one member of the (geometry x entrypoint x
    group-size) program family — the single format every driver labels and
    declares with, so the declared-family check can close over grouped
    programs too:

    ``program_label('train_step')``                    -> ``train_step``
    ``program_label('train_step', 'a16.e256.t8')``     -> ``train_step[a16.e256.t8]``
    ``program_label('grouped_step', 'a16.e256.t8', 8)``-> ``grouped_step[a16.e256.t8.g8]``
    ``program_label('grouped_step', None, 8)``         -> ``grouped_step[g8]``

    ``tag`` is a bucket geometry tag (data.buckets.geom_tag) or None;
    ``group`` > 1 is the stacked leading dim (fused K / accum A), so a
    grouped program at an undeclared (geom, K) raises at the dispatch that
    produced it, not as a mystery recompile."""
    mods = ".".join(m for m in (tag, f"g{group}" if group > 1 else None) if m)
    return f"{kind}[{mods}]" if mods else kind


class CompileWatcher(logging.Handler):
    """Counts XLA compilations by listening to jax's log_compiles records.

    Host-side only: reading ``count`` never touches the device. The
    messages are also kept (most recent first-N) so a RetraceError can
    name the program that recompiled.
    """

    def __init__(self, keep: int = 20) -> None:
        super().__init__(level=logging.DEBUG)
        self.count = 0
        # most-recent `keep` messages: a RetraceError must name the program
        # that JUST recompiled, not a warmup-era one
        self.messages: collections.deque = collections.deque(maxlen=keep)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # a malformed record must never kill a train run
            return
        if msg.startswith(_COMPILE_PREFIXES):
            self.count += 1
            # first clause of the message names the compiled program
            self.messages.append(msg.split(" with ")[0])


@dataclasses.dataclass
class CompileGuard:
    """Per-program-label compile budget: 1 warmup dispatch, then zero.

    With a bucketed geometry family (data/buckets.py) every bucket's
    program gets its own label (``train_step[a16.e256.t8]``), and grouped
    dispatch (data/grouping.py) widens the family along the group-size
    axis (``grouped_step[a16.e256.t8.g8]`` — see :func:`program_label`):
    N programs warm up, then still zero post-warmup compiles. Drivers
    additionally :meth:`declare` the family after pre-warming — from then
    on a dispatch under an UNDECLARED label raises, so a geometry or
    group size outside the declared (geom, K) table (shape drift, a
    mis-packed batch) is caught at the step that produced it, not as a
    mystery recompile."""

    watcher: CompileWatcher
    _last_count: int = 0
    _extra: int = 0
    _seen: Dict[str, int] = dataclasses.field(default_factory=dict)
    _declared: Optional[set] = None

    def declare(self, labels) -> None:
        """Close the program family: after this, ``step()`` on a label not
        in the (cumulative) declared set raises RetraceError. Idempotent
        and additive — train and decode each declare their own labels."""
        self._declared = (self._declared or set()) | set(labels)

    @property
    def family_closed(self) -> bool:
        """True once declare() has closed the program family. Mid-run
        label additions (a respawned replica's fresh program set —
        robust/recovery.py) must declare ADDITIVELY into a closed family
        and must never be the FIRST declare: closing an open family
        around only the replacement's labels would outlaw every
        already-serving program."""
        return self._declared is not None

    def step_counting(self, label: str) -> int:
        """Attribute compilations since the last call to ``label``'s
        current dispatch and record them; returns the number of
        post-warmup compilations attributed to this dispatch."""
        new = self.watcher.count - self._last_count
        self._last_count = self.watcher.count
        steps = self._seen.get(label, 0)
        self._seen[label] = steps + 1
        extra = new if steps >= 1 else 0
        self._extra += extra
        return extra

    def step(self, label: str) -> None:
        """step_counting + raise: the drivers' per-dispatch check."""
        if self._declared is not None and label not in self._declared:
            raise RetraceError(
                f"sanitizer: program '{label}' is not in the declared "
                f"program family {sorted(self._declared)} — a geometry "
                f"outside the declared bucket table reached a dispatch "
                f"site (shape drift or a mis-packed batch)")
        extra = self.step_counting(label)
        if extra:
            recent = "; ".join(list(self.watcher.messages)[-min(extra, 5):])
            raise RetraceError(
                f"sanitizer: {extra} new XLA compilation(s) at step "
                f"{self._seen[label]} of program '{label}' — the "
                f"one-compile fixed-geometry invariant is broken (shape "
                f"drift or a re-constructed jit). Recent compiles: "
                f"{recent}")

    def compiles_after_warmup(self) -> int:
        """Total compilations attributed past some label's warmup step —
        0 on a healthy run (the compile-count regression test pins this
        without needing the raise path)."""
        return self._extra


@contextlib.contextmanager
def compile_capture() -> Iterator[CompileWatcher]:
    """Arm jax_log_compiles and attach the counting handler; restores
    both on exit. Usable standalone (tests) or via :func:`sanitize`."""
    import jax

    watcher = CompileWatcher()
    loggers = [logging.getLogger(name) for name in _COMPILE_LOGGERS]
    prev_levels = [lg.level for lg in loggers]
    prev_flag = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    for lg in loggers:
        lg.addHandler(watcher)
        # the record must reach our handler even under a quiet root config;
        # the EFFECTIVE level is what gates isEnabledFor (an unset logger
        # inherits a root ERROR config and would drop WARNING records)
        if lg.getEffectiveLevel() > logging.WARNING:
            lg.setLevel(logging.WARNING)
    try:
        yield watcher
    finally:
        for lg, lvl in zip(loggers, prev_levels):
            lg.removeHandler(watcher)
            lg.setLevel(lvl)
        jax.config.update("jax_log_compiles", prev_flag)


def arm(enabled: bool = True, *, nans: bool = True, infs: bool = True,
        ) -> Optional[CompileGuard]:
    """Process-lifetime arming — CLI-ONLY (fira_tpu/cli.py). Mutates global
    jax config and logger state with no teardown, which is fine exactly
    when the process dies with the run. Library callers and tests must use
    the :func:`sanitize` context manager and pass the resulting guard into
    train()/run_test() instead."""
    if not enabled:
        return None
    import jax

    jax.config.update("jax_debug_nans", nans)
    jax.config.update("jax_debug_infs", infs)
    jax.config.update("jax_log_compiles", True)
    watcher = CompileWatcher()
    for name in _COMPILE_LOGGERS:
        lg = logging.getLogger(name)
        lg.addHandler(watcher)
        if lg.getEffectiveLevel() > logging.WARNING:
            lg.setLevel(logging.WARNING)
    return CompileGuard(watcher)


@contextlib.contextmanager
def sanitize(enabled: bool = True, *, nans: bool = True, infs: bool = True,
             ) -> Iterator[Optional[CompileGuard]]:
    """Arm the full sanitizer; yields a CompileGuard (None when disabled).

    The drivers thread the guard through their dispatch sites:
    ``train/loop.py`` labels per-step/grouped/dev programs,
    ``decode/runner.py`` labels the beam program.
    """
    if not enabled:
        yield None
        return
    import jax

    prev_nans = jax.config.jax_debug_nans
    prev_infs = jax.config.jax_debug_infs
    jax.config.update("jax_debug_nans", nans)
    jax.config.update("jax_debug_infs", infs)
    try:
        with compile_capture() as watcher:
            yield CompileGuard(watcher)
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_debug_infs", prev_infs)
