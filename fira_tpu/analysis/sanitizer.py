"""Runtime sanitizer: the dynamic twin of firacheck's static rules.

``--sanitize`` on the train/test CLIs arms four checks for the whole run:

- ``jax_debug_nans`` / ``jax_debug_infs``: every jitted program is
  re-checked for non-finite outputs (JAX re-runs op-by-op on a hit, so the
  raise points at the culprit primitive). Costs a sync per dispatch —
  this is a debugging mode, not a training mode.
- compile capture: ``jax_log_compiles`` routes one "Compiling <name>..."
  log record per XLA compilation through :class:`CompileWatcher`;
- :class:`CompileGuard`: the one-compile fixed-geometry contract
  (README Design notes; static twin: RETRACE). Call ``guard.step(label)``
  after each dispatch of a program; a label's FIRST step may compile
  (warmup), any compilation attributed to a later step of a known label
  raises :class:`RetraceError` with the captured program names.
- :class:`ThreadGuard`: the lock-discipline sanitizer (static twin:
  SHARED-MUT). While armed, the threaded shared structures — the ingest
  result cache / lex+hunk memos (ingest/cache.py), the fault injector's
  fired accounting (robust/faults.py), and the feeder's ordered-ready
  channel (data/feeder.py) — are constructed as GUARDED proxies: a
  mutation by a thread that does not hold the structure's owning lock
  raises :class:`LockDisciplineError` at the mutating line, and every
  lock acquisition records its ordering edges so an inversion (A→B
  observed after B→A) is flagged in ``ThreadGuard.inversions``.
  Unarmed, nothing is wrapped: the structures are plain dicts/Counters
  and the only cost is one is-None branch at construction — the
  CompileGuard zero-overhead discipline.
- :class:`LeakGuard`: the resource-lifecycle sanitizer (static twin:
  RES-LEAK). While armed, the acquire/release pairs the static rule
  reasons about are ALSO tracked at runtime — paged-block grants
  (decode/engine.py's refcounted allocator), pipeline threads
  (data/feeder.py start/join, robust/watchdog.py's deliberately
  abandoned dispatch thread), and the ingest process pool
  (ingest/cache.py). Every acquire records its acquire SITE
  (file:line in function); ``assert_clean()`` at engine/fleet/serve
  teardown raises :class:`LeakError` naming the acquire site of every
  resource still held — the dynamic proof of the bug class the static
  rule flags, and the chaos harness's leak oracle. The watchdog's
  abandoned thread is SANCTIONED via :meth:`LeakGuard.abandon_thread`
  (moved to the ``abandoned`` book with its reason, not counted as a
  leak) — an armed teardown distinguishes "leaked" from "abandoned by
  design". Unarmed, ``leak_guard()`` is None and every call site is
  one is-None branch — no record, no allocation, no lock.

The guard is deliberately per-label, not global: a fused-steps run
legitimately compiles the grouped program at step 1 and the per-step
program at the epoch tail; each label gets exactly one warmup dispatch.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import os
import sys
import threading
from typing import Dict, Iterator, List, Optional, Tuple

_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",  # "Compiling <fn> with global shapes..."
    "jax._src.dispatch",           # "Finished XLA compilation of <fn>..."
)
_COMPILE_PREFIXES = ("Compiling ",)


class RetraceError(RuntimeError):
    """A post-warmup step triggered a fresh XLA compilation."""


class LockDisciplineError(RuntimeError):
    """A guarded shared structure was mutated by a thread that does not
    hold its owning lock (ThreadGuard; static twin: SHARED-MUT)."""


class LeakError(RuntimeError):
    """A tracked resource was still held at a teardown assert_clean()
    (LeakGuard; static twin: RES-LEAK). The message names every leaked
    resource's ACQUIRE site — the line that owes the release."""


def program_label(kind: str, tag: Optional[str] = None, group: int = 1) -> str:
    """Canonical label for one member of the (geometry x entrypoint x
    group-size) program family — the single format every driver labels and
    declares with, so the declared-family check can close over grouped
    programs too:

    ``program_label('train_step')``                    -> ``train_step``
    ``program_label('train_step', 'a16.e256.t8')``     -> ``train_step[a16.e256.t8]``
    ``program_label('grouped_step', 'a16.e256.t8', 8)``-> ``grouped_step[a16.e256.t8.g8]``
    ``program_label('grouped_step', None, 8)``         -> ``grouped_step[g8]``

    ``tag`` is a bucket geometry tag (data.buckets.geom_tag) or None;
    ``group`` > 1 is the stacked leading dim (fused K / accum A), so a
    grouped program at an undeclared (geom, K) raises at the dispatch that
    produced it, not as a mystery recompile."""
    mods = ".".join(m for m in (tag, f"g{group}" if group > 1 else None) if m)
    return f"{kind}[{mods}]" if mods else kind


class CompileWatcher(logging.Handler):
    """Counts XLA compilations by listening to jax's log_compiles records.

    Host-side only: reading ``count`` never touches the device. The
    messages are also kept (most recent first-N) so a RetraceError can
    name the program that recompiled.
    """

    def __init__(self, keep: int = 20) -> None:
        super().__init__(level=logging.DEBUG)
        self.count = 0
        # most-recent `keep` messages: a RetraceError must name the program
        # that JUST recompiled, not a warmup-era one
        self.messages: collections.deque = collections.deque(maxlen=keep)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # a malformed record must never kill a train run
            return
        if msg.startswith(_COMPILE_PREFIXES):
            self.count += 1
            # first clause of the message names the compiled program
            self.messages.append(msg.split(" with ")[0])


@dataclasses.dataclass
class CompileGuard:
    """Per-program-label compile budget: 1 warmup dispatch, then zero.

    With a bucketed geometry family (data/buckets.py) every bucket's
    program gets its own label (``train_step[a16.e256.t8]``), and grouped
    dispatch (data/grouping.py) widens the family along the group-size
    axis (``grouped_step[a16.e256.t8.g8]`` — see :func:`program_label`):
    N programs warm up, then still zero post-warmup compiles. Drivers
    additionally :meth:`declare` the family after pre-warming — from then
    on a dispatch under an UNDECLARED label raises, so a geometry or
    group size outside the declared (geom, K) table (shape drift, a
    mis-packed batch) is caught at the step that produced it, not as a
    mystery recompile."""

    watcher: CompileWatcher
    _last_count: int = 0
    _extra: int = 0
    _seen: Dict[str, int] = dataclasses.field(default_factory=dict)
    _declared: Optional[set] = None

    def declare(self, labels) -> None:
        """Close the program family: after this, ``step()`` on a label not
        in the (cumulative) declared set raises RetraceError. Idempotent
        and additive — train and decode each declare their own labels."""
        self._declared = (self._declared or set()) | set(labels)

    @property
    def family_closed(self) -> bool:
        """True once declare() has closed the program family. Mid-run
        label additions (a respawned replica's fresh program set —
        robust/recovery.py) must declare ADDITIVELY into a closed family
        and must never be the FIRST declare: closing an open family
        around only the replacement's labels would outlaw every
        already-serving program."""
        return self._declared is not None

    def step_counting(self, label: str) -> int:
        """Attribute compilations since the last call to ``label``'s
        current dispatch and record them; returns the number of
        post-warmup compilations attributed to this dispatch."""
        new = self.watcher.count - self._last_count
        self._last_count = self.watcher.count
        steps = self._seen.get(label, 0)
        self._seen[label] = steps + 1
        extra = new if steps >= 1 else 0
        self._extra += extra
        return extra

    def step(self, label: str) -> None:
        """step_counting + raise: the drivers' per-dispatch check."""
        if self._declared is not None and label not in self._declared:
            raise RetraceError(
                f"sanitizer: program '{label}' is not in the declared "
                f"program family {sorted(self._declared)} — a geometry "
                f"outside the declared bucket table reached a dispatch "
                f"site (shape drift or a mis-packed batch)")
        extra = self.step_counting(label)
        if extra:
            recent = "; ".join(list(self.watcher.messages)[-min(extra, 5):])
            raise RetraceError(
                f"sanitizer: {extra} new XLA compilation(s) at step "
                f"{self._seen[label]} of program '{label}' — the "
                f"one-compile fixed-geometry invariant is broken (shape "
                f"drift or a re-constructed jit). Recent compiles: "
                f"{recent}")

    def compiles_after_warmup(self) -> int:
        """Total compilations attributed past some label's warmup step —
        0 on a healthy run (the compile-count regression test pins this
        without needing the raise path)."""
        return self._extra


# --------------------------------------------------------------------------
# ThreadGuard: the runtime lock-discipline sanitizer (static twin:
# SHARED-MUT / rules_concurrency.py)
# --------------------------------------------------------------------------

class _GuardedLock:
    """A lock (or Condition) wrapper that records held-set membership in
    the owning ThreadGuard's thread-local state and lock-order edges on
    every acquisition. All other attributes (``wait``, ``notify_all``,
    ...) pass through, so a Condition keeps working as a Condition."""

    def __init__(self, guard: "ThreadGuard", lock, name: str):
        self._tg_guard = guard
        self._tg_lock = lock
        self.name = name

    def acquire(self, *args, **kwargs):
        got = self._tg_lock.acquire(*args, **kwargs)
        if got:
            self._tg_guard._note_acquire(self.name)
        return got

    def release(self):
        self._tg_guard._note_release(self.name)
        self._tg_lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __getattr__(self, attr):
        # Condition.wait/notify/notify_all etc. pass through; wait()
        # releases and reacquires the UNDERLYING lock internally — the
        # held-set entry stays put, which is correct: from this thread's
        # point of view the critical section never closed
        return getattr(self._tg_lock, attr)


class _GuardedMutations:
    """The ONE copy of the mutation-check machinery the guarded
    containers mix in (before their base in the MRO, so ``super()``
    resolves to the real container). Reads are unchecked — the
    sanitizer targets unsynchronized WRITES, the SHARED-MUT bug class.
    During base-class ``__init__`` (which may call ``update``/
    ``__setitem__``) the class-level ``_tg_guard = None`` default makes
    every check a no-op; ThreadGuard.wrap binds the instance attrs
    afterwards."""

    _tg_guard: "ThreadGuard" = None  # set by ThreadGuard.wrap
    _tg_lock: str = ""
    _tg_label: str = ""

    def _tg_check(self):
        if self._tg_guard is not None:
            self._tg_guard._check_mutation(self._tg_lock, self._tg_label)

    def __setitem__(self, k, v):
        self._tg_check()
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._tg_check()
        super().__delitem__(k)

    def pop(self, *a, **kw):
        self._tg_check()
        return super().pop(*a, **kw)

    def popitem(self, *a, **kw):
        self._tg_check()
        return super().popitem(*a, **kw)

    def clear(self):
        self._tg_check()
        super().clear()

    def update(self, *a, **kw):
        self._tg_check()
        super().update(*a, **kw)

    def setdefault(self, *a, **kw):
        self._tg_check()
        return super().setdefault(*a, **kw)


class _GuardedDict(_GuardedMutations, collections.OrderedDict):
    """Mutation-checked mapping proxy (order-preserving, so it stands in
    for both plain dicts and OrderedDicts)."""

    def move_to_end(self, *a, **kw):
        self._tg_check()
        super().move_to_end(*a, **kw)


class _GuardedCounter(_GuardedMutations, collections.Counter):
    """Mutation-checked Counter (``c[k] += 1`` routes through
    ``__setitem__``, exactly the unlocked-increment bug class)."""

    def subtract(self, *a, **kw):
        self._tg_check()
        super().subtract(*a, **kw)


class ThreadGuard:
    """Runtime lock-discipline sanitizer (docs/ANALYSIS.md "Runtime
    sanitizer"): declared shared structures mutate only under their
    owning lock, and lock-acquisition order is recorded to flag
    inversions.

    Usage (the pattern ingest/cache.py, robust/faults.py and
    data/feeder.py follow)::

        tg = thread_guard()           # None when unarmed
        if tg is not None:
            self._lock = tg.lock(self._lock, "IngestCache._lock")
            self._lru = tg.wrap(self._lru, self._lock, "IngestCache._lru")

    A ``wrap``-ped structure raises :class:`LockDisciplineError` on any
    mutation by a thread not currently holding the named lock. ``lock``
    additionally records ordering edges: whenever B is acquired while A
    is held the edge A→B is added, and if B→A was ever observed the
    inversion is recorded in :attr:`inversions` (recorded, not raised —
    a single observed inversion is a deadlock PRECONDITION, and the
    post-mortem wants the full pair list, not the first half of it).
    """

    def __init__(self):
        self._tls = threading.local()
        self._meta = threading.Lock()   # guards the order/violation books
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.inversions: List[Dict] = []
        self.violations: List[Dict] = []

    # --- held-set bookkeeping (per thread) ---

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, name: str) -> None:
        held = self._held()
        if held:
            with self._meta:
                for h in held:
                    if h == name:
                        continue
                    edge = (h, name)
                    if edge not in self._edges:
                        self._edges[edge] = (threading.current_thread().name,
                                             "")
                        if (name, h) in self._edges:
                            self.inversions.append({
                                "first": f"{name} -> {h}",
                                "then": f"{h} -> {name}",
                                "thread": threading.current_thread().name,
                            })
        held.append(name)

    def _note_release(self, name: str) -> None:
        held = self._held()
        # remove the LAST occurrence: locks nest, releases unwind
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    def _check_mutation(self, lock_name: str, label: str) -> None:
        held = self._held()
        if lock_name in held:
            return
        record = {"structure": label, "lock": lock_name,
                  "thread": threading.current_thread().name,
                  "held": list(held)}
        with self._meta:
            self.violations.append(record)
        raise LockDisciplineError(
            f"sanitizer: `{label}` mutated without holding its owning "
            f"lock `{lock_name}` (thread {record['thread']}, held locks: "
            f"{record['held'] or 'none'}) — the SHARED-MUT discipline: "
            f"every write site takes the lock, or the lock protects "
            f"nothing")

    # --- declaration surface ---

    def lock(self, lock, name: str) -> _GuardedLock:
        """Wrap a threading.Lock/RLock/Condition so acquisitions are
        tracked. ``name`` should be unique per instance (the callers
        suffix ``@{id(self):x}``)."""
        return _GuardedLock(self, lock, name)

    def wrap(self, obj, lock, label: str):
        """Wrap a shared structure so mutations require holding ``lock``
        (a :meth:`lock`-wrapped GuardedLock, or its name). Supports the
        mapping/Counter shapes the armed structures actually are;
        anything else is returned unwrapped (never break a run over an
        unguardable type)."""
        lock_name = lock.name if isinstance(lock, _GuardedLock) else str(lock)
        if isinstance(obj, collections.Counter):
            new: object = _GuardedCounter(obj)
        elif isinstance(obj, dict):
            new = _GuardedDict(obj)
        else:
            return obj
        new._tg_guard = self
        new._tg_lock = lock_name
        new._tg_label = label
        return new

    def summary(self) -> Dict:
        with self._meta:
            return {"violations": len(self.violations),
                    "lock_order_edges": len(self._edges),
                    "inversions": list(self.inversions)}


# --------------------------------------------------------------------------
# LeakGuard: the runtime resource-lifecycle sanitizer (static twin:
# RES-LEAK / rules_resources.py)
# --------------------------------------------------------------------------

class LeakGuard:
    """Runtime acquire/release ledger (docs/ANALYSIS.md "Runtime
    sanitizer"): every tracked acquire records its acquire site, every
    release retires the record, and :meth:`assert_clean` at teardown
    raises :class:`LeakError` naming the acquire site of whatever is
    still held.

    Usage (the pattern decode/engine.py, data/feeder.py and
    ingest/cache.py follow)::

        self._leaks = leak_guard()    # None when unarmed
        ...
        if self._leaks is not None:
            self._leaks.note_acquire("block", key, what="paged block 3")

    Resources are keyed ``(kind, key)`` where the caller's key embeds
    ``@{id(owner):x}`` so two engines never alias each other's blocks.
    Threads get dedicated helpers (:meth:`track_thread` /
    :meth:`note_joined` / :meth:`abandon_thread`) keyed by the thread
    object, so track and join sites never have to agree on a string.
    ``abandon_thread`` is the watchdog's sanction: a deliberately
    abandoned dispatch thread moves to the :attr:`abandoned` book with
    its reason instead of counting as a leak.
    """

    def __init__(self) -> None:
        self._meta = threading.Lock()
        self._open: Dict[Tuple[str, str], Dict] = {}
        self.abandoned: List[Dict] = []
        self.acquires = 0
        self.releases = 0
        # releases with no matching acquire: 0 on a healthy run — a
        # nonzero count means a double-release or an untracked acquire
        self.unmatched_releases = 0

    @staticmethod
    def _site(skip: int) -> str:
        """``file.py:line in func`` for the frame ``skip`` levels above
        the caller of this method — the acquire site a LeakError names."""
        f = sys._getframe(skip + 1)
        return (f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno} "
                f"in {f.f_code.co_name}")

    @staticmethod
    def _thread_key(thread: threading.Thread) -> str:
        return f"{thread.name}@{id(thread):x}"

    # --- the ledger ---

    def note_acquire(self, kind: str, key: str, what: str = "",
                     site: Optional[str] = None) -> None:
        site = site if site is not None else self._site(1)
        record = {"kind": kind, "key": str(key), "what": what or kind,
                  "site": site,
                  "thread": threading.current_thread().name}
        with self._meta:
            self.acquires += 1
            self._open[(kind, str(key))] = record

    def note_release(self, kind: str, key: str) -> None:
        with self._meta:
            self.releases += 1
            if self._open.pop((kind, str(key)), None) is None:
                self.unmatched_releases += 1

    def track_thread(self, thread: threading.Thread,
                     what: str = "") -> None:
        self.note_acquire("thread", self._thread_key(thread),
                          what=what or f"thread '{thread.name}'",
                          site=self._site(1))

    def note_joined(self, thread: threading.Thread) -> None:
        self.note_release("thread", self._thread_key(thread))

    def abandon_thread(self, thread: threading.Thread,
                       reason: str) -> None:
        """Sanction a deliberately unjoined thread (the watchdog's
        abandoned dispatch): the record moves to :attr:`abandoned` with
        its reason and no longer counts as held."""
        with self._meta:
            rec = self._open.pop(("thread", self._thread_key(thread)),
                                 None)
            if rec is not None:
                rec["reason"] = reason
                self.abandoned.append(rec)

    # --- the teardown oracle ---

    def open_resources(self) -> List[Dict]:
        with self._meta:
            return list(self._open.values())

    def assert_clean(self, scope: str = "teardown") -> None:
        """Raise :class:`LeakError` naming the acquire site of every
        resource still held (sanctioned abandons excluded). The
        engine/fleet/serve teardown call — the dynamic twin of a
        RES-LEAK finding."""
        leaks = self.open_resources()
        if not leaks:
            return
        sites = "; ".join(
            f"{r['what']} ({r['kind']} '{r['key']}') acquired at "
            f"{r['site']}" for r in leaks[:5])
        more = f" (+{len(leaks) - 5} more)" if len(leaks) > 5 else ""
        raise LeakError(
            f"sanitizer: {len(leaks)} resource(s) still held at {scope}: "
            f"{sites}{more} — every acquire owes a release on every exit "
            f"path (RES-LEAK discipline)")

    def summary(self) -> Dict:
        with self._meta:
            return {"acquires": self.acquires,
                    "releases": self.releases,
                    "open": len(self._open),
                    "abandoned": len(self.abandoned),
                    "unmatched_releases": self.unmatched_releases}


# process-global arming point: the threaded structures are constructed
# deep inside worker machinery, so they look the guard up here instead
# of threading it through every constructor. None = unarmed = nothing
# is ever wrapped (the zero-overhead contract).
_THREAD_GUARD: Optional[ThreadGuard] = None
# same contract for the resource ledger: None = unarmed = every tracked
# call site is one is-None branch and nothing is recorded.
_LEAK_GUARD: Optional[LeakGuard] = None


def leak_guard() -> Optional[LeakGuard]:
    """The armed LeakGuard, or None. Captured at construction time by
    the tracked owners (FiraDecodeEngine, Feeder, IngestExecutor) so an
    owner's whole lifecycle reports to ONE ledger even if arming flips
    mid-run."""
    return _LEAK_GUARD


@contextlib.contextmanager
def leak_guarding(guard: Optional[LeakGuard] = None
                  ) -> Iterator[LeakGuard]:
    """Arm a LeakGuard for the block (tests / chaos harness; jax-free).
    Owners constructed INSIDE the block are tracked; pre-existing ones
    are not (arming is a construction-time choice, like ThreadGuard)."""
    global _LEAK_GUARD
    prev = _LEAK_GUARD
    lg = guard if guard is not None else LeakGuard()
    _LEAK_GUARD = lg
    try:
        yield lg
    finally:
        _LEAK_GUARD = prev


def thread_guard() -> Optional[ThreadGuard]:
    """The armed ThreadGuard, or None. Called at construction time by
    the guarded classes (IngestCache, FaultInjector, Feeder)."""
    return _THREAD_GUARD


def guard_structures(owner, lock, structures, lock_label: str = "_lock"):
    """Construction-time arming hook for the guarded classes
    (IngestCache/LexMemo/HunkMemo, FaultInjector, Feeder): returns
    ``(lock, [structures...])`` untouched when no ThreadGuard is armed
    (one is-None branch, zero steady-state overhead), else the guarded
    lock plus mutation-checked proxies. ``structures`` is a list of
    ``(structure, label)`` pairs; ``lock_label`` is the owner's REAL
    attribute name for the lock (Feeder's is ``_cond``) so a violation
    message points at an attribute that exists; names are suffixed
    ``@id`` so two instances never alias each other's held-lock
    authority."""
    tg = thread_guard()
    if tg is None:
        return lock, [s for s, _label in structures]
    name = f"{type(owner).__name__}.{lock_label}@{id(owner):x}"
    glock = tg.lock(lock, name)
    return glock, [tg.wrap(s, glock,
                           f"{type(owner).__name__}.{label}@{id(owner):x}")
                   for s, label in structures]


@contextlib.contextmanager
def thread_guarding(guard: Optional[ThreadGuard] = None
                    ) -> Iterator[ThreadGuard]:
    """Arm a ThreadGuard for the block (tests; jax-free — this touches
    no jax config). Structures constructed INSIDE the block are guarded;
    pre-existing ones are not (arming is a construction-time choice)."""
    global _THREAD_GUARD
    prev = _THREAD_GUARD
    tg = guard if guard is not None else ThreadGuard()
    _THREAD_GUARD = tg
    try:
        yield tg
    finally:
        _THREAD_GUARD = prev


@contextlib.contextmanager
def compile_capture() -> Iterator[CompileWatcher]:
    """Arm jax_log_compiles and attach the counting handler; restores
    both on exit. Usable standalone (tests) or via :func:`sanitize`."""
    import jax

    watcher = CompileWatcher()
    loggers = [logging.getLogger(name) for name in _COMPILE_LOGGERS]
    prev_levels = [lg.level for lg in loggers]
    prev_flag = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    for lg in loggers:
        lg.addHandler(watcher)
        # the record must reach our handler even under a quiet root config;
        # the EFFECTIVE level is what gates isEnabledFor (an unset logger
        # inherits a root ERROR config and would drop WARNING records)
        if lg.getEffectiveLevel() > logging.WARNING:
            lg.setLevel(logging.WARNING)
    try:
        yield watcher
    finally:
        for lg, lvl in zip(loggers, prev_levels):
            lg.removeHandler(watcher)
            lg.setLevel(lvl)
        jax.config.update("jax_log_compiles", prev_flag)


def arm(enabled: bool = True, *, nans: bool = True, infs: bool = True,
        ) -> Optional[CompileGuard]:
    """Process-lifetime arming — CLI-ONLY (fira_tpu/cli.py). Mutates global
    jax config and logger state with no teardown, which is fine exactly
    when the process dies with the run. Library callers and tests must use
    the :func:`sanitize` context manager and pass the resulting guard into
    train()/run_test() instead."""
    if not enabled:
        return None
    import jax

    jax.config.update("jax_debug_nans", nans)
    jax.config.update("jax_debug_infs", infs)
    jax.config.update("jax_log_compiles", True)
    watcher = CompileWatcher()
    for name in _COMPILE_LOGGERS:
        lg = logging.getLogger(name)
        lg.addHandler(watcher)
        if lg.getEffectiveLevel() > logging.WARNING:
            lg.setLevel(logging.WARNING)
    # lock-discipline + resource-lifecycle sanitizers: process-lifetime
    # arming like the rest of this function — threaded shared structures
    # and resource owners constructed from here on are guarded
    # (docstring above; thread_guarding()/leak_guarding() are the scoped
    # alternatives for library callers/tests)
    global _THREAD_GUARD, _LEAK_GUARD
    _THREAD_GUARD = ThreadGuard()
    _LEAK_GUARD = LeakGuard()
    return CompileGuard(watcher)


@contextlib.contextmanager
def sanitize(enabled: bool = True, *, nans: bool = True, infs: bool = True,
             ) -> Iterator[Optional[CompileGuard]]:
    """Arm the full sanitizer; yields a CompileGuard (None when disabled).

    The drivers thread the guard through their dispatch sites:
    ``train/loop.py`` labels per-step/grouped/dev programs,
    ``decode/runner.py`` labels the beam program.
    """
    if not enabled:
        yield None
        return
    import jax

    prev_nans = jax.config.jax_debug_nans
    prev_infs = jax.config.jax_debug_infs
    jax.config.update("jax_debug_nans", nans)
    jax.config.update("jax_debug_infs", infs)
    try:
        with compile_capture() as watcher, thread_guarding(), \
                leak_guarding():
            yield CompileGuard(watcher)
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_debug_infs", prev_infs)
