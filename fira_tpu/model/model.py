"""FIRA model: GCN graph encoder + Transformer decoder + dual copy head.

TPU-first rebuild of /root/reference/Model.py and gnn_transformer.py. The
whole forward is one jittable program over fixed shapes. The adjacency
arrives as COO triplets and is applied per ``cfg.adjacency_impl``: "dense"
scatters it once per call into a (B, graph_len, graph_len) array reused by
all GCN rounds (an MXU bmm, right for the reference's 650 nodes); "segment"
keeps it as COO and message-passes by gather/scatter in O(edges), the path
that scales past that geometry. Everything else is batched matmuls.

Live-path math matches the reference exactly (parity-tested by weight
transplant in tests/test_model_parity.py); the dead modules (Encoder.lstm,
combination_list1, TransModel.gate_fc, the attr input) are omitted
(SURVEY.md Appendix B).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fira_tpu.config import FiraConfig
from fira_tpu.model.layers import (
    stable_dtype,
    append_block_kv,
    gather_block_kv,
    Attention,
    Combination,
    FeedForward,
    GCN,
    TorchDense,
    position_encoding,
    torch_bias_init,
    torch_embed_init,
    torch_kernel_init,
)
from fira_tpu.ops import copy_score


def dense_adjacency(senders, receivers, values, graph_len: int,
                    indices_sorted: bool = False,
                    out_dtype=None, flat: bool = False) -> jnp.ndarray:
    """Scatter padded COO triplets into a dense batched adjacency.

    Pad entries are (0, 0, 0.0); scatter-ADD of zero is a no-op, so no
    masking is needed. Replaces the reference's host-side per-sample densify
    (Dataset.py:336-343) with one on-device scatter per step.
    ``out_dtype``: scatter directly in the compute dtype instead of f32 —
    bit-identical to scattering f32 then casting, because graph_build's
    dedup guarantees each cell receives exactly one value (plus exact zero
    pads), so no cross-edge accumulation happens in the narrow dtype; the
    (B, N, N) buffer is built at half the bytes with no cast pass.
    ``indices_sorted``: promise that the (batch-major, cell-ascending) index
    stream is sorted — so XLA can skip its scatter sorting prologue.

    CALLER CONTRACT: pass ``indices_sorted=True`` ONLY for batches built by
    ``data.batching.make_batch`` under ``cfg.sort_edges=True`` (it performs
    the host-side sort this flag promises). A hand-built batch with unsorted
    triplets under this flag produces silently undefined scatter results on
    TPU — there is no runtime check.
    """
    B, _ = senders.shape
    dt = values.dtype if out_dtype is None else out_dtype
    b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    # indices travel int16 to halve H2D traffic; scatter wants int32
    if flat:
        # linearized 1-D scatter: flat = (b*N + s)*N + r. Under sort_edges
        # the stream is FULLY ascending (pads (0,0) sort first within each
        # row and rows ascend), so indices_are_sorted covers the whole
        # stream — the flattest index pattern XLA can be promised.
        # Bit-identical to the N-D scatter (same cells, same adds) — pinned
        # by tests.
        idx = ((b_idx * graph_len + senders.astype(jnp.int32)) * graph_len
               + receivers.astype(jnp.int32))
        out = jnp.zeros((B * graph_len * graph_len,), dtype=dt)
        out = out.at[idx.reshape(-1)].add(
            values.astype(dt).reshape(-1), indices_are_sorted=indices_sorted)
        return out.reshape(B, graph_len, graph_len)
    adj = jnp.zeros((B, graph_len, graph_len), dtype=dt)
    return adj.at[b_idx, senders.astype(jnp.int32),
                  receivers.astype(jnp.int32)].add(
        values.astype(dt), indices_are_sorted=indices_sorted)


def coo_matvec(senders, receivers, values, x,
               indices_sorted: bool = False) -> jnp.ndarray:
    """(A @ x) directly on COO triplets: gather each edge's source column,
    weight, scatter-add into its destination row. Semantically identical to
    ``dense_adjacency(...) @ x`` (dense[b, senders, receivers] = values), but
    O(edges) instead of O(graph_len^2) — the message-passing path for graphs
    larger than the reference's 650 nodes. Pad edges (0,0,0.0) contribute 0.
    ``indices_sorted``: cfg.sort_edges ordered each row by (sender,
    receiver), so the (b, s) scatter stream here is sorted too. Same caller
    contract as ``dense_adjacency``: only ``make_batch``-built batches
    satisfy the promise; violating it is silently undefined on TPU.
    """
    B = senders.shape[0]
    b_idx = jnp.arange(B)[:, None]
    senders = senders.astype(jnp.int32)    # indices travel int16 (H2D size)
    receivers = receivers.astype(jnp.int32)
    # accumulate in f32 like the dense einsum does on the MXU: bf16 scatter
    # sums over high-in-degree nodes would otherwise drift from the dense path
    acc_dtype = stable_dtype(x.dtype)
    msgs = x.astype(acc_dtype)[b_idx, receivers] * values[..., None].astype(acc_dtype)
    out = jnp.zeros(x.shape, acc_dtype).at[b_idx, senders].add(
        msgs, indices_are_sorted=indices_sorted)
    return out.astype(x.dtype)


class Encoder(nn.Module):
    """gnn_transformer.py:21-62: embeddings + 6 rounds of
    {mark-fusion Combination -> concat [diff || sub || ast_change] -> GCN}."""

    cfg: FiraConfig
    dtype: jnp.dtype = jnp.float32

    def _residual_dtype(self):
        return None if self.cfg.stable_residual else self.dtype

    @nn.compact
    def __call__(self, diff, mark, ast_change, adj, sub_token,
                 *, deterministic: bool):
        cfg = self.cfg
        word_embed = nn.Embed(
            cfg.vocab_size, cfg.embedding_dim,
            embedding_init=torch_embed_init, dtype=self.dtype, name="word_embed",
        )
        mark_embed = nn.Embed(
            4, cfg.embedding_dim,
            embedding_init=torch_embed_init, dtype=self.dtype, name="mark_embed",
        )
        ast_change_embed = nn.Embed(
            cfg.ast_change_vocab_size, cfg.embedding_dim,
            embedding_init=torch_embed_init, dtype=self.dtype,
            name="ast_change_embed",
        )

        # padding_idx=0 semantics (gnn_transformer.py:32-39): pad rows
        # contribute exactly zero.
        def embed_padded(table, ids):
            return table(ids) * (ids != 0)[..., None].astype(self.dtype)

        pos = jnp.asarray(position_encoding(cfg.sou_len, cfg.embedding_dim),
                          dtype=self.dtype)
        input_em = embed_padded(word_embed, diff) + pos[None, :, :]
        mark_em = embed_padded(mark_embed, mark)
        ast_change_em = embed_padded(ast_change_embed, ast_change)
        sub_token_em = embed_padded(word_embed, sub_token)

        # One persistent (B, graph_len, d) node buffer for the whole stack:
        # each round the Combination rewrites only the first sou_len rows
        # (diff nodes fused with their marks) in place, then the GCN mixes
        # the full graph. The reference splits the buffer into three tensors
        # and re-concatenates every round (gnn_transformer.py:46-58) — six
        # (B, 650, 256) relayout copies per step that a static update-slice
        # never materializes. Same values, same parameter tree.
        if cfg.encoder_buffer not in ("single", "split"):
            raise ValueError(
                f"unknown encoder_buffer {cfg.encoder_buffer!r}; "
                f"choose 'single' or 'split'")
        split = cfg.encoder_buffer == "split"
        if split and callable(adj):
            raise ValueError(
                "encoder_buffer='split' needs the dense adjacency (its A.x "
                "runs as two column slabs); use adjacency_impl='dense'")
        if split:
            top = input_em
            rest = jnp.concatenate([sub_token_em, ast_change_em], axis=1)
            # loop-invariant column slabs: sliced once, reused by all rounds
            adj = (adj[:, :, : cfg.sou_len], adj[:, :, cfg.sou_len :])
            graph_em = (top, rest)
        else:
            graph_em = jnp.concatenate(
                [input_em, sub_token_em, ast_change_em], axis=1)
        for i in range(cfg.num_layers):
            input_em = graph_em[0] if split else graph_em[:, : cfg.sou_len]
            input_em = Combination(
                num_heads=cfg.num_head, d_model=cfg.embedding_dim,
                dropout_rate=cfg.dropout_rate, dtype=self.dtype,
                residual_dtype=self._residual_dtype(),
                name=f"combination_{i}",
            )(input_em, input_em, mark_em, deterministic=deterministic)
            # the buffer update does not promote dtypes the way the old
            # concatenate did: round 0's buffer is the compute dtype while
            # the Combination's post-LN output is the stable dtype — cast
            # the update (f32/f64: no-op; bf16: affects only round 0's GCN
            # residual precision, the fc1 input is cast either way)
            if split:
                graph_em = (input_em.astype(graph_em[1].dtype), graph_em[1])
            else:
                graph_em = jax.lax.dynamic_update_slice_in_dim(
                    graph_em, input_em.astype(graph_em.dtype), 0, axis=1)
            graph_em = GCN(
                d_model=cfg.embedding_dim, dropout_rate=cfg.gcn_dropout_rate,
                dtype=self.dtype, residual_dtype=self._residual_dtype(),
                name=f"gcn_{i}",
            )(graph_em, adj, deterministic=deterministic)

        if split:
            return graph_em[0], graph_em[1][:, : cfg.sub_token_len]
        return (graph_em[:, : cfg.sou_len],
                graph_em[:, cfg.sou_len : cfg.sou_len + cfg.sub_token_len])


class Decoder(nn.Module):
    """gnn_transformer.py:88-122: 6 x {causal self-attn, cross-attn over
    [diff || sub-token] encoder states, FFN}, all post-LN.

    setup-based so the KV-cached decode path (``cross_kv`` once per batch +
    ``decode_step`` once per position) can reuse the exact same parameters
    as the full-prefix ``__call__``. Layer scope names (self_attn_i /
    cross_attn_i / ffn_i / embed) are unchanged from the previous compact
    layout — checkpoints and parity tests see the same tree."""

    cfg: FiraConfig
    dtype: jnp.dtype = jnp.float32
    ring_mesh: object = None  # (data, seq) mesh for cross-attention SP

    def setup(self):
        cfg = self.cfg
        # no padding_idx on the decoder embedding (gnn_transformer.py:93-94)
        self.embed = nn.Embed(
            cfg.vocab_size, cfg.embedding_dim,
            embedding_init=torch_embed_init, dtype=self.dtype,
        )
        for i in range(cfg.num_layers):
            # setattr keeps the historical per-layer scope names; Flax
            # registers setup attribute assignments whatever their spelling
            rdt = None if cfg.stable_residual else self.dtype
            setattr(self, f"self_attn_{i}", Attention(
                num_heads=cfg.num_head, d_model=cfg.embedding_dim,
                dropout_rate=cfg.dropout_rate, dtype=self.dtype,
                residual_dtype=rdt))
            # only cross-attention rides the ring: its key axis ([diff||sub]
            # source states) is the one that grows with context length;
            # causal self-attention stays dense (attend() keeps causal=True
            # off the ring path, and these layers get no ring_mesh)
            setattr(self, f"cross_attn_{i}", Attention(
                num_heads=cfg.num_head, d_model=cfg.embedding_dim,
                dropout_rate=cfg.dropout_rate, dtype=self.dtype,
                residual_dtype=rdt, ring_mesh=self.ring_mesh))
            setattr(self, f"ffn_{i}", FeedForward(
                d_model=cfg.embedding_dim, mult=cfg.ffn_mult,
                dropout_rate=cfg.dropout_rate, dtype=self.dtype,
                residual_dtype=rdt))

    def _pos_table(self) -> jnp.ndarray:
        cfg = self.cfg
        return jnp.asarray(position_encoding(cfg.tar_len, cfg.embedding_dim),
                           dtype=self.dtype)

    def __call__(self, tar, sou_embedding, sou_mask, tar_mask_pad,
                 *, deterministic: bool):
        cfg = self.cfg
        T = tar.shape[1]
        x = self.embed(tar) + self._pos_table()[None, :T, :]

        # (B,1,1,T) pad mask AND (1,1,T,T) causal (gnn_transformer.py:117),
        # applied as two chained where-terms inside attend (causal=True) so
        # the combined (B,1,T,T) boolean buffer never materializes
        for i in range(cfg.num_layers):
            x = getattr(self, f"self_attn_{i}")(
                x, x, x, tar_mask_pad, deterministic=deterministic,
                causal=True)
            x = getattr(self, f"cross_attn_{i}")(
                x, sou_embedding, sou_embedding, sou_mask,
                deterministic=deterministic)
            x = getattr(self, f"ffn_{i}")(x, deterministic=deterministic)
        return x

    def cross_kv(self, sou_embedding):
        """Per-layer cross-attention K/V of the encoder states, computed
        once per batch: (L, B, H, S, d_head) x 2. The full-prefix path
        recomputes these every beam step (the reference recomputes them
        every step x beam, run_model.py:256)."""
        ks, vs = [], []
        for i in range(self.cfg.num_layers):
            k, v = getattr(self, f"cross_attn_{i}").project_kv(
                sou_embedding, sou_embedding)
            ks.append(k)
            vs.append(v)
        return jnp.stack(ks), jnp.stack(vs)

    def decode_step(self, tok, pos_idx, k_cache, v_cache, cross_k, cross_v,
                    sou_mask, self_mask):
        """One decode position with cached K/V.

        tok: (B, 1) token ids at position ``pos_idx`` (traced scalar);
        k_cache/v_cache: (L, B, H, tar_len, d_head) self-attention caches;
        cross_k/cross_v: from :meth:`cross_kv`; self_mask: (B, 1, 1, tar_len)
        validity of cached positions. Returns (x (B,1,D), k_cache, v_cache)
        with position ``pos_idx`` of the caches filled.

        Mathematically identical to slicing position ``pos_idx`` out of
        ``__call__`` over the full prefix: post-LN blocks act per position,
        and causality makes cached K/V equal recomputed K/V.
        """
        x = self.embed(tok) + jax.lax.dynamic_slice_in_dim(
            self._pos_table(), pos_idx, 1, axis=0)[None, :, :]
        # cache writes CAST to the arena's storage dtype and reads UPCAST
        # to the stable dtype (both no-ops for the default f32 arena):
        # cfg.kv_dtype="bf16" stores the K/V stripes half-width while the
        # attention math stays full precision (decode/quant.py)
        cd = stable_dtype(k_cache.dtype)
        for i in range(self.cfg.num_layers):
            sa = getattr(self, f"self_attn_{i}")
            k_new, v_new = sa.project_kv(x, x)       # (B, H, 1, d_head)
            k_cache = k_cache.at[i, :, :, pos_idx, :].set(
                k_new[:, :, 0, :].astype(k_cache.dtype))
            v_cache = v_cache.at[i, :, :, pos_idx, :].set(
                v_new[:, :, 0, :].astype(v_cache.dtype))
            x = sa.attend(x, k_cache[i].astype(cd), v_cache[i].astype(cd),
                          self_mask, deterministic=True)
            x = getattr(self, f"cross_attn_{i}").attend(
                x, cross_k[i], cross_v[i], sou_mask, deterministic=True)
            x = getattr(self, f"ffn_{i}")(x, deterministic=True)
        return x, k_cache, v_cache

    def embed_at(self, tok, pos_idx):
        """Decoder INPUT stem at per-row positions — token embedding plus
        the positional row, k-position capable: ``tok`` (B, n) with
        ``pos_idx`` (B, n) embeds n positions per row at once; the cached
        step paths call it at n=1 with a (B,) vector. Exposed on its own
        so the speculative copy drafter (decode/spec.py) can score the
        copy head against the raw target embedding WITHOUT running any
        decoder layer."""
        pos = pos_idx.astype(jnp.int32)
        table = self._pos_table()[pos]
        if table.ndim == 2:            # (B,) positions -> (B, 1, D) rows
            table = table[:, None, :]
        return self.embed(tok) + table

    def decode_step_multi(self, tok, pos_idx, k_cache, v_cache, cross_k,
                          cross_v, sou_mask, self_mask):
        """One cached decode position PER ROW: like :meth:`decode_step` but
        ``pos_idx`` is a (B,) vector — row b advances its own position
        ``pos_idx[b]``. The slot-refill engine (decode/engine.py) holds
        samples at mixed decode depths in one fixed-shape program, so the
        shared-scalar position of the batch beam does not apply. Per row
        the math is identical to :meth:`decode_step` at that row's scalar
        position: the position-table row is gathered per row instead of
        sliced once, and the cache write scatters per-row columns."""
        B = tok.shape[0]
        pos = pos_idx.astype(jnp.int32)
        b_idx = jnp.arange(B)
        x = self.embed_at(tok, pos)
        # same storage-cast / read-upcast rule as decode_step (no-op f32)
        cd = stable_dtype(k_cache.dtype)
        for i in range(self.cfg.num_layers):
            sa = getattr(self, f"self_attn_{i}")
            k_new, v_new = sa.project_kv(x, x)       # (B, H, 1, d_head)
            k_cache = k_cache.at[i, b_idx, :, pos, :].set(
                k_new[:, :, 0, :].astype(k_cache.dtype))
            v_cache = v_cache.at[i, b_idx, :, pos, :].set(
                v_new[:, :, 0, :].astype(v_cache.dtype))
            x = sa.attend(x, k_cache[i].astype(cd), v_cache[i].astype(cd),
                          self_mask, deterministic=True)
            x = getattr(self, f"cross_attn_{i}").attend(
                x, cross_k[i], cross_v[i], sou_mask, deterministic=True)
            x = getattr(self, f"ffn_{i}")(x, deterministic=True)
        return x, k_cache, v_cache

    def decode_step_paged(self, tok, pos_idx, k_pool, v_pool, block_tab,
                          cross_k, cross_v, sou_mask, self_mask):
        """:meth:`decode_step_multi` with the self-attention cache behind
        BLOCK-TABLE INDIRECTION (the slot engine's paged KV arena,
        decode/engine.py): instead of each row owning a whole-sequence
        (tar_len) cache stripe, the cache lives in a fixed pool of KV
        blocks — k_pool/v_pool: (L, P, K, H, block, d_head) — and
        ``block_tab`` (S, W) maps slot s's position range
        [w*block, (w+1)*block) to a pool block (sentinel id P = unmapped:
        reads clamp to garbage the validity mask zeroes exactly, writes
        drop). Per written position the gathered cache view is
        bit-identical to the whole-sequence cache, so the attention math
        — and therefore the beam trajectory — is unchanged
        (tests/test_paged_kv.py pins tokens AND probs bitwise).

        tok: (S*K, 1) token ids; pos_idx: (S*K,) per-row positions (rows
        of one slot share theirs); W*block must equal the attended cache
        width ``self_mask.shape[-1]``."""
        _L, _P, K, _H, BS, _dh = k_pool.shape
        B = tok.shape[0]
        S, W = block_tab.shape
        if W * BS != self_mask.shape[-1] or B != S * K:
            raise ValueError(
                f"paged cache geometry mismatch: table {W} x block {BS} "
                f"must tile the {self_mask.shape[-1]}-position budget and "
                f"pool beam lanes {K} x {S} slots must equal the {B} rows")
        pos = pos_idx.astype(jnp.int32)
        slot = jnp.arange(B, dtype=jnp.int32) // K
        krow = jnp.arange(B, dtype=jnp.int32) % K
        blk = block_tab[slot, pos // BS]             # (B,) current tail block
        off = pos % BS
        x = self.embed(tok) + self._pos_table()[pos][:, None, :]
        for i in range(self.cfg.num_layers):
            sa = getattr(self, f"self_attn_{i}")
            k_new, v_new = sa.project_kv(x, x)       # (B, H, 1, d_head)
            k_pool = append_block_kv(k_pool, i, blk, krow, off,
                                     k_new[:, :, 0, :])
            v_pool = append_block_kv(v_pool, i, blk, krow, off,
                                     v_new[:, :, 0, :])
            x = sa.attend(x, gather_block_kv(k_pool[i], block_tab),
                          gather_block_kv(v_pool[i], block_tab),
                          self_mask, deterministic=True)
            x = getattr(self, f"cross_attn_{i}").attend(
                x, cross_k[i], cross_v[i], sou_mask, deterministic=True)
            x = getattr(self, f"ffn_{i}")(x, deterministic=True)
        return x, k_pool, v_pool


class _ScoreHead(nn.Module):
    """Parameter container matching TorchDense(1, name="score") exactly
    (names, shapes, init), so both score implementations share one
    checkpoint-compatible param tree."""

    d_in: int

    @nn.compact
    def __call__(self):
        kernel = self.param("kernel", torch_kernel_init, (self.d_in, 1),
                            jnp.float32)
        bias = self.param(
            "bias",
            lambda k, s, d: torch_bias_init(k, s, d, self.d_in),
            (1,), jnp.float32,
        )
        return kernel, bias


class CopyNet(nn.Module):
    """Model.py:7-20: Bahdanau-style pointer scores over source positions
    plus a 2-way generate/copy gate.

    ``impl`` selects the scoring path: "xla" materializes the (B,T,S,D)
    tanh intermediate in forward and rematerializes it in backward
    (jax.checkpoint); "pallas" runs the fused kernel (ops/copy_score.py)
    that streams it through VMEM and never touches HBM with it."""

    d_model: int
    impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    remat: bool = True  # False stores the (B,T,S,D) tanh for backward

    def setup(self):
        self.src_proj = TorchDense(self.d_model, use_bias=False,
                                   dtype=self.dtype)
        self.tgt_proj = TorchDense(self.d_model, use_bias=False,
                                   dtype=self.dtype)
        self.score = _ScoreHead(self.d_model)
        self.gate = TorchDense(2, dtype=self.dtype)

    def project_src(self, source):
        """(B,S,D) source projection — constant per batch, computed once by
        the KV-cached decode instead of once per beam step."""
        return self.src_proj(source)

    def score_gate(self, src, target):
        """Pointer scores + gate from a pre-projected source."""
        tgt = self.tgt_proj(target)                   # (B,T,D)
        kernel, bias = self.score()
        if self.impl == "pallas":
            scores = copy_score.copy_scores(
                src, tgt, kernel.astype(self.dtype), bias.astype(self.dtype)
            )
        elif self.impl == "xla":
            # remat (default): recompute the (B,T,S,D) tanh intermediate in
            # backward instead of storing it; cfg.copy_head_remat=False
            # stores it instead — values identical either way
            fn = copy_score.copy_scores_reference
            if self.remat:
                fn = jax.checkpoint(fn)
            scores = fn(
                src, tgt, kernel.astype(self.dtype), bias.astype(self.dtype)
            )
        else:
            raise ValueError(
                f"copy_head_impl={self.impl!r} not in {{'xla', 'pallas'}}")
        gate = jax.nn.softmax(
            self.gate(target).astype(stable_dtype(self.dtype)), axis=-1,
        )
        return scores, gate

    def __call__(self, source, target):
        return self.score_gate(self.project_src(source), target)


class FiraModel(nn.Module):
    """Model.py:24-86: encoder + decoder + fused gen/copy distribution."""

    cfg: FiraConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.cfg
        ring_mesh = None
        if cfg.seq_shards > 1:
            from fira_tpu.parallel.ring import seq_mesh
            import jax as _jax

            n_dev = len(_jax.devices())
            if n_dev % cfg.seq_shards:
                raise ValueError(
                    f"seq_shards={cfg.seq_shards} does not divide the "
                    f"{n_dev} visible devices")
            ring_mesh = seq_mesh(n_data=n_dev // cfg.seq_shards,
                                 n_seq=cfg.seq_shards)
        self.encoder = Encoder(cfg, dtype=self.dtype)
        self.decoder = Decoder(cfg, dtype=self.dtype, ring_mesh=ring_mesh)
        self.copy_net = CopyNet(cfg.embedding_dim, impl=cfg.copy_head_impl,
                                dtype=self.dtype, remat=cfg.copy_head_remat)
        self.out_fc = TorchDense(cfg.vocab_size, dtype=self.dtype)
        if cfg.typed_edges:
            from fira_tpu.data.graph_build import N_EDGE_KINDS

            self.edge_gain = self.param(
                "edge_gain", nn.initializers.ones, (N_EDGE_KINDS,),
                jnp.float32)

    def encode(self, batch: Dict[str, jnp.ndarray], *,
               deterministic: bool = True):
        """Run the graph encoder once; returns ([diff||sub] states, mask)."""
        cfg = self.cfg
        batch = dict(batch)
        # node count from the BATCH, not the config: equals cfg.graph_len at
        # full pad, smaller under a bucketed geometry (data/buckets.py) whose
        # ast_change tail was truncated — the diff/sub regions are pinned by
        # the copy-label id space and never shrink
        graph_len = (batch["diff"].shape[1] + batch["sub_token"].shape[1]
                     + batch["ast_change"].shape[1])
        if cfg.typed_edges:
            # typed-edge extension: per-family learned gain on the normalized
            # weights; at init (all ones) this is bit-identical to the
            # reference's flattened adjacency
            batch["values"] = batch["values"] * self.edge_gain.astype(
                batch["values"].dtype)[batch["edge_kinds"].astype(jnp.int32)]
        if cfg.adjacency_impl == "segment":
            if cfg.flat_scatter:
                raise ValueError(
                    "flat_scatter applies to the dense adjacency build; "
                    "use adjacency_impl='dense'")
            adj = functools.partial(
                coo_matvec, batch["senders"], batch["receivers"],
                batch["values"], indices_sorted=cfg.sort_edges,
            )
        elif cfg.adjacency_impl == "dense":
            # scatter straight into the compute dtype: dedup guarantees one
            # value per cell (dense_adjacency docstring), so this is
            # bit-identical to the f32 scatter + cast it replaces while
            # never materializing the f32 (B, N, N) buffer at all
            adj = dense_adjacency(
                batch["senders"], batch["receivers"], batch["values"],
                graph_len, indices_sorted=cfg.sort_edges,
                out_dtype=self.dtype, flat=cfg.flat_scatter,
            )
        else:
            raise ValueError(
                f"adjacency_impl={cfg.adjacency_impl!r} not in "
                f"{{'dense', 'segment'}}")
        sou_mask = batch["diff"] != 0
        sub_mask = batch["sub_token"] != 0
        sou_emb, sub_emb = self.encoder(
            batch["diff"], batch["diff_mark"], batch["ast_change"], adj,
            batch["sub_token"], deterministic=deterministic,
        )
        states = jnp.concatenate([sou_emb, sub_emb], axis=1)
        mask = jnp.concatenate([sou_mask, sub_mask], axis=1)
        return states, mask

    def _dist_parts(self, states, mask, tar, tar_mask_pad, *,
                    deterministic: bool = True):
        """The fused distribution's three factors — generation softmax over
        the vocab, copy softmax over source positions, 2-way gate — WITHOUT
        assembling the (B, T, vocab+sou+sub) concatenation. The training
        loss gathers one label per position from the factors directly
        (gate*dist then gather == gather then gate — multiplication is
        elementwise), skipping ~1.5 GB/step of full-vocab f32 assembly at
        flagship geometry; the beam consumes the assembled form via
        :meth:`fused_probs`."""
        tar_emb = self.decoder(tar, states, mask, tar_mask_pad,
                               deterministic=deterministic)
        gen = jax.nn.softmax(
            self.out_fc(tar_emb).astype(stable_dtype(self.dtype)), axis=-1
        )
        scores, gate = self.copy_net(states, tar_emb)
        scores = jnp.where(mask[:, None, :], scores, jnp.asarray(-1e9, scores.dtype))
        copy = jax.nn.softmax(scores.astype(stable_dtype(self.dtype)), axis=-1)
        return gen, copy, gate

    def fused_probs(self, states, mask, tar, tar_mask_pad, *,
                    deterministic: bool = True):
        """Decoder + copy fusion -> probability-space distribution over
        vocab_size + sou_len + sub_token_len (Model.py:52-64). The beam
        search consumes this directly in its reference-compat prob-space
        accumulation mode (run_model.py:257-271)."""
        gen, copy, gate = self._dist_parts(states, mask, tar, tar_mask_pad,
                                           deterministic=deterministic)
        return jnp.concatenate(
            [gate[:, :, 0:1] * gen, gate[:, :, 1:2] * copy], axis=-1
        )

    def decode_init(self, states):
        """Everything constant across decode steps, computed once per batch:
        per-layer cross-attention K/V of the encoder states and the copy
        head's source projection. The reference recomputes all of it every
        step x beam (run_model.py:256-259)."""
        cross_k, cross_v = self.decoder.cross_kv(states)
        return cross_k, cross_v, self.copy_net.project_src(states)

    def copy_draft_scores(self, mask, src_proj, tok, pos_idx):
        """Speculative COPY drafter head (decode/spec.py, tier ``copy``):
        the pointer scores ALONE against the raw target-embedding proxy
        ``Decoder.embed_at(tok, pos_idx)`` — no decoder layer runs and no
        cache is touched, so a k-token draft roll costs k embedding rows
        plus k copy-score passes. Scores get the same source-validity mask
        as :meth:`_step_heads`; the drafter argmaxes them into copy-space
        proposals (``vocab_size +`` source position). Draft quality only
        moves the acceptance rate — never output bytes (the verify program
        is the exact step body) — so the proxy target is deliberately
        cheap."""
        x = self.decoder.embed_at(tok, pos_idx)
        scores, _gate = self.copy_net.score_gate(src_proj, x)
        return jnp.where(mask[:, None, :], scores,
                         jnp.asarray(-1e9, scores.dtype))

    def dist_parts(self, states, mask, tar, tar_mask_pad, *,
                   deterministic: bool = True):
        """Public factor view for the factored beam (cfg.beam_factored_topk):
        (gen, copy, gate) with no fused assembly — see :meth:`_dist_parts`."""
        return self._dist_parts(states, mask, tar, tar_mask_pad,
                                deterministic=deterministic)

    def _step_heads(self, mask, src_proj, tar_emb):
        """Shared generation/copy/gate head of the cached one-position
        decode paths (scalar-position :meth:`dist_parts_step` and the
        engine's per-row :meth:`dist_parts_step_multi`)."""
        gen = jax.nn.softmax(
            self.out_fc(tar_emb).astype(stable_dtype(self.dtype)), axis=-1
        )
        scores, gate = self.copy_net.score_gate(src_proj, tar_emb)
        scores = jnp.where(mask[:, None, :], scores,
                           jnp.asarray(-1e9, scores.dtype))
        copy = jax.nn.softmax(scores.astype(stable_dtype(self.dtype)), axis=-1)
        return gen, copy, gate

    def dist_parts_step(self, mask, tok, pos_idx, k_cache, v_cache,
                        cross_k, cross_v, src_proj, self_mask):
        """One-position distribution FACTORS with KV caching: the
        (gen, copy, gate) triple of :meth:`fused_probs_step` without the
        fused concatenation/gate products. The factored beam takes per-side
        top-k from these directly (the fused distribution is the two sides
        scaled by their gate weights, so the global top-k lives in the
        union of the per-side top-ks)."""
        tar_emb, k_cache, v_cache = self.decoder.decode_step(
            tok, pos_idx, k_cache, v_cache, cross_k, cross_v, mask, self_mask,
        )
        gen, copy, gate = self._step_heads(mask, src_proj, tar_emb)
        return gen, copy, gate, k_cache, v_cache

    def dist_parts_step_multi(self, mask, tok, pos_idx, k_cache, v_cache,
                              cross_k, cross_v, src_proj, self_mask):
        """Per-ROW-position twin of :meth:`dist_parts_step` (``pos_idx`` is
        a (B,) vector): the slot-refill engine's step program advances every
        slot at its own depth in one dispatch (decode/engine.py). Row-wise
        identical math — Decoder.decode_step_multi plus the same heads."""
        tar_emb, k_cache, v_cache = self.decoder.decode_step_multi(
            tok, pos_idx, k_cache, v_cache, cross_k, cross_v, mask, self_mask,
        )
        gen, copy, gate = self._step_heads(mask, src_proj, tar_emb)
        return gen, copy, gate, k_cache, v_cache

    def dist_parts_step_paged(self, mask, tok, pos_idx, k_pool, v_pool,
                              block_tab, cross_k, cross_v, src_proj,
                              self_mask):
        """Paged-arena twin of :meth:`dist_parts_step_multi`: the self-
        attention cache is read and written through block-table
        indirection (Decoder.decode_step_paged) instead of whole-sequence
        stripes; heads are the shared :meth:`_step_heads`, so per row the
        distribution factors are bit-identical to the unpaged step."""
        tar_emb, k_pool, v_pool = self.decoder.decode_step_paged(
            tok, pos_idx, k_pool, v_pool, block_tab, cross_k, cross_v,
            mask, self_mask,
        )
        gen, copy, gate = self._step_heads(mask, src_proj, tar_emb)
        return gen, copy, gate, k_pool, v_pool

    def fused_probs_step_paged(self, mask, tok, pos_idx, k_pool, v_pool,
                               block_tab, cross_k, cross_v, src_proj,
                               self_mask):
        """Paged-arena twin of :meth:`fused_probs_step_multi` — the
        engine's non-factored step head over the block pool."""
        gen, copy, gate, k_pool, v_pool = self.dist_parts_step_paged(
            mask, tok, pos_idx, k_pool, v_pool, block_tab, cross_k,
            cross_v, src_proj, self_mask)
        fused = jnp.concatenate(
            [gate[:, :, 0:1] * gen, gate[:, :, 1:2] * copy], axis=-1
        )
        return fused, k_pool, v_pool

    def fused_probs_step_multi(self, mask, tok, pos_idx, k_cache, v_cache,
                               cross_k, cross_v, src_proj, self_mask):
        """Per-ROW-position twin of :meth:`fused_probs_step` — the engine's
        non-factored step head. Returns (fused (B, 1, V_out), caches)."""
        gen, copy, gate, k_cache, v_cache = self.dist_parts_step_multi(
            mask, tok, pos_idx, k_cache, v_cache, cross_k, cross_v,
            src_proj, self_mask)
        fused = jnp.concatenate(
            [gate[:, :, 0:1] * gen, gate[:, :, 1:2] * copy], axis=-1
        )
        return fused, k_cache, v_cache

    def fused_probs_step(self, mask, tok, pos_idx, k_cache, v_cache,
                         cross_k, cross_v, src_proj, self_mask):
        """One-position fused distribution with KV caching: same math as
        slicing position ``pos_idx`` out of :meth:`fused_probs`, at O(1)
        decoder cost per step instead of O(tar_len). Returns
        (fused (B, 1, V_out), k_cache, v_cache)."""
        gen, copy, gate, k_cache, v_cache = self.dist_parts_step(
            mask, tok, pos_idx, k_cache, v_cache, cross_k, cross_v,
            src_proj, self_mask)
        fused = jnp.concatenate(
            [gate[:, :, 0:1] * gen, gate[:, :, 1:2] * copy], axis=-1
        )
        return fused, k_cache, v_cache

    def fused_log_probs(self, states, mask, tar, tar_mask_pad, *,
                        deterministic: bool = True):
        """log-clamped fused distribution (Model.py:69: clip to [1e-10, 1])."""
        fused = self.fused_probs(states, mask, tar, tar_mask_pad,
                                 deterministic=deterministic)
        return jnp.log(jnp.clip(fused, 1e-10, 1.0))

    def __call__(self, batch: Dict[str, jnp.ndarray], *,
                 deterministic: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Training/dev forward. Returns (loss_sum, token_count) like the
        reference (Model.py:83-84); callers normalize (run_model.py:105)."""
        states, mask = self.encode(batch, deterministic=deterministic)
        tar = batch["msg"]
        gen, copy, gate = self._dist_parts(
            states, mask, tar, tar != 0, deterministic=deterministic
        )
        # label = tar_label shifted left with a zero column (Model.py:71-79)
        label = jnp.concatenate(
            [batch["msg_tar"][:, 1:],
             jnp.zeros((tar.shape[0], 1), dtype=batch["msg_tar"].dtype)],
            axis=1,
        )
        label_mask = label != 0
        # Gather the label's probability from the distribution FACTORS, then
        # log-clamp (Model.py:69's clip to [1e-10, 1]). Equivalent to
        # assembling the fused (B, T, 25k) tensor, log-clamping it, and
        # gathering after — gate multiplication and log are elementwise, so
        # both commute with the gather — but neither the concatenation nor
        # the full-vocab gate products nor the full f32 log tensor
        # (~2 GB/step combined at flagship) is ever materialized.
        V = self.cfg.vocab_size
        label = label.astype(jnp.int32)
        is_gen = label < V
        gi = jnp.where(is_gen, label, 0)[..., None]
        ci = jnp.clip(label - V, 0, copy.shape[-1] - 1)[..., None]
        pg = jnp.take_along_axis(gen, gi, axis=-1)[..., 0] * gate[..., 0]
        pc = jnp.take_along_axis(copy, ci, axis=-1)[..., 0] * gate[..., 1]
        p = jnp.where(is_gen, pg, pc)
        nll = -jnp.log(jnp.clip(p, 1e-10, 1.0))
        nll = jnp.where(label_mask, nll, 0.0)
        return nll.sum(), label_mask.sum()

    def dev_predict(self, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        """Teacher-forced greedy ids for all positions at once (Model.py:86).

        argmax over the probability-space distribution: log-clamp is
        monotonic on [1e-10, 1] and the 25k-way softmax's max is always
        >= 1/25020 > 1e-10, so the argmax is identical to the reference's
        argmax over the clamped log — minus a full-vocab f32 log pass."""
        states, mask = self.encode(batch, deterministic=True)
        tar = batch["msg"]
        fused = self.fused_probs(states, mask, tar, tar != 0)
        return jnp.argmax(fused, axis=-1)
