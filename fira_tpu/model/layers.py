"""Building-block Flax modules for the FIRA graph encoder / decoder.

Each module is a TPU-first rebuild of a reference layer (cited per class from
/root/reference/gnn_transformer.py and combination_layer.py), matching the
live math exactly — post-LN residuals, dropout sites (0.2 in the GCN, 0.1
elsewhere), additive -1e9 masking, interleaved sin/cos positions — while
omitting the reference's dead modules (lstm, combination_list1, gate_fc;
SURVEY.md Appendix B).

Initializers mirror PyTorch defaults so training dynamics are comparable:
Linear weights ~ U(+-1/sqrt(fan_in)) (kaiming_uniform with a=sqrt(5)),
Linear biases ~ U(+-1/sqrt(fan_in)), Embedding ~ N(0,1).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from flax import linen as nn

# torch nn.Linear default: kaiming_uniform(a=sqrt(5)) == U(+-sqrt(1/fan_in))
torch_kernel_init = nn.initializers.variance_scaling(1.0 / 3.0, "fan_in", "uniform")
torch_embed_init = nn.initializers.normal(stddev=1.0)


def stable_dtype(dtype):
    """Numerics-sensitive ops (LayerNorm, softmax, log) run in at least
    float32: bf16 compute promotes to f32, f64 (parity testing) stays f64."""
    return jnp.promote_types(dtype, jnp.float32)


def residual_out(x, residual_dtype):
    """Post-LN output cast for the stable_residual=False perf knob: LN
    statistics stay in the stable dtype; only the STORED residual stream is
    narrowed (no-op when residual_dtype is None — the default f32 parity
    numerics)."""
    return x if residual_dtype is None else x.astype(residual_dtype)


def torch_bias_init(key, shape, dtype, fan_in: int):
    bound = 1.0 / np.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


class TorchDense(nn.Module):
    """nn.Dense with PyTorch nn.Linear default initialization."""

    features: int
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        kernel = self.param(
            "kernel", torch_kernel_init, (fan_in, self.features), jnp.float32
        )
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param(
                "bias",
                lambda k, s, d: torch_bias_init(k, s, d, fan_in),
                (self.features,),
                jnp.float32,
            )
            y = y + bias.astype(self.dtype)
        return y


def position_encoding(length: int, dmodel: int) -> np.ndarray:
    """Interleaved sin/cos positions (gnn_transformer.py:10-19): for each
    frequency j the pair (sin, cos) is laid out adjacently — NOT the usual
    all-sin-then-all-cos layout."""
    pos = np.zeros((length, dmodel), dtype=np.float32)
    i = np.arange(length)[:, None].astype(np.float64)
    j = np.arange(dmodel // 2)[None, :].astype(np.float64)
    angle = i / np.power(10000.0, 2.0 * j / dmodel)
    pos[:, 0::2] = np.sin(angle)
    pos[:, 1::2] = np.cos(angle)
    return pos


def combination_gate(query, key, value, *, dropout=None, scale=None):
    """combination_layer.py:6-17: attention-free two-channel gating.

    Per element: weights = softmax over the pair (q*k/sqrt(d), q*v/sqrt(d));
    output = w0*k + w1*v, then dropout. Used to fuse token vs. diff-mark
    channels. ``scale`` overrides the 1/sqrt(last-dim) default — the
    multi-head wrapper passes 1/sqrt(d_head) while keeping tensors in
    merged (B, S, d_model) layout.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(query.shape[-1])
    qk = query * key * scale
    qv = query * value * scale
    # The 2-way softmax in closed form: softmax([a, b]) = (sigmoid(a-b),
    # sigmoid(b-a)). Same math in the same stable dtype as an explicit pair
    # softmax (no-op in f32; guards bf16 exp precision) WITHOUT stacking a
    # (..., 2) logits tensor — at flagship geometry that stack plus its
    # softmax round-trips ~146 MB of f32 per encoder round, pure HBM
    # traffic the closed form never touches.
    sd = stable_dtype(qk.dtype)
    diff = qk.astype(sd) - qv.astype(sd)
    w0 = jax.nn.sigmoid(diff).astype(qk.dtype)
    w1 = jax.nn.sigmoid(-diff).astype(qk.dtype)
    out = w0 * key + w1 * value
    if dropout is not None:
        out = dropout(out)
    return out


class Combination(nn.Module):
    """Multi-head wrapper around the combination gate
    (gnn_transformer.py:176-205): three input projections, per-head gating,
    output projection, post-LN residual on the query. Dropout is applied both
    inside the gate and after the output projection, as the reference does.
    """

    num_heads: int
    d_model: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    residual_dtype: object = None  # see residual_out

    @nn.compact
    def __call__(self, query, key, value, *, deterministic: bool):
        old_query = query
        # the reshape-based head split used to enforce divisibility; keep
        # the guard so a bad head count fails fast instead of silently
        # training with a scale that matches no valid head layout
        assert self.d_model % self.num_heads == 0, \
            f"d_model={self.d_model} not divisible by num_heads={self.num_heads}"
        d_head = self.d_model // self.num_heads

        # The gate is purely elementwise, so the reference's head
        # split/merge transposes (gnn_transformer.py:185-198) are layout
        # no-ops: elementwise math on (B, H, S, d_head) equals the same
        # math on (B, S, d_model). The head count only enters through the
        # 1/sqrt(d_head) scale, passed explicitly — bit-identical in
        # deterministic mode (what the torch-parity tests pin); the inner
        # dropout mask is now drawn in merged layout (same distribution,
        # different stream). Six (B, S, d_model) transpose copies per
        # layer saved (fwd + bwd).
        q = TorchDense(self.d_model, dtype=self.dtype, name="q_proj")(query)
        k = TorchDense(self.d_model, dtype=self.dtype, name="k_proj")(key)
        v = TorchDense(self.d_model, dtype=self.dtype, name="v_proj")(value)

        inner_dropout = nn.Dropout(self.dropout_rate, deterministic=deterministic)
        x = combination_gate(q, k, v, dropout=inner_dropout,
                             scale=1.0 / np.sqrt(d_head))
        out = TorchDense(self.d_model, dtype=self.dtype, name="out_proj")(x)
        out = nn.Dropout(self.dropout_rate, deterministic=deterministic)(out)
        return residual_out(
            nn.LayerNorm(epsilon=1e-5, dtype=stable_dtype(self.dtype),
                         name="norm")(out + old_query), self.residual_dtype)


class GCN(nn.Module):
    """One graph-convolution round (gnn_transformer.py:64-86):
    fc1 -> A.x -> fc2 -> dropout(0.2) + residual -> LayerNorm, over the
    shared normalized adjacency. ``adj`` is either a dense (B, N, N) batch
    (one MXU bmm) or a callable applying A.x directly from COO triplets
    (model.coo_matvec, the O(edges) path for large graphs)."""

    d_model: int
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.float32
    residual_dtype: object = None  # see residual_out

    @nn.compact
    def __call__(self, graph_em, adj, *, deterministic: bool):
        fc1 = TorchDense(self.d_model, dtype=self.dtype, name="fc1")
        fc2 = TorchDense(self.d_model, dtype=self.dtype, name="fc2")
        drop = nn.Dropout(self.dropout_rate)
        norm = nn.LayerNorm(epsilon=1e-5, dtype=stable_dtype(self.dtype),
                            name="norm")
        if isinstance(graph_em, tuple):
            # split-buffer mode (cfg.encoder_buffer="split"): the node
            # buffer never exists as one tensor — fc1/fc2/norm are the SAME
            # parameters applied per segment, A.x runs as two column-slab
            # bmms, and the single full-width dropout call keeps the RNG
            # stream identical to the single-buffer path. Outputs match
            # "single" to matmul-reassociation tolerance (two partial sums
            # instead of one 650-long contraction).
            top, rest = graph_em
            adj_top, adj_rest = adj
            s = top.shape[1]
            x = (jnp.einsum("bij,bjd->bid", adj_top.astype(self.dtype),
                            fc1(top))
                 + jnp.einsum("bij,bjd->bid", adj_rest.astype(self.dtype),
                              fc1(rest)))
            x = drop(fc2(x), deterministic=deterministic)
            y_top = residual_out(norm(x[:, :s] + top), self.residual_dtype)
            y_rest = residual_out(norm(x[:, s:] + rest), self.residual_dtype)
            return y_top, y_rest
        x = fc1(graph_em)
        if callable(adj):  # COO message-passing path (model.coo_matvec)
            x = adj(x)
        else:
            x = jnp.einsum("bij,bjd->bid", adj.astype(self.dtype), x)
        x = drop(fc2(x), deterministic=deterministic)
        return residual_out(norm(x + graph_em), self.residual_dtype)


class Attention(nn.Module):
    """Post-LN multi-head attention (gnn_transformer.py:124-161): additive
    -1e9 masking where mask==0, softmax, output projection, dropout, residual
    on the ORIGINAL query, LayerNorm.

    setup-based (not compact) so the K/V projection is callable separately
    from the attention itself: the KV-cached beam decode projects each new
    position once (``project_kv``) and attends over the cache (``attend``)
    instead of re-running the whole stack on the full prefix. Param names are
    identical to the previous compact layout (q_proj/k_proj/v_proj/out_proj/
    norm), so checkpoints and the weight-transplant parity tests are
    unaffected."""

    num_heads: int
    d_model: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    residual_dtype: object = None  # see residual_out
    # a (data, seq) jax.sharding.Mesh routes this module's attention core
    # through ring attention (parallel/ring.py) whenever the mask is a pure
    # key-padding mask and both sequence lengths divide the seq axis; adds
    # no parameters, so checkpoints are interchangeable with dense attention
    ring_mesh: object = None

    def setup(self):
        self.q_proj = TorchDense(self.d_model, dtype=self.dtype)
        self.k_proj = TorchDense(self.d_model, dtype=self.dtype)
        self.v_proj = TorchDense(self.d_model, dtype=self.dtype)
        self.out_proj = TorchDense(self.d_model, dtype=self.dtype)
        self.norm = nn.LayerNorm(epsilon=1e-5, dtype=stable_dtype(self.dtype))
        self.dropout = nn.Dropout(self.dropout_rate)

    def _ring_applicable(self, q, k, mask) -> bool:
        if self.ring_mesh is None or mask.ndim != 2:
            # ring carries key-padding semantics only; callers with richer
            # masking (4D decode-step masks, or causal=True — excluded at
            # the attend() call site) stay on the dense path
            return False
        from fira_tpu.parallel.ring import SEQ_AXIS

        n_seq = self.ring_mesh.shape[SEQ_AXIS]
        n_data = self.ring_mesh.shape["data"]
        return (q.shape[2] % n_seq == 0 and k.shape[2] % n_seq == 0
                and q.shape[0] % n_data == 0)

    def _split_heads(self, x):
        B, length = x.shape[0], x.shape[1]
        d_head = self.d_model // self.num_heads
        return x.reshape(B, length, self.num_heads, d_head).transpose(0, 2, 1, 3)

    def project_kv(self, key, value):
        """(B, L, D) inputs -> head-split (B, H, L, d_head) K and V."""
        return self._split_heads(self.k_proj(key)), \
            self._split_heads(self.v_proj(value))

    def attend(self, query, k, v, mask, *, deterministic: bool,
               causal: bool = False):
        """Attention over pre-projected K/V (as returned by project_kv).

        ``causal=True`` applies the lower-triangular mask as a SEPARATE
        broadcast where-term over the logits instead of expecting it folded
        into ``mask``: pad AND causal -> one (B,1,T,T) boolean buffer that
        XLA materializes and copies between fusions (~4 ms/step of pred
        copies in the round-4 per-op trace, docs/TPU_OP_TIMES.json); two
        chained wheres with (B,1,1,T) and (1,1,T,T) operands fuse into the
        logits computation with no batched mask buffer. Elementwise
        identical: both fills are the same -1e9."""
        old_query = query
        B, q_len = query.shape[0], query.shape[1]
        d_head = self.d_model // self.num_heads

        q = self._split_heads(self.q_proj(query))
        if not causal and self._ring_applicable(q, k, mask):
            # sequence-parallel exact attention: K/V blocks rotate over the
            # seq mesh axis with an online softmax (same -1e9 key-padding
            # semantics as the dense branch below)
            from fira_tpu.parallel.ring import ring_attention_sharded

            out = ring_attention_sharded(q, k, v, mask != 0, self.ring_mesh)
        else:
            weight = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d_head)
            if mask.ndim < 4:  # (B, kv_len) key-padding mask -> (B,1,1,kv)
                mask = mask[:, None, None, :]
            weight = jnp.where(mask == 0, jnp.asarray(-1e9, weight.dtype), weight)
            if causal:
                # the triangle below assumes queries start at key position 0;
                # a cached/offset decode step (q_len=1, kv_len=T) would get a
                # mask attending only key 0 — fail loudly on that misuse
                # (offset decode goes through the KV-cache path instead).
                # Shapes are static so this costs nothing at trace time; a
                # bare assert would vanish under `python -O`
                if q_len != k.shape[2]:
                    raise ValueError(
                        f"causal=True requires q_len == kv_len (got {q_len} "
                        f"vs {k.shape[2]}); offset decode must use the "
                        f"cache path")
                tri = jnp.tril(jnp.ones((q_len, k.shape[2]), dtype=bool))
                weight = jnp.where(tri[None, None],
                                   weight, jnp.asarray(-1e9, weight.dtype))
            weight = jax.nn.softmax(weight.astype(stable_dtype(self.dtype)), axis=-1).astype(self.dtype)
            out = jnp.einsum("bhqk,bhkd->bhqd", weight, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, q_len, self.d_model)
        out = self.out_proj(out)
        out = self.dropout(out, deterministic=deterministic)
        return residual_out(self.norm(out + old_query), self.residual_dtype)

    def __call__(self, query, key, value, mask, *, deterministic: bool,
                 causal: bool = False):
        k, v = self.project_kv(key, value)
        return self.attend(query, k, v, mask, deterministic=deterministic,
                           causal=causal)


def gather_block_kv(pool_l: jnp.ndarray, block_tab: jnp.ndarray
                    ) -> jnp.ndarray:
    """Assemble one layer's per-row K (or V) cache view from a paged block
    pool (decode/engine.py; docs/DECODE_ENGINE.md "Paged KV arena").

    pool_l: one layer's pool slice, (P, K, H, BS, d_head): P fixed pool
    blocks, each holding BS cache positions for all K beams of the owning
    slot. block_tab: (S, W) int32 — slot s's position range
    [w*BS, (w+1)*BS) lives in block ``block_tab[s, w]``; the sentinel id P
    marks unmapped entries (gather CLAMPS them to a garbage block whose
    values are exactly zeroed by the validity mask's -1e9 —
    beam.step_valid_mask).

    Returns (S*K, H, W*BS, d_head): row-major (slot, beam) rows in the
    exact layout ``Attention.attend`` consumes, bit-identical for every
    written position to the whole-sequence cache it replaces. A
    low-precision pool (cfg.kv_dtype="bf16" — decode/quant.py) UPCASTS on
    read to the stable dtype, so the attention math downstream runs full
    precision whatever the arena stores; for an f32 pool the cast is a
    no-op (the byte-identity contract path)."""
    P, K, H, BS, d_head = pool_l.shape
    S, W = block_tab.shape
    blocks = pool_l[block_tab]                      # (S, W, K, H, BS, dh)
    blocks = blocks.transpose(0, 2, 3, 1, 4, 5)     # (S, K, H, W, BS, dh)
    return blocks.reshape(S * K, H, W * BS, d_head).astype(
        stable_dtype(pool_l.dtype))


def gather_block_kv_beam(pool_l: jnp.ndarray, block_tab: jnp.ndarray,
                         beam: int) -> jnp.ndarray:
    """One BEAM LANE's dense cache view from the paged pool: the
    (S, H, W*BS, d_head) slice of :func:`gather_block_kv` at beam lane
    ``beam``, gathered without materializing the other K-1 lanes. The
    speculative draft-tier roll (decode/spec.py) copies the top-beam lane
    into a dense scratch cache once per draft and rolls on that — the
    pool itself is never written by a drafter. Same read-upcast rule as
    :func:`gather_block_kv` (no-op for an f32 pool)."""
    P, K, H, BS, d_head = pool_l.shape
    S, W = block_tab.shape
    blocks = pool_l[:, beam][block_tab]             # (S, W, H, BS, dh)
    blocks = blocks.transpose(0, 2, 1, 3, 4)        # (S, H, W, BS, dh)
    return blocks.reshape(S, H, W * BS, d_head).astype(
        stable_dtype(pool_l.dtype))


def append_block_kv(pool: jnp.ndarray, layer: int, blk: jnp.ndarray,
                    krow: jnp.ndarray, off: jnp.ndarray, new: jnp.ndarray
                    ) -> jnp.ndarray:
    """Write one decode position into the paged pool: row r's projected
    K (or V) at this step lands at ``pool[layer, blk[r], krow[r], :,
    off[r], :]``. pool: (L, P, K, H, BS, d_head); blk/krow/off: (B,) int32
    per-row block id / beam lane / in-block offset; new: (B, H, d_head).
    ``mode="drop"`` makes sentinel block ids (idle/done rows the engine
    masked out) write NOWHERE — a freed block can never be scribbled on by
    the slot that used to own it. The write CASTS to the pool's storage
    dtype (cfg.kv_dtype="bf16" stores the arena half-width —
    decode/quant.py; a no-op for the f32 pool)."""
    return pool.at[layer, blk, krow, :, off, :].set(
        new.astype(pool.dtype), mode="drop")


class FeedForward(nn.Module):
    """Post-LN 4x ReLU FFN (gnn_transformer.py:163-174)."""

    d_model: int
    mult: int = 4
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    residual_dtype: object = None  # see residual_out

    @nn.compact
    def __call__(self, x, *, deterministic: bool):
        h = TorchDense(self.mult * self.d_model, dtype=self.dtype, name="fc1")(x)
        h = jax.nn.relu(h)
        h = TorchDense(self.d_model, dtype=self.dtype, name="fc2")(h)
        h = nn.Dropout(self.dropout_rate, deterministic=deterministic)(h)
        return residual_out(
            nn.LayerNorm(epsilon=1e-5, dtype=stable_dtype(self.dtype),
                         name="norm")(h + x), self.residual_dtype)
