"""AST/graph extraction: diff chunks -> AST nodes, change nodes, edge lists.

Rebuilds the reference's per-chunk extraction worker
(/root/reference/Preprocess/process_data_ast_parallel.py and the GumTree
bridge get_ast_root_action.py) on top of the in-process native astdiff
component — no JVM, no temp files, no subprocesses.

Per update chunk (a delete-run followed by an add-run) the worker:
  1. reconstructs parseable Java from each fragment by bracket-balancing and
     wrapping in a ``class pad_pad_class`` shell per the reference's case
     analysis (process_data_ast_parallel.py:20-115, replicated exactly since
     which wrapper fires decides which AST exists and hence which edges);
  2. parses both versions (astdiff `parse`) and maps every AST leaf to a diff
     token position by ordered scanning (get_edge_ast_code, :132-185);
  3. tree-diffs old vs new (astdiff `diff`), reclassifies Match actions into
     match/update/move by joining against the Update/Move lists
     (get_ast_root_action.py:185-232), and emits one change node per
     surviving action with edges to the code/AST nodes it touches
     (get_edge_update, :187-298).
Context/pure-add/pure-delete chunks get only AST-structure edges
(get_edge_normal, :300-316).

Chunk-local indices are rebased into per-commit global coordinates and the
reassembled token stream must equal the original difftoken stream — the
reference's global invariant (:420).

Deliberately NOT replicated: the WASTE_TIME blocklist and CHANGE_SINGLE
input-rewrite tables (:16-17,38-39,123-124) — curated workarounds for inputs
that hang GumTree's JVM; the native parser handles or cleanly rejects them.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from fira_tpu.preprocess import astdiff_binding as astdiff
from fira_tpu.preprocess.fsm import Chunk

MODIFIERS = (
    "abstract", "default", "final", "native", "private", "protected",
    "public", "static", "strictfp", "transient", "volatile",
)

_ACTOR_RE = re.compile(
    r"^(?P<typ>[A-Za-z]+)(?:: (?P<name>.+?))?\((?P<idx>\d+)\)$")


class ExtractError(ValueError):
    """Invariant violation inside extraction (the reference uses asserts)."""


# --------------------------------------------------------------------------
# Java fragment reconstruction (process_data_ast_parallel.py:20-115)
# --------------------------------------------------------------------------

def balance_brackets(tokens: List[str]) -> List[str]:
    """Drop a leading stray '}', then close unmatched braces on both sides
    (process_bracket, :20-35)."""
    tokens = list(tokens)
    if tokens and tokens[0] == "}":
        tokens.pop(0)
    stack: List[str] = []
    for token in tokens:
        if token == "{":
            stack.append("{")
        elif token == "}":
            if stack and stack[-1] == "{":
                stack.pop()
            else:
                stack.append("}")
    unmatched_close = stack.count("}")
    unmatched_open = stack.count("{")
    return ["{"] * unmatched_close + tokens + ["}"] * unmatched_open


def reconstruct_java(code_tokens: Sequence[str]) -> Optional[Tuple[str, int]]:
    """Fragment tokens -> (parseable Java text, char offset of the fragment).

    Returns None when the fragment is empty after cleaning — the chunk then
    degrades to code-tokens-only, like the reference on GumTree failure.
    The wrapper case analysis replicates get_ast (:37-115): which shell a
    fragment gets decides the AST shape, so parity here is parity of edges.
    """
    text = " ".join(code_tokens)
    for junk in ("COMMENT", "SINGLE", "<nl>", "<nb>"):
        text = text.replace(junk, " ")
    if not text.strip():
        return None
    toks = astdiff.tokenize(text)
    if not toks:
        return None

    # stray-token cleanup (:56-65): a lone 'implement' typo token, a trailing
    # 'implements', an unclosed trailing generic on a class header
    if "implement" in toks:
        toks.remove("implement")
    if toks and toks[-1] == "implements":
        toks.remove("implements")  # first occurrence, like the reference (:59)
    if not toks:
        return None
    if len(toks) >= 4 and "class" in toks and toks[-2] == "<" and toks[-1] != ">":
        toks.append(">")

    toks = balance_brackets(toks)
    if not toks:
        return None
    fragment = " ".join(toks)

    if toks[0] in ("import", "package"):
        wrapped = toks
    elif toks[0] == "@":
        if "class" in toks:  # annotated class definition parses as-is
            wrapped = toks
        else:  # annotated method: needs a class shell
            wrapped = ["class", "pad_pad_class", "{"] + toks + ["}"]
    elif toks[0] in MODIFIERS:
        if "class" in toks:  # class definition
            if toks[-1] == "}":
                wrapped = toks
            else:
                wrapped = toks + ["{", "}"]
        elif ("(" in toks and ")" in toks
              and ("=" not in toks
                   or (toks.index("(") < toks.index("=")
                       and toks.index(")") < toks.index("=")))):
            # method definition (possibly header-only)
            if toks[-1] == "}":
                pass
            elif toks[-1] != ";":
                toks = toks + ["{", "}"]
            wrapped = ["class", "pad_pad_class", "{"] + toks + ["}"]
        else:  # field definition: extra instance-initializer block shell
            wrapped = (["class", "pad_pad_class", "{", "{"] + toks
                       + ["}", "}"])
    elif toks[0] == "{":
        wrapped = ["class", "pad_pad_class", "{"] + toks + ["}"]
    else:  # statement fragment
        if toks[0] == "if" and toks[-1] == ")":
            toks = toks + ["{", "}"]
        wrapped = ["class", "pad_pad_class", "{", "{"] + toks + ["}", "}"]

    full = " ".join(wrapped)
    start = full.find(fragment)
    if start < 0:
        raise ExtractError("fragment lost during wrapping")
    return full, start


# --------------------------------------------------------------------------
# Parsed-tree view
# --------------------------------------------------------------------------

@dataclasses.dataclass
class AstNode:
    """Node of the parsed wrapped fragment (preorder id == astdiff JSON id)."""

    id: int
    type_label: str
    label: Optional[str]
    pos: int
    children: List["AstNode"]
    parent: Optional["AstNode"] = None


def build_tree(parsed: dict) -> List[AstNode]:
    """JSON tree -> preorder node list with parent links. NullLiteral /
    ThisExpression get their implicit labels injected, as the reference
    bridge does (get_ast_root_action.py:56-61)."""
    nodes: List[AstNode] = []

    def walk(j: dict, parent: Optional[AstNode]) -> None:
        label = j.get("label")
        if j["typeLabel"] == "NullLiteral":
            label = "null"
        elif j["typeLabel"] == "ThisExpression":
            label = "this"
        node = AstNode(id=j["id"], type_label=j["typeLabel"], label=label,
                       pos=j["pos"], children=[], parent=parent)
        if node.id != len(nodes):
            raise ExtractError("non-preorder ids in parse output")
        nodes.append(node)
        if parent is not None:
            parent.children.append(node)
        for c in j["children"]:
            walk(c, node)

    walk(parsed["root"], None)
    return nodes


# --------------------------------------------------------------------------
# AST <-> code mapping (get_edge_ast_code, :132-185)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class SideGraph:
    """One fragment version's AST contribution, chunk-local indices."""

    edge_ast_code: List[Tuple[int, int]]  # (ast_idx, code_token_idx)
    edge_ast: List[Tuple[int, int]]       # (parent_ast_idx, child_ast_idx)
    ast_tokens: List[str]                 # internal-node type labels
    dmap_ast: Dict[int, int]              # node id -> ast_idx
    dmap_code: Dict[int, int]             # leaf node id -> code_token_idx


def empty_side() -> SideGraph:
    return SideGraph([], [], [], {}, {})


def ast_code_edges(nodes: List[AstNode], codes: Sequence[str],
                   start_pos: int, *,
                   commit_index: Optional[int] = None) -> SideGraph:
    """Map leaves to diff-token positions by ordered scan; collect internal
    nodes as AST-type tokens and parent-child edges.

    Wrapper pruning: nodes positioned before the fragment (the shell tokens)
    are skipped, as are the CompilationUnit/Block that share the fragment's
    start offset (:143-146). Each leaf label is matched to the next unseen
    occurrence via ``codes.index(name, last+1)`` with per-name progress
    bookkeeping (:148-169); a leaf is connected through its PARENT's ast
    node (:171-172).
    """
    side = SideGraph([], [], [], {}, {})
    start_index: Dict[str, int] = {}
    pos_index: Dict[str, int] = {}
    codes = list(codes)
    for node in nodes:
        if node.pos < start_pos:
            continue
        if node.pos == start_pos and node.type_label in ("CompilationUnit",
                                                         "Block"):
            continue
        if not node.children and node.type_label != "Block":
            name = node.label
            if name is None:
                continue
            last = start_index.get(name, -1)
            if name in start_index and pos_index[name] >= node.pos:
                continue  # out-of-order revisit of an already-consumed label
            if name not in codes:
                continue
            # replicated per-corpus hack (:159-160): commit 70's 'nextParent'
            # leaf maps to the 'nextParent:' label token
            if commit_index == 70 and name == "nextParent" and last == -1:
                try:
                    code_no = codes.index("nextParent:", last + 1)
                except ValueError:
                    continue
            else:
                try:
                    code_no = codes.index(name, last + 1)
                except ValueError:
                    continue
            side.dmap_code[node.id] = code_no
            start_index[name] = code_no
            pos_index[name] = node.pos
            parent_ast = side.dmap_ast.get(node.parent.id)
            if parent_ast is None:
                raise ExtractError(
                    f"leaf {name!r} under pruned parent {node.parent.type_label}")
            side.edge_ast_code.append((parent_ast, code_no))
        else:
            side.dmap_ast[node.id] = len(side.ast_tokens)
            side.ast_tokens.append(node.type_label)
            parent = node.parent
            if parent is None or parent.pos < start_pos:
                continue
            if parent.pos == start_pos and parent.type_label in (
                    "CompilationUnit", "Block"):
                continue
            side.edge_ast.append((side.dmap_ast[parent.id],
                                  side.dmap_ast[node.id]))
    # one code token per AST leaf (:181-184)
    used = list(side.dmap_code.values())
    if len(used) != len(set(used)):
        raise ExtractError("code token claimed by two AST leaves")
    return side


def parse_fragment(code_tokens: Sequence[str], *,
                   commit_index: Optional[int] = None
                   ) -> Tuple[Optional[str], SideGraph]:
    """Reconstruct + parse + map one fragment. Returns (wrapped_text, side);
    text is None when the fragment doesn't parse (side is then empty)."""
    recon = reconstruct_java(code_tokens)
    if recon is None:
        return None, empty_side()
    text, start = recon
    parsed = astdiff.parse_json(text)
    if parsed is None:
        return None, empty_side()
    nodes = build_tree(parsed)
    return text, ast_code_edges(nodes, code_tokens, start,
                                commit_index=commit_index)


# --------------------------------------------------------------------------
# Action parsing + reclassification (get_ast_root_action.py:103-232)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Actor:
    typ: str
    idx: int
    name: Optional[str]


def _parse_actor(s: str) -> Actor:
    m = _ACTOR_RE.match(s.strip())
    if not m:
        raise ExtractError(f"malformed action node {s!r}")
    name = m.group("name")
    typ = m.group("typ")
    if name is None and typ == "NullLiteral":
        name = "null"
    if name is None and typ == "ThisExpression":
        name = "this"
    return Actor(typ, int(m.group("idx")), name)


@dataclasses.dataclass
class Actions:
    """(kind, old_actor, new_actor) triples for matched nodes, plus pure
    deletes (old side) and adds (new side)."""

    classified: List[Tuple[str, Actor, Actor]]
    deletes: List[Actor]
    adds: List[Actor]


def classify_actions(lines: Sequence[str]) -> Actions:
    """Split raw action lines and reclassify Match into match/update/move by
    joining against the Update/Move lists on the old node (:185-222); update
    wins when a node both moved and was renamed (:221-222)."""
    matches: List[Tuple[Actor, Actor]] = []
    deletes: List[Actor] = []
    updates: List[Tuple[Actor, str]] = []
    moves: List[Actor] = []
    adds: List[Actor] = []
    for raw in lines:
        line = raw.strip()
        if line.startswith("Match "):
            old_s, new_s = line[len("Match "):].rsplit(" to ", 1)
            matches.append((_parse_actor(old_s), _parse_actor(new_s)))
        elif line.startswith("Delete "):
            deletes.append(_parse_actor(line[len("Delete "):]))
        elif line.startswith("Update "):
            old_s, new_name = line[len("Update "):].split(" to ", 1)
            updates.append((_parse_actor(old_s), new_name.strip()))
        elif line.startswith("Move "):
            old_s, rest = line[len("Move "):].split(" into ", 1)
            moves.append(_parse_actor(old_s))
        elif line.startswith("Insert "):
            new_s, rest = line[len("Insert "):].split(" into ", 1)
            adds.append(_parse_actor(new_s))
        elif line:
            raise ExtractError(f"unrecognized action line {line!r}")

    consumed_updates = [False] * len(updates)
    consumed_moves = [False] * len(moves)
    classified: List[Tuple[str, Actor, Actor]] = []
    for old, new in matches:
        updated = moved = False
        for j, (u_old, u_name) in enumerate(updates):
            if u_old == old:
                if u_name != new.name:
                    raise ExtractError(
                        f"update target {u_name!r} != matched name {new.name!r}")
                updated = True
                consumed_updates[j] = True
                break
        for j, m_old in enumerate(moves):
            if m_old == old:
                moved = True
                consumed_moves[j] = True
                break
        kind = "update" if updated else ("move" if moved else "match")
        classified.append((kind, old, new))
    if not all(consumed_updates) or not all(consumed_moves):
        raise ExtractError("Update/Move action without a Match line")
    return Actions(classified, deletes, adds)


# --------------------------------------------------------------------------
# Per-chunk edge extraction (get_edge_update / get_edge_normal)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ChunkGraph:
    """One chunk's contribution, chunk-local indices. For update chunks the
    new side's code indices are relative to the ADD fragment and its ast
    indices relative to the new side's own ast list; ``change`` labels are
    shared across both sides."""

    old: SideGraph = dataclasses.field(default_factory=empty_side)
    new: SideGraph = dataclasses.field(default_factory=empty_side)
    change: List[str] = dataclasses.field(default_factory=list)
    edge_change_code_old: List[Tuple[int, int]] = dataclasses.field(
        default_factory=list)
    edge_change_code_new: List[Tuple[int, int]] = dataclasses.field(
        default_factory=list)
    edge_change_ast_old: List[Tuple[int, int]] = dataclasses.field(
        default_factory=list)
    edge_change_ast_new: List[Tuple[int, int]] = dataclasses.field(
        default_factory=list)


def normal_chunk_edges(tokens: Sequence[str], *,
                       commit_index: Optional[int] = None) -> ChunkGraph:
    """Context / pure-add / pure-delete chunk: AST structure only (:300-316)."""
    g = ChunkGraph()
    _, g.old = parse_fragment(tokens, commit_index=commit_index)
    return g


def update_chunk_edges(old_tokens: Sequence[str], new_tokens: Sequence[str],
                       *, commit_index: Optional[int] = None) -> ChunkGraph:
    """Update chunk: both sides' AST edges plus one change node per diff
    action, wired to the code/AST nodes it touches (:187-298)."""
    g = ChunkGraph()
    text_old, g.old = parse_fragment(old_tokens, commit_index=commit_index)
    text_new, g.new = parse_fragment(new_tokens, commit_index=commit_index)
    if text_old is None or text_new is None:
        return g  # graceful degradation: code tokens only (:213-217)

    lines = astdiff.diff_lines(text_old, text_new)
    if lines is None:
        return g
    actions = classify_actions(lines)

    for kind, old, new in actions.classified:
        c = len(g.change)
        if old.idx in g.old.dmap_code:
            if new.idx not in g.new.dmap_code:
                continue
            g.edge_change_code_old.append((c, g.old.dmap_code[old.idx]))
            g.edge_change_code_new.append((c, g.new.dmap_code[new.idx]))
            g.change.append(kind)
        elif old.idx in g.old.dmap_ast:
            if new.idx not in g.new.dmap_ast:
                continue
            g.edge_change_ast_old.append((c, g.old.dmap_ast[old.idx]))
            g.edge_change_ast_new.append((c, g.new.dmap_ast[new.idx]))
            g.change.append(kind)
    for old in actions.deletes:
        c = len(g.change)
        if old.idx in g.old.dmap_code:
            g.edge_change_code_old.append((c, g.old.dmap_code[old.idx]))
            g.change.append("delete")
        elif old.idx in g.old.dmap_ast:
            g.edge_change_ast_old.append((c, g.old.dmap_ast[old.idx]))
            g.change.append("delete")
    for new in actions.adds:
        c = len(g.change)
        if new.idx in g.new.dmap_code:
            g.edge_change_code_new.append((c, g.new.dmap_code[new.idx]))
            g.change.append("add")
        elif new.idx in g.new.dmap_ast:
            g.edge_change_ast_new.append((c, g.new.dmap_ast[new.idx]))
            g.change.append("add")
    return g


# --------------------------------------------------------------------------
# Per-commit assembly (worker main loop, :344-426)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class CommitGraph:
    """The six per-commit graph streams of the DataSet schema."""

    ast: List[str]
    change: List[str]
    edge_ast: List[Tuple[int, int]]
    edge_ast_code: List[Tuple[int, int]]
    edge_change_ast: List[Tuple[int, int]]
    edge_change_code: List[Tuple[int, int]]


def extract_commit(chunks: Sequence[Chunk], types: Sequence[int],
                   diff_tokens: Sequence[str], *,
                   commit_index: Optional[int] = None,
                   memo=None) -> CommitGraph:
    """Rebase chunk-local indices into commit-global coordinates (:369-393)
    and verify the reassembled token stream equals the diff (:420).

    ``memo``: optional hunk-level extraction memo
    (``ingest.cache.HunkMemo``) — per-chunk parse/diff results are a pure
    function of the typed chunk content, so the online ingest path reuses
    them across near-identical requests while this merge/rebase re-runs
    per commit; the cached ChunkGraph is only ever READ here."""
    out = CommitGraph([], [], [], [], [], [])
    all_token: List[str] = []
    for chunk, typ in zip(chunks, types):
        code_base = len(all_token)
        ast_base = len(out.ast)
        change_base = len(out.change)
        if typ == 100:
            old_tokens, new_tokens = chunk
            g = (memo.chunk_graph(chunk, typ, commit_index)
                 if memo is not None else
                 update_chunk_edges(old_tokens, new_tokens,
                                    commit_index=commit_index))
            n_ast_old = len(g.old.ast_tokens)
            n_code_old = len(old_tokens)
            for a, j in g.old.edge_ast_code:
                out.edge_ast_code.append((ast_base + a, code_base + j))
            for a1, a2 in g.old.edge_ast:
                out.edge_ast.append((ast_base + a1, ast_base + a2))
            for c, j in g.edge_change_code_old:
                out.edge_change_code.append((change_base + c, code_base + j))
            for c, a in g.edge_change_ast_old:
                out.edge_change_ast.append((change_base + c, ast_base + a))
            for a, j in g.new.edge_ast_code:
                out.edge_ast_code.append(
                    (ast_base + n_ast_old + a, code_base + n_code_old + j))
            for a1, a2 in g.new.edge_ast:
                out.edge_ast.append((ast_base + n_ast_old + a1,
                                     ast_base + n_ast_old + a2))
            for c, j in g.edge_change_code_new:
                out.edge_change_code.append(
                    (change_base + c, code_base + n_code_old + j))
            for c, a in g.edge_change_ast_new:
                out.edge_change_ast.append(
                    (change_base + c, ast_base + n_ast_old + a))
            out.ast.extend(g.old.ast_tokens)
            out.ast.extend(g.new.ast_tokens)
            out.change.extend(g.change)
            all_token.extend(old_tokens)
            all_token.extend(new_tokens)
        else:
            if typ not in (0, -1, 1):
                raise ExtractError(f"unknown chunk type {typ}")
            tokens = list(chunk)
            if not tokens:
                raise ExtractError("empty non-update chunk")
            g = (memo.chunk_graph(chunk, typ, commit_index)
                 if memo is not None else
                 normal_chunk_edges(tokens, commit_index=commit_index))
            for a, j in g.old.edge_ast_code:
                out.edge_ast_code.append((ast_base + a, code_base + j))
            for a1, a2 in g.old.edge_ast:
                out.edge_ast.append((ast_base + a1, ast_base + a2))
            out.ast.extend(g.old.ast_tokens)
            all_token.extend(tokens)
    if list(all_token) != list(diff_tokens):
        raise ExtractError(
            "reassembled chunk tokens disagree with the difftoken stream")
    return out
