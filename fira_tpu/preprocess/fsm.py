"""Hunk-splitting FSM: diff token/mark streams -> typed chunks.

Pure rebuild of the reference's preprocessing state machine
(/root/reference/Preprocess/run_total_process_data.py:8-158). Walks the
aligned (difftoken, diffmark) streams and segments each commit's diff into
typed chunks:

    type  0   context run (including every <nb>...<nl> header block)
    type -1   pure deletion run
    type  1   pure addition run
    type 100  update: a delete-run immediately followed by an add-run,
              emitted as the pair (delete_tokens, add_tokens)

Semantics preserved exactly: a delete-run flushed by context becomes type -1
(NOT an update even if adds come later); an add-run is promoted to an update
only when the pending delete-run is non-empty; <nb> blocks must be all
context (mark 2) through their closing <nl>; end-of-stream flushes like <nb>.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple, Union

Chunk = Union[List[str], Tuple[List[str], List[str]]]

NB = "<nb>"
NL = "<nl>"


class FSMError(ValueError):
    """Malformed (tokens, marks) input (the reference uses bare asserts)."""


def split_hunks(tokens: Sequence[str], marks: Sequence[int]
                ) -> Tuple[List[Chunk], List[int]]:
    """Segment one commit's diff. Returns (chunks, types) where types[i] in
    {0, -1, 1, 100} and a type-100 chunk is (delete_tokens, add_tokens)."""
    if len(tokens) != len(marks):
        raise FSMError(f"token/mark length mismatch: {len(tokens)} vs {len(marks)}")

    chunks: List[Chunk] = []
    types: List[int] = []
    delete_run: List[str] = []
    add_run: List[str] = []
    normal_run: List[str] = []
    state: Union[str, int] = "<start>"

    def flush_pending() -> None:
        nonlocal state
        if state == 0:
            if not normal_run:
                raise FSMError("empty context run at flush")
            chunks.append(list(normal_run))
            types.append(0)
        elif state == -1:
            if not delete_run:
                raise FSMError("empty delete run at flush")
            chunks.append(list(delete_run))
            types.append(-1)
        elif state == 1:
            if not add_run:
                raise FSMError("empty add run at flush")
            if not delete_run:
                chunks.append(list(add_run))
                types.append(1)
            else:
                chunks.append((list(delete_run), list(add_run)))
                types.append(100)

    j = 0
    n = len(tokens)
    while j < n:
        token, mark = tokens[j], marks[j]
        if mark not in (1, 2, 3) and token != NB:
            raise FSMError(f"mark {mark!r} at {j} outside {{1,2,3}}")

        if token == NB:
            flush_pending()
            if mark != 2:
                raise FSMError(f"<nb> at {j} has mark {mark}, expected 2")
            try:
                end_nl = tokens.index(NL, j)
            except ValueError:
                raise FSMError(f"<nb> at {j} without closing <nl>") from None
            for jj in range(j, end_nl + 1):
                if marks[jj] != 2:
                    raise FSMError(
                        f"non-context mark {marks[jj]} inside <nb> block at {jj}")
            chunks.append(list(tokens[j : end_nl + 1]))
            types.append(0)
            state = "<start>"
            delete_run, add_run, normal_run = [], [], []
            j = end_nl + 1
            continue

        if state == "<start>":
            if mark == 1:
                delete_run.append(token)
                state = -1
            elif mark == 3:
                add_run.append(token)
                state = 1
            elif mark == 2:
                normal_run.append(token)
                state = 0
        elif state == 0:
            if mark == 2:
                normal_run.append(token)
            else:
                chunks.append(list(normal_run))
                types.append(0)
                normal_run = []
                if mark == 1:
                    delete_run.append(token)
                    state = -1
                else:
                    add_run.append(token)
                    state = 1
        elif state == -1:
            if mark == 1:
                delete_run.append(token)
            elif mark == 3:
                add_run.append(token)
                state = 1
            else:  # context flushes the delete-run as a pure deletion
                chunks.append(list(delete_run))
                types.append(-1)
                delete_run = []
                normal_run.append(token)
                state = 0
        elif state == 1:
            if mark == 3:
                add_run.append(token)
            else:
                if not delete_run:
                    chunks.append(list(add_run))
                    types.append(1)
                else:
                    chunks.append((list(delete_run), list(add_run)))
                    types.append(100)
                delete_run, add_run = [], []
                if mark == 1:
                    delete_run.append(token)
                    state = -1
                else:
                    normal_run.append(token)
                    state = 0
        j += 1

    flush_pending()
    return chunks, types


def flatten_chunks(chunks: Sequence[Chunk], types: Sequence[int]) -> List[str]:
    """Re-concatenate chunk tokens in order (delete before add for updates) —
    must reproduce the original difftoken stream, the reference's global
    invariant (process_data_ast_parallel.py:420)."""
    out: List[str] = []
    for chunk, t in zip(chunks, types):
        if t == 100:
            out.extend(chunk[0])
            out.extend(chunk[1])
        else:
            out.extend(chunk)  # type: ignore[arg-type]
    return out
