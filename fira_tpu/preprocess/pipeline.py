"""Preprocessing pipeline: raw diff streams -> complete DataSet corpus.

Rebuilds the reference's orchestration layer
(/root/reference/Preprocess/run_total_process_data.py:160-184 worker fan-out,
gather_data.py shard concatenation) with a cleaner contract:

- Input: a corpus dir holding at least ``difftoken.json`` + ``diffmark.json``
  (plus ``msg.json`` / ``variable.json`` from the crawl stage; ``diffatt.json``
  is derived here when absent).
- Shard workers (multiprocessing) run the FSM + AST extraction per commit and
  write per-shard stream files under ``<out>/shards/shard_<s>_<e>/``;
  idempotent re-runs skip completed shards (the reference skips on an existing
  pickle, run_total_process_data.py:161).
- Per-commit failures degrade that commit to an empty graph and are recorded
  in the shard's ``errors.json`` (the reference aborts the whole 100-commit
  shard to an ERROR file instead, process_data_ast_parallel.py:439-443).
- ``gather`` concatenates shards in order, asserts the commit count, and
  writes the six graph streams next to the inputs; vocabularies are built
  last if absent (Dataset.py:46-62 rebuilds ast_change_vocab the same way).

The native astdiff library is loaded once per worker process — no JVM
subprocesses (the reference forks two per update hunk).
"""

from __future__ import annotations

import dataclasses
import json
import multiprocessing
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from fira_tpu.data.schema import CORPUS_FILES
from fira_tpu.data.vocab import CASE_PRESERVED_TOKENS, Vocab
from fira_tpu.preprocess import extract
from fira_tpu.preprocess.fsm import split_hunks

GRAPH_STREAMS = ("ast", "change", "edge_ast", "edge_ast_code",
                 "edge_change_ast", "edge_change_code")

_IDENT_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_$]*$")
_CAMEL_RE = re.compile(
    r"[A-Z]+(?=[A-Z][a-z0-9])|[A-Z]?[a-z0-9]+|[A-Z]+|\$")


def split_sub_tokens(token: str) -> List[str]:
    """camelCase/snake_case sub-token split, lower-cased.

    Produces the ``diffatt.json`` stream (SURVEY.md Appendix A): a token
    yields sub-tokens only when it actually splits into >= 2 parts;
    placeholders (STRING0, NUMBER3, ...), punctuation, keywords-as-single-
    words and sentinels yield []. Sub-tokens are asserted lower-case
    downstream (Dataset.py:150-151), so parts are lowered here.
    """
    if token in CASE_PRESERVED_TOKENS or not _IDENT_RE.match(token):
        return []
    parts: List[str] = []
    for piece in token.split("_"):
        if not piece:
            continue
        parts.extend(m.group(0) for m in _CAMEL_RE.finditer(piece))
    parts = [p.lower() for p in parts if p and p != "$"]
    return parts if len(parts) >= 2 else []


def derive_diffatt(difftokens: Sequence[Sequence[str]]
                   ) -> List[List[List[str]]]:
    return [[split_sub_tokens(t) for t in commit] for commit in difftokens]


# --------------------------------------------------------------------------
# Shard worker
# --------------------------------------------------------------------------

def _empty_commit_graph() -> Dict[str, list]:
    return {s: [] for s in GRAPH_STREAMS}


def process_commits(difftokens: Sequence[Sequence[str]],
                    diffmarks: Sequence[Sequence[int]],
                    begin: int, end: int, *, index_offset: int = 0
                    ) -> Tuple[Dict[str, list], List[dict]]:
    """Extract graphs for commits [begin, end). ``index_offset`` maps local
    positions back to corpus-global commit indices (error records and the
    reference's per-commit hack both key on the global index). Returns
    ({stream: [per-commit lists]}, [error records])."""
    streams: Dict[str, list] = {s: [] for s in GRAPH_STREAMS}
    errors: List[dict] = []
    for m in range(begin, end):
        try:
            chunks, types = split_hunks(difftokens[m], diffmarks[m])
            g = extract.extract_commit(chunks, types, difftokens[m],
                                       commit_index=index_offset + m)
            commit = {
                "ast": g.ast,
                "change": g.change,
                "edge_ast": [list(e) for e in g.edge_ast],
                "edge_ast_code": [list(e) for e in g.edge_ast_code],
                "edge_change_ast": [list(e) for e in g.edge_change_ast],
                "edge_change_code": [list(e) for e in g.edge_change_code],
            }
        except Exception as exc:  # degrade the commit, keep the corpus aligned
            errors.append({"commit": index_offset + m,
                           "error": f"{type(exc).__name__}: {exc}"})
            commit = _empty_commit_graph()
        for s in GRAPH_STREAMS:
            streams[s].append(commit[s])
    return streams, errors


def _shard_dir(out_dir: str, begin: int, end: int) -> str:
    return os.path.join(out_dir, "shards", f"shard_{begin}_{end}")


def _shard_done(out_dir: str, begin: int, end: int) -> bool:
    # errors.json is part of done-ness: it is always written (possibly []),
    # so a shard that crashed between its stream writes and its error record
    # reprocesses instead of passing for a clean shard on re-run. Shard dirs
    # written before this marker existed also reprocess once — deliberate: a
    # legacy shard without errors.json is indistinguishable from a crashed
    # one, and correctness of the error ledger beats one re-run.
    d = _shard_dir(out_dir, begin, end)
    return all(os.path.exists(os.path.join(d, f"{s}.json"))
               for s in GRAPH_STREAMS) \
        and os.path.exists(os.path.join(d, "errors.json"))


def _run_shard(job: Tuple[str, int, int, list, list]) -> Tuple[int, int, int]:
    """(out_dir, begin, end, difftoken_slice, diffmark_slice) ->
    (begin, end, n_errors). The parent ships each worker only its own slice
    of the streams, so corpus-sized JSON is parsed exactly once."""
    out_dir, begin, end, difftokens, diffmarks = job
    if _shard_done(out_dir, begin, end):
        # idempotent re-run: report the errors recorded when the shard ran,
        # so re-runs don't claim a clean corpus that isn't
        err_path = os.path.join(_shard_dir(out_dir, begin, end), "errors.json")
        with open(err_path) as f:
            return begin, end, len(json.load(f))
    streams, errors = process_commits(difftokens, diffmarks, 0,
                                      end - begin, index_offset=begin)
    d = _shard_dir(out_dir, begin, end)
    os.makedirs(d, exist_ok=True)
    for s in GRAPH_STREAMS:
        tmp = os.path.join(d, f"{s}.json.tmp")
        with open(tmp, "w") as f:
            json.dump(streams[s], f)
        os.replace(tmp, os.path.join(d, f"{s}.json"))
    # last write completes the shard (atomic like the streams above)
    tmp = os.path.join(d, "errors.json.tmp")
    with open(tmp, "w") as f:
        json.dump(errors, f, indent=1)
    os.replace(tmp, os.path.join(d, "errors.json"))
    return begin, end, len(errors)


# --------------------------------------------------------------------------
# Orchestrator + gather
# --------------------------------------------------------------------------

@dataclasses.dataclass
class PipelineReport:
    n_commits: int
    n_shards: int
    n_errors: int
    skipped_shards: int


def run_pipeline(data_dir: str, *, out_dir: Optional[str] = None,
                 shard_size: int = 100, num_procs: Optional[int] = None,
                 build_vocabs: bool = True) -> PipelineReport:
    """Full pipeline: shard fan-out, gather, diffatt derivation, vocabs."""
    out_dir = out_dir or data_dir
    with open(os.path.join(data_dir, "difftoken.json")) as f:
        difftokens = json.load(f)
    n = len(difftokens)
    with open(os.path.join(data_dir, "diffmark.json")) as f:
        diffmarks = json.load(f)
    jobs = []
    for s in range(0, n, shard_size):
        e = min(s + shard_size, n)
        jobs.append((out_dir, s, e, difftokens[s:e], diffmarks[s:e]))
    skipped = sum(1 for j in jobs if _shard_done(out_dir, j[1], j[2]))

    num_procs = num_procs or min(len(jobs), os.cpu_count() or 1)
    if num_procs <= 1 or len(jobs) <= 1:
        results = [_run_shard(j) for j in jobs]
    else:
        # spawn, not fork: the caller may be multi-threaded (JAX runtime,
        # pytest), and the workers import no heavyweight modules anyway.
        ctx = multiprocessing.get_context("spawn")
        with ctx.Pool(num_procs) as pool:
            results = pool.map(_run_shard, jobs)
    n_errors = sum(r[2] for r in results)

    gather(out_dir, n, shard_size=shard_size)

    if not os.path.exists(os.path.join(out_dir, "diffatt.json")):
        with open(os.path.join(out_dir, "diffatt.json"), "w") as f:
            json.dump(derive_diffatt(difftokens), f)

    if build_vocabs:
        _build_vocabs(data_dir, out_dir, difftokens)
    return PipelineReport(n_commits=n, n_shards=len(jobs),
                          n_errors=n_errors, skipped_shards=skipped)


def gather(out_dir: str, n_commits: int, shard_size: int = 100) -> None:
    """Concatenate shard outputs in index order into the six corpus streams
    (gather_data.py:14-43, including its final count assert)."""
    totals: Dict[str, list] = {s: [] for s in GRAPH_STREAMS}
    for begin in range(0, n_commits, shard_size):
        end = min(begin + shard_size, n_commits)
        d = _shard_dir(out_dir, begin, end)
        for s in GRAPH_STREAMS:
            with open(os.path.join(d, f"{s}.json")) as f:
                totals[s].extend(json.load(f))
    for s in GRAPH_STREAMS:
        if len(totals[s]) != n_commits:
            raise RuntimeError(
                f"gather: stream {s} has {len(totals[s])} commits, "
                f"expected {n_commits}")
        tmp = os.path.join(out_dir, f"{s}.json.tmp")
        with open(tmp, "w") as f:
            json.dump(totals[s], f)
        os.replace(tmp, os.path.join(out_dir, f"{s}.json"))


def _build_vocabs(data_dir: str, out_dir: str,
                  difftokens: Sequence[Sequence[str]]) -> None:
    word_path = os.path.join(out_dir, "word_vocab.json")
    if not os.path.exists(word_path):
        streams = list(difftokens)
        msg_path = os.path.join(data_dir, "msg.json")
        if os.path.exists(msg_path):
            with open(msg_path) as f:
                streams += json.load(f)
        Vocab.build_word_vocab(streams).to_json(word_path)
    ast_path = os.path.join(out_dir, "ast_change_vocab.json")
    if not os.path.exists(ast_path):
        with open(os.path.join(out_dir, "ast.json")) as f:
            asts = json.load(f)
        Vocab.build_ast_change_vocab(asts).to_json(ast_path)


def main(args) -> int:
    """CLI entry (``python -m fira_tpu.cli preprocess``)."""
    report = run_pipeline(
        args.data_dir,
        shard_size=getattr(args, "shard_size", 100) or 100,
        num_procs=getattr(args, "num_procs", None),
    )
    missing = [f for f in CORPUS_FILES
               if not os.path.exists(os.path.join(args.data_dir, f))]
    print(f"preprocess: {report.n_commits} commits, {report.n_shards} shards "
          f"({report.skipped_shards} already done), "
          f"{report.n_errors} degraded commits")
    if missing:
        print(f"note: corpus still missing {missing} (crawl-stage inputs)")
    return 0
