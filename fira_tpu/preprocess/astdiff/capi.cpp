// C API (for ctypes, the in-process fast path) + CLI main (the GumTree
// contract surface: `astdiff parse f.java`, `astdiff diff a.java b.java` —
// drop-in for the reference's `gumtree parse|diff` subprocess calls,
// get_ast_root_action.py:70,124).
#include "astdiff.hpp"

#include <cstring>
#include <fstream>
#include <iostream>
#include <sstream>

namespace {

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (!out) return nullptr;
  std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

}  // namespace

extern "C" {

// Parse Java source -> malloc'd JSON string, or NULL on any parse failure.
char* astdiff_parse(const char* src) {
  try {
    auto tree = astdiff::parse(src);
    return dup_string(astdiff::to_json(*tree));
  } catch (const std::exception&) {
    return nullptr;
  }
}

// Diff two Java sources -> malloc'd action-line text, or NULL on failure.
char* astdiff_diff(const char* src_old, const char* src_new) {
  try {
    auto told = astdiff::parse(src_old);
    auto tnew = astdiff::parse(src_new);
    return dup_string(astdiff::diff_actions(*told, *tnew));
  } catch (const std::exception&) {
    return nullptr;
  }
}

// Tokenize Java source -> malloc'd newline-joined token texts, or NULL.
// (Replaces the reference's javalang.tokenizer calls.)
char* astdiff_tokenize(const char* src) {
  try {
    auto toks = astdiff::lex(src);
    std::ostringstream os;
    for (const auto& t : toks) {
      if (t.kind == astdiff::Tok::End) break;
      os << t.text << "\n";
    }
    return dup_string(os.str());
  } catch (const std::exception&) {
    return nullptr;
  }
}

void astdiff_free(char* p) { std::free(p); }

}  // extern "C"

#ifdef ASTDIFF_MAIN
namespace {
std::string read_file(const char* path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error(std::string("cannot open ") + path);
  std::ostringstream os;
  os << f.rdbuf();
  return os.str();
}
}  // namespace

int main(int argc, char** argv) {
  try {
    if (argc >= 3 && std::string(argv[1]) == "parse") {
      auto tree = astdiff::parse(read_file(argv[2]));
      std::cout << astdiff::to_json(*tree) << "\n";
      return 0;
    }
    if (argc >= 4 && std::string(argv[1]) == "diff") {
      auto told = astdiff::parse(read_file(argv[2]));
      auto tnew = astdiff::parse(read_file(argv[3]));
      std::cout << astdiff::diff_actions(*told, *tnew);
      return 0;
    }
    std::cerr << "usage: astdiff parse <f.java> | astdiff diff <a.java> <b.java>\n";
    return 2;
  } catch (const std::exception& e) {
    std::cerr << "astdiff: " << e.what() << "\n";
    return 1;
  }
}
#endif
