// Java tokenizer (maximal-munch). Replaces the reference's use of
// javalang.tokenizer (process_data_ast_parallel.py:48,122): same observable
// role — split fragment text into Java tokens; a LexError makes the caller
// drop the chunk's AST, mirroring the reference's try/except around
// javalang.tokenizer.tokenize.
#include "astdiff.hpp"

#include <array>
#include <cctype>
#include <unordered_set>

namespace astdiff {

namespace {

const std::unordered_set<std::string>& keywords() {
  static const std::unordered_set<std::string> kw = {
      "abstract", "assert",    "boolean",  "break",      "byte",     "case",
      "catch",    "char",      "class",    "const",      "continue", "default",
      "do",       "double",    "else",     "enum",       "extends",  "final",
      "finally",  "float",     "for",      "goto",       "if",       "implements",
      "import",   "instanceof","int",      "interface",  "long",     "native",
      "new",      "package",   "private",  "protected",  "public",   "return",
      "short",    "static",    "strictfp", "super",      "switch",   "synchronized",
      "this",     "throw",     "throws",   "transient",  "try",      "void",
      "volatile", "while",     "true",     "false",      "null"};
  return kw;
}

// Multi-char operators, longest first within each leading char.
const std::array<const char*, 25> MULTI_OPS = {
    ">>>=", ">>>", ">>=", ">>", ">=", "<<=", "<<", "<=", "...", "->",
    "::",   "==",  "!=",  "&&", "&=", "||",  "|=", "++", "+=",  "--",
    "-=",   "*=",  "/=",  "%=", "^="};

bool ident_start(char c) {
  return std::isalpha(static_cast<unsigned char>(c)) || c == '_' || c == '$' ||
         static_cast<unsigned char>(c) >= 0x80;  // UTF-8 continuation-friendly
}
bool ident_part(char c) {
  return ident_start(c) || std::isdigit(static_cast<unsigned char>(c));
}

}  // namespace

std::vector<Token> lex(const std::string& src) {
  std::vector<Token> out;
  const size_t n = src.size();
  size_t i = 0;
  while (i < n) {
    char c = src[i];
    if (std::isspace(static_cast<unsigned char>(c))) {
      ++i;
      continue;
    }
    // comments
    if (c == '/' && i + 1 < n && (src[i + 1] == '/' || src[i + 1] == '*')) {
      if (src[i + 1] == '/') {
        while (i < n && src[i] != '\n') ++i;
      } else {
        size_t j = i + 2;
        while (j + 1 < n && !(src[j] == '*' && src[j + 1] == '/')) ++j;
        if (j + 1 >= n) throw LexError("unterminated block comment");
        i = j + 2;
      }
      continue;
    }
    const int pos = static_cast<int>(i);
    // identifier / keyword
    if (ident_start(c)) {
      size_t j = i + 1;
      while (j < n && ident_part(src[j])) ++j;
      std::string text = src.substr(i, j - i);
      out.push_back({keywords().count(text) ? Tok::Keyword : Tok::Ident,
                     std::move(text), pos});
      i = j;
      continue;
    }
    // number literal (int/float, hex/bin/oct, underscores, suffixes)
    if (std::isdigit(static_cast<unsigned char>(c)) ||
        (c == '.' && i + 1 < n &&
         std::isdigit(static_cast<unsigned char>(src[i + 1])))) {
      size_t j = i;
      bool hex = false;
      if (c == '0' && j + 1 < n && (src[j + 1] == 'x' || src[j + 1] == 'X')) {
        hex = true;
        j += 2;
        while (j < n && (std::isxdigit(static_cast<unsigned char>(src[j])) ||
                         src[j] == '_'))
          ++j;
      } else if (c == '0' && j + 1 < n &&
                 (src[j + 1] == 'b' || src[j + 1] == 'B')) {
        j += 2;
        while (j < n && (src[j] == '0' || src[j] == '1' || src[j] == '_')) ++j;
      } else {
        while (j < n && (std::isdigit(static_cast<unsigned char>(src[j])) ||
                         src[j] == '_'))
          ++j;
        if (j < n && src[j] == '.') {
          ++j;
          while (j < n && (std::isdigit(static_cast<unsigned char>(src[j])) ||
                           src[j] == '_'))
            ++j;
        }
        if (j < n && (src[j] == 'e' || src[j] == 'E')) {
          size_t k = j + 1;
          if (k < n && (src[k] == '+' || src[k] == '-')) ++k;
          if (k < n && std::isdigit(static_cast<unsigned char>(src[k]))) {
            j = k;
            while (j < n && std::isdigit(static_cast<unsigned char>(src[j])))
              ++j;
          }
        }
      }
      if (j < n && (src[j] == 'l' || src[j] == 'L' ||
                    (!hex && (src[j] == 'f' || src[j] == 'F' || src[j] == 'd' ||
                              src[j] == 'D'))))
        ++j;
      out.push_back({Tok::Number, src.substr(i, j - i), pos});
      i = j;
      continue;
    }
    // Java 13+ text block: """ ... """ (may span lines; \ escapes)
    if (c == '"' && i + 2 < n && src[i + 1] == '"' && src[i + 2] == '"') {
      size_t j = i + 3;
      while (j + 2 < n &&
             !(src[j] == '"' && src[j + 1] == '"' && src[j + 2] == '"')) {
        if (src[j] == '\\') ++j;
        ++j;
      }
      if (j + 2 >= n) throw LexError("unterminated text block");
      out.push_back({Tok::String, src.substr(i, j + 3 - i), pos});
      i = j + 3;
      continue;
    }
    // string / char literal
    if (c == '"' || c == '\'') {
      size_t j = i + 1;
      while (j < n && src[j] != c) {
        if (src[j] == '\\') ++j;
        if (src[j] == '\n') throw LexError("newline in literal");
        ++j;
      }
      if (j >= n) throw LexError("unterminated literal");
      out.push_back({c == '"' ? Tok::String : Tok::Char,
                     src.substr(i, j - i + 1), pos});
      i = j + 1;
      continue;
    }
    // multi-char operator (maximal munch)
    bool matched = false;
    for (const char* op : MULTI_OPS) {
      size_t len = std::char_traits<char>::length(op);
      if (src.compare(i, len, op) == 0) {
        out.push_back({Tok::Op, op, pos});
        i += len;
        matched = true;
        break;
      }
    }
    if (matched) continue;
    // single-char operator/separator
    static const std::string singles = "+-*/%=<>!~&|^?:;,.(){}[]@";
    if (singles.find(c) != std::string::npos) {
      out.push_back({Tok::Op, std::string(1, c), pos});
      ++i;
      continue;
    }
    throw LexError("unexpected character at " + std::to_string(i));
  }
  out.push_back({Tok::End, "", static_cast<int>(n)});
  return out;
}

}  // namespace astdiff
